"""Per-request tracing (ISSUE 2 tentpole, part 2).

Lightweight span API answering "where did this request's 934ms go":

    from paddle_tpu.observability import tracing
    with tracing.span("prefill", request_id=rid):
        ...

Every span/event is one dict with MONOTONIC timestamps
(time.perf_counter — durations and orderings are exact; `wall` carries
one time.time() anchor per process so JSONL files from different runs
can still be aligned roughly). Events buffer in memory and, when a sink
is configured, append to a JSONL file line-by-line — the trace survives
a crash up to the last completed span.

The serving engine emits a small vocabulary per request
(inference/serving.py):

    request_submitted    point event, request_id
    request_admitted     point event, request_id (slot picked)
    prefill_chunk        span, request_ids=[...] (ONE packed ragged
                         prefill dispatch serving several requests'
                         prompt chunks)
    prefill              per-request event with explicit ts/dur: first
                         chunk dispatch start -> final chunk done (its
                         end IS the request's first-token time); carries
                         `chunks`, the dispatches the prompt spanned
    decode_dispatch      span, request_ids=[...] (one batched step for
                         every active slot; k tokens when multi-step)
    request_done         point event, request_id, new_tokens, ttft_s,
                         cost (the request's closed attribution
                         account, ISSUE 17 — None when attribution
                         is off)
    detokenize           span, request_id (assemble + resolve future)

`assemble_request_traces` folds that stream back into one record per
request with contiguous phases (queue_wait / admission / prefill /
decode / detokenize) that tile the request's wall-clock exactly, plus
TTFT and per-token decode latency — the standard latency lens of paged
serving engines (Ragged Paged Attention, arXiv:2604.15464).

`attach_device_ops` bridges utils/profiler.top_ops so a traced serving
window can carry a device-op breakdown in the same report.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time

ENV_ENABLE = "PADDLE_TPU_TELEMETRY"
ENV_TRACE_PATH = "PADDLE_TPU_TRACE_PATH"
ENV_TRACE_MAX_BYTES = "PADDLE_TPU_TRACE_MAX_BYTES"

# Bounded sink (ISSUE 10 satellite): a long-lived serving run must not
# grow the trace file without bound. When the sink crosses the cap it
# rotates ONCE (path -> path + ".1", replacing any previous rotation)
# and restarts the live file, so disk usage is bounded at ~2x the cap
# while the most recent cap's worth of events is always on disk.
DEFAULT_TRACE_MAX_BYTES = 64 << 20  # 64 MiB


class Tracer:
    """Event collector: in-memory buffer + optional JSONL sink. All
    methods are thread-safe; span nesting is tracked per thread."""

    def __init__(self, enabled=None, path=None):
        if enabled is None:
            enabled = os.environ.get(ENV_ENABLE, "0") not in ("", "0",
                                                              "false")
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self._file = None
        self._path = None
        self._bytes = 0
        self._rotations = 0
        self.max_bytes = int(os.environ.get(ENV_TRACE_MAX_BYTES,
                                            DEFAULT_TRACE_MAX_BYTES))
        self._next_id = 0
        self._local = threading.local()
        # one wall-clock anchor: wall ~= _wall0 + (ts - _ts0)
        self._ts0 = time.perf_counter()
        self._wall0 = time.time()
        if path or os.environ.get(ENV_TRACE_PATH):
            self.configure(path=path or os.environ[ENV_TRACE_PATH])

    # -- config ----------------------------------------------------------
    def configure(self, path=None, enabled=None, truncate=False,
                  max_bytes=None):
        """Set the JSONL sink (None detaches) and/or toggle tracing.
        max_bytes caps the sink file (default 64 MiB, env
        PADDLE_TPU_TRACE_MAX_BYTES): crossing it rotates the file once
        to `path + ".1"` and restarts the live file."""
        with self._lock:
            if max_bytes is not None:
                self.max_bytes = int(max_bytes)
            if self._file is not None and path != self._path:
                self._file.close()
                self._file = None
                self._path = None
            if path and self._file is None:
                d = os.path.dirname(os.path.abspath(path))
                os.makedirs(d, exist_ok=True)
                self._file = open(path, "w" if truncate else "a",
                                  buffering=1)
                self._path = path
                self._bytes = self._file.tell()
                self._write_line(json.dumps(
                    {"name": "trace_start", "ts": self._ts0,
                     "wall": self._wall0}))
        if enabled is not None:
            self.enabled = bool(enabled)
        return self

    def _write_line(self, line):
        """Caller holds the lock. Rotates BEFORE the write when the
        sink would cross max_bytes, so the live file never exceeds the
        cap and the previous cap's worth of events survives at
        path + '.1'."""
        n = len(line) + 1
        if self._bytes and self._bytes + n > self.max_bytes:
            self._rotate_locked()
        self._file.write(line + "\n")
        self._bytes += n

    def _rotate_locked(self):
        self._file.close()
        try:
            os.replace(self._path, self._path + ".1")
        except OSError:  # cross-device/unwritable: truncate in place
            pass
        self._file = open(self._path, "w", buffering=1)
        self._bytes = 0
        self._rotations += 1
        header = json.dumps({"name": "trace_start", "ts": self._ts0,
                             "wall": self._wall0,
                             "rotation": self._rotations})
        self._file.write(header + "\n")
        self._bytes += len(header) + 1

    @property
    def path(self):
        return self._path

    def enable(self):
        self.enabled = True

    def disable(self):
        self.enabled = False

    # -- emission --------------------------------------------------------
    def _emit(self, ev):
        with self._lock:
            ev["id"] = self._next_id
            self._next_id += 1
            self._events.append(ev)
            if self._file is not None:
                self._write_line(json.dumps(ev))

    def event(self, name, **attrs):
        """Point event (duration 0)."""
        if not self.enabled:
            return
        ev = {"name": name, "ts": time.perf_counter(),
              "tid": threading.get_ident()}
        ev.update(attrs)
        self._emit(ev)

    @contextlib.contextmanager
    def span(self, name, **attrs):
        """Timed span; emitted on exit with its duration. Nested spans
        record their parent span's id (per-thread stack)."""
        if not self.enabled:
            yield None
            return
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        ev = {"name": name, "ts": time.perf_counter(),
              "tid": threading.get_ident()}
        ev.update(attrs)
        if stack:
            ev["parent"] = stack[-1]["name"]
        ev["depth"] = len(stack)
        stack.append(ev)
        try:
            yield ev
        finally:
            stack.pop()
            ev["dur"] = time.perf_counter() - ev["ts"]
            self._emit(ev)

    def wrap(self, name, fn, **attrs):
        """Decorator form: time every call of `fn` as a span — used for
        jitted dispatch boundaries (nn/decode.py)."""
        def wrapped(*a, **kw):
            if not self.enabled:
                return fn(*a, **kw)
            with self.span(name, **attrs):
                return fn(*a, **kw)
        wrapped.__name__ = getattr(fn, "__name__", name)
        wrapped.__wrapped__ = fn
        return wrapped

    # -- access ----------------------------------------------------------
    def events(self):
        with self._lock:
            return list(self._events)

    def reset(self):
        """Drop buffered events (the JSONL sink, if any, keeps its
        already-written lines)."""
        with self._lock:
            self._events.clear()

    def flush(self):
        with self._lock:
            if self._file is not None:
                self._file.flush()

    def close(self):
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
                self._path = None


# ---- process-wide default tracer ---------------------------------------
TRACER = Tracer()


def configure(path=None, enabled=None, truncate=False, max_bytes=None):
    return TRACER.configure(path, enabled, truncate, max_bytes)


def span(name, **attrs):
    return TRACER.span(name, **attrs)


def event(name, **attrs):
    TRACER.event(name, **attrs)


def wrap(name, fn, **attrs):
    return TRACER.wrap(name, fn, **attrs)


def enable():
    TRACER.enable()


def disable():
    TRACER.disable()


def enabled():
    return TRACER.enabled


def events():
    return TRACER.events()


def reset():
    TRACER.reset()


def flush():
    TRACER.flush()


def load_events(path):
    """Read a trace JSONL file back into a list of event dicts (skips
    lines that fail to parse — a crashed writer can leave one)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out


# ---- per-request trace assembly ----------------------------------------

def assemble_request_traces(evs=None, path=None):
    """Fold a serving event stream into one record per request_id.

    Returns {request_id: record} where record["phases_ms"] holds the
    contiguous queue_wait / admission / prefill / decode / detokenize
    breakdown (phases tile [submit, end] exactly, so their sum equals
    wall_ms up to float rounding), record["ttft_ms"] is submit -> first
    token (prefill end), and record["per_token_ms"] is the decode phase
    over the tokens it produced. Batched `decode_dispatch` spans are
    also counted per request (record["decode_dispatches"]) — their
    batch-shared durations explain the decode phase but are not used to
    build it, so overlapping requests don't double-book wall time.
    """
    if evs is None:
        if path is None:
            evs = TRACER.events()
        else:
            evs = load_events(path)
    reqs: dict[object, dict] = {}
    compiles = []  # (ts, dur, program): compile-tracker events, used
    # below to attribute TTFT/ITL outliers to in-window XLA compiles

    def rec(rid):
        return reqs.setdefault(rid, {"request_id": rid,
                                     "decode_dispatches": 0,
                                     "decode_dispatch_ms": 0.0})

    for ev in evs:
        name = ev.get("name")
        rid = ev.get("request_id")
        if name == "request_submitted" and rid is not None:
            rec(rid)["t_submit"] = ev["ts"]
        elif name == "request_admitted" and rid is not None:
            # a preempted request is re-admitted: keep the FIRST
            # admission (phases keep first-residency semantics; the
            # preempted gap is reported separately as requeue_ms)
            r = rec(rid)
            r.setdefault("t_admit", ev["ts"])
            if "_t_preempt" in r:
                r["requeue_ms"] = r.get("requeue_ms", 0.0) + \
                    (ev["ts"] - r.pop("_t_preempt")) * 1e3
        elif name == "preempted" and rid is not None:
            r = rec(rid)
            r["preemptions"] = r.get("preemptions", 0) + 1
            r["_t_preempt"] = ev["ts"]
        elif name == "prefill" and rid is not None:
            r = rec(rid)
            # keep the FIRST prefill: its end IS the request's first
            # token; a resume re-prefill lands inside the decode phase
            r.setdefault("t_prefill_start", ev["ts"])
            r.setdefault("t_first_token", ev["ts"] + ev.get("dur", 0.0))
            if ev.get("chunks") is not None:
                r["prefill_chunks"] = (r.get("prefill_chunks", 0)
                                       + ev["chunks"])
        elif name == "decode_dispatch":
            for rid2 in ev.get("request_ids", ()):
                r = rec(rid2)
                r["decode_dispatches"] += 1
                r["decode_dispatch_ms"] += ev.get("dur", 0.0) * 1e3
        elif name == "request_done" and rid is not None:
            r = rec(rid)
            r["t_done"] = ev["ts"]
            r["new_tokens"] = ev.get("new_tokens")
            if ev.get("ttft_s") is not None:
                r["ttft_ms"] = ev["ttft_s"] * 1e3
            if ev.get("cost") is not None:
                # per-request cost attribution (ISSUE 17): the closed
                # ledger account the engine attached at completion
                r["cost"] = ev["cost"]
        elif name == "detokenize" and rid is not None:
            rec(rid)["t_end"] = ev["ts"] + ev.get("dur", 0.0)
        elif name == "tier_promote" and rid is not None:
            # aggregated host-tier promote batch attributed to this
            # request's admission attach (overlapped prefetch batches
            # carry no request_id — they ran before admission)
            r = rec(rid)
            r["tier_promote_ms"] = (r.get("tier_promote_ms", 0.0)
                                    + ev.get("dur_s", 0.0) * 1e3)
            r["tier_promote_blocks"] = (r.get("tier_promote_blocks", 0)
                                        + ev.get("blocks", 0))
        elif name == "compile":
            compiles.append((ev["ts"], ev.get("dur", 0.0),
                             ev.get("program")))

    out = {}
    for rid, r in reqs.items():
        t_submit = r.get("t_submit")
        if t_submit is None:
            continue  # partial trace (request predates the window)
        t_admit = r.get("t_admit", t_submit)
        t_pre0 = r.get("t_prefill_start", t_admit)
        t_first = r.get("t_first_token", t_pre0)
        t_done = r.get("t_done", t_first)
        t_end = r.get("t_end", t_done)
        phases = {
            "queue_wait": (t_admit - t_submit) * 1e3,
            "admission": (t_pre0 - t_admit) * 1e3,
            "prefill": (t_first - t_pre0) * 1e3,
            "decode": (t_done - t_first) * 1e3,
            "detokenize": (t_end - t_done) * 1e3,
        }
        wall_ms = (t_end - t_submit) * 1e3
        new = r.get("new_tokens") or 0
        decode_toks = max(new - 1, 0)  # token 0 comes from prefill
        out[rid] = {
            "request_id": rid,
            "phases_ms": {k: round(v, 4) for k, v in phases.items()},
            "wall_ms": round(wall_ms, 4),
            "ttft_ms": round(r.get("ttft_ms",
                                   (t_first - t_submit) * 1e3), 4),
            "new_tokens": new,
            "per_token_ms": round(phases["decode"] / decode_toks, 4)
            if decode_toks else None,
            "decode_dispatches": r["decode_dispatches"],
            "decode_dispatch_ms": round(r["decode_dispatch_ms"], 4),
        }
        if "prefill_chunks" in r:  # chunked prefill (paged server)
            out[rid]["prefill_chunks"] = r["prefill_chunks"]
        if r.get("cost") is not None:  # per-request attribution
            # account closed at completion (ISSUE 17)
            out[rid]["cost"] = r["cost"]
        if r.get("tier_promote_ms"):  # host-tier promote wall time of
            # this request's admission attach — its own trace event
            # now (not silently absorbed into the admission span); a
            # parallel "of which, tier promote" annotation inside the
            # admission phase, the compile_overlap_ms discipline —
            # the phase tiling of wall clock is untouched
            out[rid]["tier_promote_ms"] = round(r["tier_promote_ms"], 4)
            out[rid]["tier_promote_blocks"] = r["tier_promote_blocks"]
        if r.get("preemptions"):  # front door (round 12): the decode
            # phase of a preempted request absorbs its swap-out,
            # requeue wait, and resume re-prefill; requeue_ms says how
            # much of it was spent evicted
            out[rid]["preemptions"] = r["preemptions"]
            out[rid]["requeue_ms"] = round(r.get("requeue_ms", 0.0), 4)
        # XLA compile attribution (ISSUE 10): compile-tracker events
        # overlapping this request's residency explain TTFT/ITL
        # outliers that would otherwise read as queue/prefill/decode
        # time — the phases still tile wall clock; this is a parallel
        # "of which, compile" annotation
        overlap = 0.0
        n_comp = 0
        for cts, cdur, _prog in compiles:
            o = min(cts + cdur, t_end) - max(cts, t_submit)
            if o > 0:
                overlap += o
                n_comp += 1
        if n_comp:
            out[rid]["compiles_in_window"] = n_comp
            out[rid]["compile_overlap_ms"] = round(overlap * 1e3, 4)
    return out


def summarize_traces(traces):
    """Aggregate assembled request traces: count, TTFT/wall percentiles,
    mean phase breakdown — the report block bench --telemetry prints."""
    recs = list(traces.values()) if isinstance(traces, dict) else \
        list(traces)
    if not recs:
        return {"requests": 0}
    ttfts = sorted(r["ttft_ms"] for r in recs)
    walls = sorted(r["wall_ms"] for r in recs)
    n = len(recs)

    def pct(xs, p):
        return xs[min(n - 1, int(p * n))]

    phases = {}
    for r in recs:
        for k, v in r["phases_ms"].items():
            phases[k] = phases.get(k, 0.0) + v
    return {
        "requests": n,
        "ttft_p50_ms": round(pct(ttfts, .50), 3),
        "ttft_p99_ms": round(pct(ttfts, .99), 3),
        "wall_p50_ms": round(pct(walls, .50), 3),
        "wall_p99_ms": round(pct(walls, .99), 3),
        "mean_phase_ms": {k: round(v / n, 3) for k, v in phases.items()},
    }


def attach_device_ops(report, fn, steps=3, k=25):
    """Attach a device-op breakdown (utils/profiler.top_ops over the
    already-compiled zero-arg `fn`) to an assembled trace report dict:
    the per-request phases say WHERE the request's time went host-side,
    the op table says where the device milliseconds inside the dispatch
    spans go. Returns `report` (mutated) for chaining; profiling
    failures (no xplane on this backend) degrade to an "error" note
    rather than losing the report."""
    from ..utils import profiler as _profiler

    try:
        ops = _profiler.top_ops(fn, steps=steps, k=k)
        report["device_ops"] = [
            {"op": name, "total_ms": round(ms, 4), "count": count}
            for name, ms, count in ops]
    except Exception as e:  # noqa: BLE001 — xplane parsing is optional
        report["device_ops_error"] = f"{type(e).__name__}: {e}"
    return report
