"""Live ops endpoint — /metrics, /statusz, /healthz (ISSUE 10
tentpole, part a).

A stdlib-only `http.server` daemon thread that makes a running engine
watchable from outside the process:

  * `/metrics`  — Prometheus text exposition from the PR 2 registry
    (the standard scrape target);
  * `/statusz`  — live JSON engine state from a provider callable
    (the paged server wires `PagedGenerationServer.statusz()`: slots,
    lanes, tenants, pool / prefix-cache / quantization / sharding /
    speculation blocks from `stats()`, flight-recorder and compile
    summaries);
  * `/healthz`  — ok | degraded | stalled from a provider callable;
    ok and degraded answer 200 (the process still serves), stalled
    answers 503 so load balancers drain it. (Legacy shape, kept
    backward-compatible.)
  * `/healthz/live` and `/healthz/ready` — the SPLIT health semantics
    the fleet router routes on (r18 satellite): liveness = the engine
    loop is alive (dead -> 503 -> fail over, re-admit its sessions
    elsewhere); readiness = alive AND accepting admissions (a
    draining or stalled engine answers 503 ready=false -> stop
    routing NEW sessions there, but do NOT fail over the residents).
    Both return {"live"/"ready": bool, ...detail}.
  * `/slo`      — the burn-rate report of the attached SLO engine
    (`observability.slo`): per-SLO state ok | warn | page with fast/
    slow burn rates and error-budget accounting; a paging report
    answers 503. Served only when the owner wired an SLO engine
    (engine `slos=` / router `slos=`).
  * `/capacity` — the versioned `PressureSignals` snapshot (ISSUE 17):
    pool headroom + exhaustion forecast, tier occupancy, queue
    depths, shed/exhaustion pressure and SLO burn states — the
    ROADMAP-3 Autoscaler input. Served only when the owner wired a
    capacity provider (paged engine / fleet router federation).

Binding is ephemeral-port friendly (`port=0` → the kernel picks; the
bound port is on `.port`/`.url` after `start()` returns), which is how
the tests and `PagedGenerationServer(expose_port=0)` use it. Loopback
by default — exposing telemetry beyond the host is a deployment
decision, not a library default.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import log as _log
from . import metrics as _metrics

_logger = _log.get_logger(__name__)

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
HEALTH_STATES = ("ok", "degraded", "stalled")

_m_scrapes = _metrics.counter(
    "serving_ops_scrapes_total",
    "ops-endpoint requests served, by endpoint "
    "(metrics | statusz | healthz | livez | readyz | slo | capacity)",
    labelnames=("endpoint",))


class OpsEndpoint:
    """One HTTP listener serving the scrape/status/health triad.

    registry: a metrics.Registry (default: the process registry).
    statusz_fn: zero-arg callable returning a JSON-serializable dict.
    healthz_fn: zero-arg callable returning either a status string or
        a (status, detail_dict) pair; status must be one of
        ok | degraded | stalled.
    livez_fn / readyz_fn: zero-arg callables returning (bool, detail)
        for the split /healthz/live and /healthz/ready endpoints
        (absent -> those paths answer 404, the pre-split shape).
    metrics_fn: zero-arg callable returning Prometheus text to serve
        at /metrics INSTEAD of the registry (the fleet router's
        federated, replica-labeled view).
    slo_fn: zero-arg callable returning the SLO burn-rate report dict
        (`observability.slo.SLOEngine.report()` shape: {"slos": [...],
        "worst": ok|warn|page, "paging": [...]}) served at /slo —
        answers 200 while worst is ok or warn, 503 on page (the
        load-balancer drain signal); absent -> /slo answers 404.
    capacity_fn: zero-arg callable returning the versioned capacity
        snapshot (`observability.capacity.PressureSignals.sample()`
        shape, or the fleet-federated twin) served at /capacity;
        absent -> /capacity answers 404.
    """

    def __init__(self, registry=None, statusz_fn=None, healthz_fn=None,
                 livez_fn=None, readyz_fn=None, metrics_fn=None,
                 slo_fn=None, capacity_fn=None):
        self._registry = registry or _metrics.REGISTRY
        self._statusz_fn = statusz_fn
        self._healthz_fn = healthz_fn
        self._livez_fn = livez_fn
        self._readyz_fn = readyz_fn
        self._metrics_fn = metrics_fn
        self._slo_fn = slo_fn
        self._capacity_fn = capacity_fn
        self._httpd = None
        self._thread = None
        self.port = None

    @property
    def url(self):
        return None if self.port is None else f"http://127.0.0.1:{self.port}"

    # -- lifecycle --------------------------------------------------------
    def start(self, port=0, host="127.0.0.1"):
        if self._httpd is not None:
            return self
        endpoint = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # no stderr per request
                _logger.debug("ops endpoint: " + fmt, *args)

            def _send(self, code, body, ctype):
                data = body.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802 — http.server API
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path == "/metrics":
                        _m_scrapes.labels(endpoint="metrics").inc()
                        body = (endpoint._metrics_fn()
                                if endpoint._metrics_fn
                                else endpoint._registry.to_prometheus())
                        self._send(200, body, PROM_CONTENT_TYPE)
                    elif path == "/healthz/live" \
                            and endpoint._livez_fn is not None:
                        _m_scrapes.labels(endpoint="livez").inc()
                        ok, detail = endpoint._livez_fn()
                        self._send(200 if ok else 503, json.dumps(
                            {"live": bool(ok), **dict(detail)}),
                            "application/json")
                    elif path == "/healthz/ready" \
                            and endpoint._readyz_fn is not None:
                        _m_scrapes.labels(endpoint="readyz").inc()
                        ok, detail = endpoint._readyz_fn()
                        self._send(200 if ok else 503, json.dumps(
                            {"ready": bool(ok), **dict(detail)}),
                            "application/json")
                    elif path == "/statusz":
                        _m_scrapes.labels(endpoint="statusz").inc()
                        body = (endpoint._statusz_fn()
                                if endpoint._statusz_fn else {})
                        self._send(200, json.dumps(body, default=str),
                                   "application/json")
                    elif path == "/healthz":
                        _m_scrapes.labels(endpoint="healthz").inc()
                        status, detail = endpoint._health()
                        self._send(
                            503 if status == "stalled" else 200,
                            json.dumps({"status": status, **detail}),
                            "application/json")
                    elif path == "/slo" \
                            and endpoint._slo_fn is not None:
                        _m_scrapes.labels(endpoint="slo").inc()
                        report = endpoint._slo_fn()
                        code = (503 if report.get("worst") == "page"
                                else 200)
                        self._send(code, json.dumps(report),
                                   "application/json")
                    elif path == "/capacity" \
                            and endpoint._capacity_fn is not None:
                        _m_scrapes.labels(endpoint="capacity").inc()
                        snap = endpoint._capacity_fn()
                        self._send(200, json.dumps(snap, default=str),
                                   "application/json")
                    else:
                        paths = ["/metrics", "/statusz", "/healthz"]
                        if endpoint._livez_fn is not None:
                            paths.append("/healthz/live")
                        if endpoint._readyz_fn is not None:
                            paths.append("/healthz/ready")
                        if endpoint._slo_fn is not None:
                            paths.append("/slo")
                        if endpoint._capacity_fn is not None:
                            paths.append("/capacity")
                        self._send(404, json.dumps(
                            {"error": f"unknown path {path!r}",
                             "paths": paths}),
                            "application/json")
                except Exception as e:  # noqa: BLE001 — a provider bug
                    # must answer 500, not kill the listener thread
                    self._send(500, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}),
                        "application/json")

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"paddle-tpu-ops-endpoint:{self.port}")
        self._thread.start()
        _logger.info("ops endpoint serving /metrics /statusz /healthz "
                     "on %s", self.url)
        return self

    def _health(self):
        if self._healthz_fn is None:
            return "ok", {}
        out = self._healthz_fn()
        if isinstance(out, str):
            status, detail = out, {}
        else:
            status, detail = out
        if status not in HEALTH_STATES:
            return "degraded", {"detail": f"bad health state {status!r}"}
        return status, dict(detail)

    def stop(self):
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)
        self.port = None
