"""Chrome/Perfetto trace-event timeline export (ISSUE 14 tentpole,
part c).

Counters say THAT cross-replica overlap happened; a timeline shows it.
This module lays the span sink (r7 tracer events, now stamped with a
`replica` attribute) and the per-replica flight-recorder rings (r15)
out as Chrome trace-event JSON — the format `chrome://tracing` and
https://ui.perfetto.dev open directly:

  * one PROCESS per replica (plus one for the router / unattributed
    events), named via `process_name` metadata events;
  * one TRACK (thread) per event family inside each replica —
    `dispatch` (engine rounds, decode/prefill/verify dispatch spans),
    `requests` (submit/admit/done/detokenize), `compiles`, `faults`
    (fault injection, recovery, quarantine, stalls), `lifecycle`
    (preemptions, migrations, failover, draining), and `ring` for the
    flight-recorder's instant entries;
  * spans with a duration become complete (`"ph": "X"`) events,
    everything else an instant (`"ph": "i"`); timestamps are the
    tracer's monotonic seconds rebased to 0 and scaled to µs.

Entry points: `write_chrome_trace(path, ...)` here,
`FleetRouter.export_timeline(path)` /
`PagedGenerationServer.export_timeline(path)` on the serving stack,
and `bench.py served --timeline` which drops
`telemetry/TELEMETRY_timeline.json` next to the other artifacts.
"""
from __future__ import annotations

import json
import os

from . import tracing as _tracing

# event name -> track; anything unlisted lands on "requests"
_TRACKS = {
    "round": "dispatch",
    "decode_dispatch": "dispatch",
    "prefill_chunk": "dispatch",
    "verify_dispatch": "dispatch",
    "dispatch": "dispatch",
    "compile": "compiles",
    "fault_injected": "faults",
    "recovered": "faults",
    "recover_requeue": "faults",
    "quarantined": "faults",
    "quarantine": "faults",
    "request_timeout": "faults",
    "stall": "faults",
    "engine_exception": "faults",
    "shed": "faults",
    "reject": "faults",
    "preempted": "lifecycle",
    "preempt": "lifecycle",
    "resumed": "lifecycle",
    "migrate_out": "lifecycle",
    "fleet_migrate": "lifecycle",
    "fleet_place": "lifecycle",
    "fleet_failover_session": "lifecycle",
    "replica_kill": "lifecycle",
    "journal_readmit": "lifecycle",
    "draining": "lifecycle",
    "slo_degrade": "lifecycle",
}
_TRACK_ORDER = ("dispatch", "requests", "compiles", "faults",
                "lifecycle", "ring")
_SKIP = {"trace_start"}
_DROP_ARGS = {"ts", "dur", "name", "id", "tid", "depth", "parent",
              "seq", "replica"}


def _track_of(name, ring=False):
    if ring:
        return "ring"
    return _TRACKS.get(name, "requests")


def chrome_trace_events(span_events=(), recorders=None,
                        default_name="engine"):
    """Build the trace-event list. `span_events` is a tracer event
    stream (each event routed to the process named by its `replica`
    attribute, else `default_name`); `recorders` maps replica name ->
    flight-recorder event list (always instants on that replica's
    `ring` track). Returns (events, t0) with t0 the monotonic second
    everything was rebased against."""
    recorders = recorders or {}
    all_ts = [ev["ts"] for ev in span_events
              if "ts" in ev and ev.get("name") not in _SKIP]
    for evs in recorders.values():
        all_ts.extend(ev["ts"] for ev in evs
                      if "ts" in ev and ev.get("name") not in _SKIP)
    t0 = min(all_ts) if all_ts else 0.0
    pids: dict[str, int] = {}
    tids: dict[tuple, int] = {}
    out = []

    def pid_of(name):
        if name not in pids:
            pids[name] = len(pids) + 1
            out.append({"ph": "M", "name": "process_name",
                        "pid": pids[name], "tid": 0,
                        "args": {"name": name}})
            out.append({"ph": "M", "name": "process_sort_index",
                        "pid": pids[name], "tid": 0,
                        "args": {"sort_index": pids[name]}})
        return pids[name]

    def tid_of(pid, track):
        key = (pid, track)
        if key not in tids:
            tids[key] = _TRACK_ORDER.index(track) + 1 \
                if track in _TRACK_ORDER else len(_TRACK_ORDER) + 1
            out.append({"ph": "M", "name": "thread_name", "pid": pid,
                        "tid": tids[key], "args": {"name": track}})
        return tids[key]

    def emit(ev, proc, ring=False):
        name = ev.get("name")
        if name is None or name in _SKIP or "ts" not in ev:
            return
        pid = pid_of(proc)
        tid = tid_of(pid, _track_of(name, ring=ring))
        args = {k: v for k, v in ev.items()
                if k not in _DROP_ARGS and v is not None}
        args.pop("name", None)
        rec = {"name": name, "pid": pid, "tid": tid, "cat": "serving",
               "ts": round((ev["ts"] - t0) * 1e6, 3), "args": args}
        dur = ev.get("dur")
        if dur is not None and not ring:
            rec["ph"] = "X"
            rec["dur"] = round(float(dur) * 1e6, 3)
        else:
            rec["ph"] = "i"
            rec["s"] = "t"
        out.append(rec)

    for ev in span_events:
        emit(ev, ev.get("replica") or default_name)
    for rep_name, evs in recorders.items():
        for ev in evs:
            emit(ev, rep_name, ring=True)
    return out, t0


def write_chrome_trace(path, span_events=None, recorders=None,
                       default_name="engine"):
    """Write a Chrome trace-event JSON file; returns the number of
    non-metadata events written. `span_events=None` reads the process
    tracer's in-memory buffer."""
    if span_events is None:
        span_events = _tracing.events()
    events, _t0 = chrome_trace_events(span_events, recorders,
                                      default_name)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return sum(1 for e in events if e["ph"] != "M")
