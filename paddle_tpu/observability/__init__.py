"""paddle_tpu.observability — unified runtime telemetry (ISSUE 2) and
the serving operations plane (ISSUE 10).

Pillars, shared by serving, training, and bench:

  * `metrics` — process-wide registry of counters/gauges/histograms
    with labels; Prometheus-text and JSON snapshot exporters; near-zero
    cost when disabled.
  * `tracing` — span API emitting a JSONL event log with monotonic
    timestamps (bounded/rotating sink), plus the per-request trace
    assembler (queue-wait / admission / prefill / decode / detokenize
    phases, TTFT, per-token latency) and the utils/profiler.top_ops
    bridge.
  * `exporter` — stdlib http.server daemon thread serving /metrics
    (Prometheus text), /statusz (live JSON engine state), /healthz
    (ok | degraded | stalled); started via
    `PagedGenerationServer(expose_port=...)` / `FrontDoor` or
    PADDLE_TPU_METRICS_PORT.
  * `compile_tracker` — exact XLA compile detection at the decode jit
    boundaries (`serving_xla_compiles_total{program,in_flight,shard}`),
    always on, with a window API bench uses to prove measurement
    windows compile-clean.
  * `flight_recorder` — bounded ring buffer of structured engine
    events + the stall watchdog that auto-dumps it (no-op when
    disabled, like all telemetry).
  * `log` — the library logger (PADDLE_TPU_LOG_LEVEL verbosity);
    library code uses this instead of bare print()
    (scripts/check_no_print.py enforces it).
  * `trace_context` — fleet-wide causal tracing (ISSUE 14): a
    `TraceContext` (trace_id + hop + cause) minted at submit and
    carried through retries, failover, and migration; the causal
    assembler stitches one request's whole fleet lifetime into a
    single span tree whose phases tile wall-clock exactly.
  * `slo` — declarative `SLO(objective, target, window)` specs over
    TTFT/ITL/availability/goodput with sliding-window reservoirs and
    multi-window fast/slow burn-rate states (ok | warn | page),
    exported as `slo_*` gauges and the `/slo` ops endpoint.
  * `timeline` — Chrome/Perfetto trace-event JSON export of the span
    sink + flight-recorder rings, per-replica-per-track
    (`FleetRouter.export_timeline`, `bench.py served --timeline`).
  * `attribution` — ISSUE 17: per-tenant / per-request cost ledgers
    with exact integer conservation (device-seconds, KV
    block-seconds, host byte-seconds, wire bytes, compile time,
    prefix savings); `serving_tenant_*` metrics,
    `stats()["attribution"]`, `CostReport.to_json()` billing export.
  * `capacity` — ISSUE 17: the deterministic `PressureSignals` bus —
    pool headroom + reclaim trend + exhaustion-ETA forecast, tier
    occupancy, queue depths, shed/exhaustion pressure and SLO burns
    in one versioned snapshot (`/capacity` endpoint, federated by
    the fleet router; the ROADMAP-3 Autoscaler input contract).

One switch turns metrics+tracing on: PADDLE_TPU_TELEMETRY=1 in the
environment, or `observability.enable()` at runtime.
"""
from __future__ import annotations

from . import attribution, capacity  # noqa: F401
from . import compile_tracker, exporter, flight_recorder  # noqa: F401
from . import log, metrics, slo, timeline, trace_context  # noqa: F401
from . import tracing  # noqa: F401
from .attribution import (CostReport, ResourceLedger,  # noqa: F401
                          apportion, disabled_attribution_stats)
from .capacity import (PressureSignals,  # noqa: F401
                       federate_capacity)
from .exporter import OpsEndpoint  # noqa: F401
from .flight_recorder import FlightRecorder, StallWatchdog  # noqa: F401
from .log import get_logger  # noqa: F401
from .metrics import (REGISTRY, counter, gauge, histogram,  # noqa: F401
                      snapshot, to_prometheus)
from .slo import SLO, SLOEngine, default_slos  # noqa: F401
from .timeline import write_chrome_trace  # noqa: F401
from .trace_context import (TraceContext,  # noqa: F401
                            assemble_causal_traces)
from .tracing import (TRACER, assemble_request_traces,  # noqa: F401
                      attach_device_ops, span, summarize_traces)


def enable():
    """Turn on metrics collection AND tracing."""
    metrics.enable()
    tracing.enable()


def disable():
    metrics.disable()
    tracing.disable()


def enabled():
    return metrics.enabled() or tracing.enabled()
