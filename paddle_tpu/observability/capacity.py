"""Deterministic capacity / pressure signals (ISSUE 17).

`PressureSignals` is the pull-based sampler that turns the engine's
scattered load indicators — pool headroom, tier occupancy, lane and
tenant queue depths, admission sheds, `BlockPoolExhausted`
needed/available pressure, SLO burn rates — into ONE versioned
snapshot, plus a forecast: the reclaim-rate trend and a
blocks-exhaustion ETA from a linear fit over the sample window.

Discipline is the same as `utils.net.TokenBucket`: an explicit
injectable clock and a min-interval gate, so sampling is deterministic
and replay-testable — feed a fake clock, get byte-identical snapshot
sequences. Sources are zero-arg callables; a source that raises is
reported as `{"error": ...}` in its slot instead of poisoning the
snapshot (dead-source tolerance, same rule the fleet federation
applies across replicas).

The snapshot schema (`schema_version` 1) is the contract surface the
ROADMAP-3 `Autoscaler` control loop consumes — see
docs/OBSERVABILITY.md "Capacity & attribution".
"""
from __future__ import annotations

import threading
import time
from collections import deque

SCHEMA_VERSION = 1

#: the FEDERATED snapshot's version (ISSUE 20): 2 added the
#: fleet-level "aggregate" block alongside the per-replica slots.
#: Per-replica snapshots keep their own SCHEMA_VERSION.
FLEET_SCHEMA_VERSION = 2


def _linear_slope(points):
    """Least-squares slope of (t, v) points; None with < 2 points."""
    n = len(points)
    if n < 2:
        return None
    mt = sum(t for t, _ in points) / n
    mv = sum(v for _, v in points) / n
    num = sum((t - mt) * (v - mv) for t, v in points)
    den = sum((t - mt) ** 2 for t, _ in points)
    if den == 0:
        return None
    return num / den


class PressureSignals:
    """Assemble the named `sources` into versioned pressure snapshots.

    `sources` maps slot name ("pool", "tier", "queues", "admission",
    "slo", ...) to a zero-arg callable returning a JSON-able dict.
    `maybe_sample()` honors `min_interval_s` on the explicit clock
    (call it every engine round; it is nearly always a no-op);
    `sample()` forces one. The newest snapshot is kept for `snapshot()`
    and the pool's `free_blocks` series feeds the exhaustion forecast.
    """

    def __init__(self, sources, *, min_interval_s=1.0, window=32,
                 clock=None):
        self._sources = dict(sources)
        self._min_interval = float(min_interval_s)
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._free_series = deque(maxlen=int(window))
        self._last_t = None          # last sample time (gate)
        self._last = None            # last snapshot
        self._n_samples = 0

    def add_source(self, name, fn):
        with self._lock:
            self._sources[name] = fn

    def _read_sources(self):
        out = {}
        for name, fn in self._sources.items():
            try:
                out[name] = fn()
            except Exception as e:  # dead-source tolerance
                out[name] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def _forecast_locked(self, now, pool):
        free = pool.get("free_blocks") if isinstance(pool, dict) else None
        if isinstance(free, (int, float)):
            self._free_series.append((now, free))
        slope = _linear_slope(list(self._free_series))
        eta = None
        if slope is not None and slope < 0 and self._free_series:
            last_free = self._free_series[-1][1]
            if last_free > 0:
                eta = last_free / -slope
        return {
            # blocks/s the free list is draining (<0) or refilling (>0)
            "free_blocks_slope_per_s": slope,
            "exhaustion_eta_s": (None if eta is None
                                 else round(eta, 3)),
            "window_samples": len(self._free_series),
        }

    def sample(self, now=None):
        """Take one snapshot unconditionally; returns it."""
        now = self._clock() if now is None else now
        readings = self._read_sources()
        with self._lock:
            self._last_t = now
            self._n_samples += 1
            snap = {
                "schema_version": SCHEMA_VERSION,
                "ts": now,
                "samples": self._n_samples,
                "forecast": self._forecast_locked(
                    now, readings.get("pool")),
            }
            snap.update(readings)
            self._last = snap
            return snap

    def maybe_sample(self, now=None):
        """Sample iff `min_interval_s` elapsed since the last sample
        (TokenBucket-style gate). Returns the new snapshot or None."""
        now = self._clock() if now is None else now
        with self._lock:
            if (self._last_t is not None
                    and now - self._last_t < self._min_interval):
                return None
        return self.sample(now)

    def snapshot(self, now=None, refresh=True):
        """The newest snapshot; samples fresh when none exists yet (or
        always, with `refresh=True` — the `/capacity` endpoint path)."""
        if refresh:
            return self.sample(now)
        with self._lock:
            return self._last

    def history_len(self):
        with self._lock:
            return len(self._free_series)


def fleet_aggregate(replicas):
    """Fold per-replica capacity snapshots into the fleet-level
    aggregate block (ISSUE 20 satellite) so autoscale policies never
    re-derive it: total free/used blocks, min headroom fraction, max
    SLO burn, summed queue depth and shed pressure, plus the soonest
    blocks-exhaustion ETA. Tolerates old-shape sources — a replica
    slot that is an error, or predates a field, simply contributes
    nothing to that field."""
    agg = {
        "replicas_total": len(replicas),
        "replicas_ok": 0,
        "replicas_error": 0,
        "free_blocks_total": 0,
        "used_blocks_total": 0,
        "num_blocks_total": 0,
        "min_headroom_frac": None,
        "max_burn": None,
        "queue_depth_total": 0,
        "busy_slots_total": 0,
        "max_slots_total": 0,
        "sheds_total": 0,
        "draining": 0,
        "min_exhaustion_eta_s": None,
    }
    for snap in replicas.values():
        if not isinstance(snap, dict) or "error" in snap:
            agg["replicas_error"] += 1
            continue
        agg["replicas_ok"] += 1
        pool = snap.get("pool")
        if isinstance(pool, dict) and "error" not in pool:
            free = pool.get("free_blocks")
            used = pool.get("used_blocks")
            num = pool.get("num_blocks")
            if isinstance(free, (int, float)):
                agg["free_blocks_total"] += int(free)
            if isinstance(used, (int, float)):
                agg["used_blocks_total"] += int(used)
            if isinstance(num, (int, float)):
                agg["num_blocks_total"] += int(num)
            if (isinstance(free, (int, float))
                    and isinstance(num, (int, float)) and num > 0):
                frac = free / num
                if (agg["min_headroom_frac"] is None
                        or frac < agg["min_headroom_frac"]):
                    agg["min_headroom_frac"] = frac
        queues = snap.get("queues")
        if isinstance(queues, dict) and "error" not in queues:
            for src, dst in (("queue_depth", "queue_depth_total"),
                             ("busy_slots", "busy_slots_total"),
                             ("max_slots", "max_slots_total")):
                v = queues.get(src)
                if isinstance(v, (int, float)):
                    agg[dst] += int(v)
        adm = snap.get("admission")
        if isinstance(adm, dict) and "error" not in adm:
            sheds = adm.get("sheds")
            if isinstance(sheds, (int, float)):
                agg["sheds_total"] += int(sheds)
            if adm.get("draining"):
                agg["draining"] += 1
        slo = snap.get("slo")
        if isinstance(slo, dict) and slo.get("enabled"):
            for s in slo.get("slos") or ():
                if not isinstance(s, dict):
                    continue
                for k in ("burn_fast", "burn_slow"):
                    b = s.get(k)
                    if isinstance(b, (int, float)) and (
                            agg["max_burn"] is None
                            or b > agg["max_burn"]):
                        agg["max_burn"] = b
        fc = snap.get("forecast")
        if isinstance(fc, dict):
            eta = fc.get("exhaustion_eta_s")
            if isinstance(eta, (int, float)) and (
                    agg["min_exhaustion_eta_s"] is None
                    or eta < agg["min_exhaustion_eta_s"]):
                agg["min_exhaustion_eta_s"] = eta
    return agg


def federate_capacity(sources, timeout_s=None):
    """Fold named per-replica capacity callables into one fleet
    snapshot, tolerating dead sources — the JSON twin of
    `fleet.federation.federate_metrics`.

    `sources`: dict name -> zero-arg callable returning a snapshot
    dict. A source that raises contributes `{"error": ...}` under its
    name instead of failing the page.

    `timeout_s`: per-snapshot deadline. A source that HANGS (e.g. a
    wedged subprocess replica whose socket accepts but never answers)
    degrades to an error slot exactly like a dead one, instead of
    stalling the whole page: sources run on daemon worker threads and
    any still unfinished at the deadline is abandoned (its thread
    dies with the process; the next snapshot probes it afresh).
    None = synchronous in-caller calls (no threads), the in-process
    fleet shape.
    """
    replicas = {}
    if timeout_s is None:
        for name, fn in sources.items():
            try:
                replicas[name] = fn()
            except Exception as e:
                replicas[name] = {"error": f"{type(e).__name__}: {e}"}
        return {"schema_version": FLEET_SCHEMA_VERSION,
                "replicas": replicas,
                "aggregate": fleet_aggregate(replicas)}

    results = {}
    threads = {}
    for name, fn in sources.items():
        def _run(n=name, f=fn):
            try:
                results[n] = f()
            except Exception as e:  # noqa: BLE001 — error slot
                results[n] = {"error": f"{type(e).__name__}: {e}"}

        t = threading.Thread(target=_run, daemon=True,
                             name=f"capacity-{name}")
        t.start()
        threads[name] = t
    deadline = time.monotonic() + float(timeout_s)
    for name, t in threads.items():
        t.join(timeout=max(0.0, deadline - time.monotonic()))
        if name in results:
            replicas[name] = results[name]
        else:
            replicas[name] = {
                "error": f"timeout: no capacity snapshot within "
                         f"{float(timeout_s):g}s"}
    return {"schema_version": FLEET_SCHEMA_VERSION,
            "replicas": replicas,
            "aggregate": fleet_aggregate(replicas)}
