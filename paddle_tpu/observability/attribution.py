"""Per-tenant / per-request resource attribution (ISSUE 17).

The serving stack already *measures* everything that costs money —
device-busy wall time, KV pool occupancy, host-tier bytes, collective
wire bytes, compile time — but none of it says WHO spent it. This
module is the cost ledger the engine, pool and tier charge into, built
around one rule: **exact conservation**. Every charged quantity is an
integer (nanoseconds or bytes) split with largest-remainder
apportionment, so

  * Σ over tenants of device-ns     == engine busy-ns, exactly;
  * Σ over tenants of KV block-ns   == the pool occupancy integral
    (blocks × time, integrated on the same event clock), exactly;
  * Σ over tenants of host byte-ns  == the host-tier occupancy
    integral, exactly;
  * Σ over tenants of wire bytes    == the r20 analytic collective
    counters + migration payload bytes, exactly.

There is no "unattributed" bucket and no float residue — conservation
is arithmetic, not approximation, which is what makes the ledger a
billing surface (`CostReport.to_json()`) rather than a sampling one.

Block-seconds use a single-owner model: each non-free pool block is
owned by exactly one (tenant, request) — the one whose `_take_blocks`
pulled it off the free list — until the block RETURNS to the free
list. Prefix sharing, retention and revival keep the original owner
(the publisher pays; the attacher is credited `prefix_saved_*`
instead), so per-tenant block counts always sum to pool occupancy no
matter how wild the sharing graph gets.

Everything takes an explicit clock (`clock_ns=`, default
`time.monotonic_ns`) so conservation properties are replay-testable
on a fake clock, same discipline as `utils.net.TokenBucket`.
"""
from __future__ import annotations

import json
import threading
import time

from . import metrics as _metrics

_NS = 1_000_000_000

_m_tenant_device = _metrics.counter(
    "serving_tenant_device_seconds_total",
    "device-busy seconds apportioned to the tenant's resident requests "
    "per dispatch (exact: sums to engine busy time)",
    labelnames=("tenant",))
_m_tenant_block = _metrics.counter(
    "serving_tenant_kv_block_seconds_total",
    "KV device-block-seconds owned by the tenant (exact: sums to the "
    "pool occupancy integral)", labelnames=("tenant",))
_m_tenant_host = _metrics.counter(
    "serving_tenant_host_byte_seconds_total",
    "host-tier byte-seconds owned by the tenant's demoted KV entries",
    labelnames=("tenant",))
_m_tenant_wire = _metrics.counter(
    "serving_tenant_wire_bytes_total",
    "wire bytes attributed to the tenant by kind (collective = r20 "
    "analytic sharded-decode bytes, migration = session export payloads)",
    labelnames=("tenant", "kind"))
_m_tenant_compile = _metrics.counter(
    "serving_tenant_compile_seconds_total",
    "XLA compile seconds charged to the tenant whose dispatch triggered "
    "the compile", labelnames=("tenant",))
_m_tenant_prefix_saved = _metrics.counter(
    "serving_tenant_prefix_saved_tokens_total",
    "prompt tokens the tenant attached from the prefix cache instead of "
    "prefilling", labelnames=("tenant",))
_m_tenant_requests = _metrics.counter(
    "serving_tenant_requests_total",
    "requests finished per tenant (any terminal reason)",
    labelnames=("tenant",))


def apportion(total, weights):
    """Split integer `total` by integer `weights`, conserving exactly.

    Largest-remainder division in pure integer arithmetic: shares are
    `total*w // Σw` plus one extra unit to the largest remainders
    (ties broken by index, so the split is deterministic). Guarantees
    `sum(apportion(t, w)) == t` for any non-negative weights; an
    all-zero weight vector degrades to an even split.
    """
    n = len(weights)
    if n == 0:
        return []
    total = int(total)
    ws = [max(0, int(w)) for w in weights]
    wsum = sum(ws)
    if wsum == 0:
        ws = [1] * n
        wsum = n
    shares = [total * w // wsum for w in ws]
    left = total - sum(shares)
    rems = [(total * w) % wsum for w in ws]
    for i in sorted(range(n), key=lambda i: (-rems[i], i))[:left]:
        shares[i] += 1
    return shares


def _tenant_zero():
    return {"device_ns": 0, "compile_ns": 0, "block_ns": 0,
            "host_byte_ns": 0, "wire_bytes": 0, "wire_migration_bytes": 0,
            "prefix_saved_tokens": 0, "prefix_saved_ns": 0,
            "requests": 0, "new_tokens": 0}


class CostReport:
    """Frozen view of a ledger window — the billing export."""

    def __init__(self, payload):
        self._payload = payload

    def __getitem__(self, k):
        return self._payload[k]

    @property
    def tenants(self):
        return self._payload["tenants"]

    @property
    def totals(self):
        return self._payload["totals"]

    def to_dict(self):
        return self._payload

    def to_json(self, indent=None):
        return json.dumps(self._payload, indent=indent, sort_keys=True)


class ResourceLedger:
    """The attribution ledger: integer-exact per-tenant cost accounts.

    Thread-safe; every mutator takes the one lock. The engine charges
    device/compile/wire, the pool reports block ownership transitions
    (free-list boundary crossings only), and the tier reports host-byte
    ownership. `stats()` is the live window; `reset()` zeroes the
    window but carries the CURRENT occupancy levels forward so the
    next window's integrals start from zero coherently.
    """

    def __init__(self, clock_ns=None):
        self._clock = clock_ns or time.monotonic_ns
        self._lock = threading.RLock()
        self._tenants = {}          # tenant -> account dict
        self._reqs = {}             # live rid -> per-request account
        # block / host-byte ownership LEVELS (survive reset())
        self._blk = {}              # tenant -> owned device blocks
        self._rid_blk = {}          # live rid -> owned device blocks
        self._host = {}             # tenant -> owned host-tier bytes
        self._last_ns = self._clock()
        # window totals (the conservation right-hand sides)
        self._busy_ns = 0
        self._occ_block_ns = 0
        self._host_occ_byte_ns = 0
        self._wire_bytes = 0
        self._compile_ns = 0
        # measured per-token prefill cost (EMA, ns/token) for
        # prefix-savings credit
        self._prefill_ns_per_tok = 0.0
        self._prefill_samples = 0

    # -- internals ----------------------------------------------------

    def _acct(self, tenant):
        a = self._tenants.get(tenant)
        if a is None:
            a = self._tenants[tenant] = _tenant_zero()
        return a

    def _advance(self, now_ns):
        """Integrate occupancy up to `now_ns`.

        Per-tenant block-ns and the pool occupancy integral advance by
        the SAME `count * dt` products, so Σ tenants == occupancy by
        distributivity — conservation is maintained at every event,
        not reconciled after the fact.
        """
        dt = now_ns - self._last_ns
        if dt <= 0:
            self._last_ns = max(self._last_ns, now_ns)
            return
        self._last_ns = now_ns
        for t, c in self._blk.items():
            if c:
                add = c * dt
                self._acct(t)["block_ns"] += add
                if _metrics.enabled():
                    _m_tenant_block.labels(tenant=t).inc(add / _NS)
        self._occ_block_ns += sum(self._blk.values()) * dt
        for rid, c in self._rid_blk.items():
            if c:
                r = self._reqs.get(rid)
                if r is not None:
                    r["block_ns"] += c * dt
        for t, b in self._host.items():
            if b:
                add = b * dt
                self._acct(t)["host_byte_ns"] += add
                if _metrics.enabled():
                    _m_tenant_host.labels(tenant=t).inc(add / _NS)
        self._host_occ_byte_ns += sum(self._host.values()) * dt

    # -- pool / tier event surface ------------------------------------

    def block_event(self, tenant, rid, delta, now_ns=None):
        """A block crossed the free-list boundary (+1 taken, -1 freed)."""
        with self._lock:
            self._advance(self._clock() if now_ns is None else now_ns)
            self._blk[tenant] = self._blk.get(tenant, 0) + delta
            if self._blk[tenant] <= 0:
                del self._blk[tenant]
            if rid is not None and rid in self._reqs:
                c = self._rid_blk.get(rid, 0) + delta
                if c > 0:
                    self._rid_blk[rid] = c
                else:
                    self._rid_blk.pop(rid, None)

    def host_bytes_event(self, tenant, delta_bytes, now_ns=None):
        """Host-tier bytes entered (+) or left (-) the tenant's account."""
        with self._lock:
            self._advance(self._clock() if now_ns is None else now_ns)
            self._host[tenant] = self._host.get(tenant, 0) + delta_bytes
            if self._host[tenant] <= 0:
                del self._host[tenant]

    def owned_blocks(self):
        """Current per-tenant device-block ownership (test surface)."""
        with self._lock:
            return dict(self._blk)

    def owned_host_bytes(self):
        with self._lock:
            return dict(self._host)

    # -- engine charge surface ----------------------------------------

    def charge_device(self, dur_ns, parts):
        """Apportion `dur_ns` of device-busy time over `parts`.

        `parts` is a list of (tenant, rid, weight) — one entry per
        resident request the dispatch computed for, weighted by its
        token count in the round. One apportion call produces BOTH the
        per-tenant and per-request shares, so they agree exactly.
        """
        if dur_ns <= 0 or not parts:
            return
        with self._lock:
            shares = apportion(int(dur_ns), [p[2] for p in parts])
            self._busy_ns += int(dur_ns)
            for (tenant, rid, _w), s in zip(parts, shares):
                self._acct(tenant)["device_ns"] += s
                r = self._reqs.get(rid)
                if r is not None:
                    r["device_ns"] += s
                if s and _metrics.enabled():
                    _m_tenant_device.labels(tenant=tenant).inc(s / _NS)

    def charge_compile(self, dur_ns, parts):
        """Charge an in-window compile to the dispatch that tripped it."""
        if dur_ns <= 0 or not parts:
            return
        with self._lock:
            shares = apportion(int(dur_ns), [p[2] for p in parts])
            self._compile_ns += int(dur_ns)
            for (tenant, rid, _w), s in zip(parts, shares):
                self._acct(tenant)["compile_ns"] += s
                r = self._reqs.get(rid)
                if r is not None:
                    r["compile_ns"] += s
                if s and _metrics.enabled():
                    _m_tenant_compile.labels(tenant=tenant).inc(s / _NS)

    def charge_wire(self, nbytes, parts, kind="collective"):
        """Apportion wire bytes (collective traffic or migration payload)."""
        if nbytes <= 0 or not parts:
            return
        key = ("wire_migration_bytes" if kind == "migration"
               else "wire_bytes")
        with self._lock:
            shares = apportion(int(nbytes), [p[2] for p in parts])
            self._wire_bytes += int(nbytes)
            for (tenant, rid, _w), s in zip(parts, shares):
                self._acct(tenant)[key] += s
                r = self._reqs.get(rid)
                if r is not None:
                    r[key] += s
                if s and _metrics.enabled():
                    _m_tenant_wire.labels(tenant=tenant, kind=kind).inc(s)

    def note_prefill_cost(self, dur_ns, tokens):
        """Feed one measured prefill dispatch (EMA of ns per token)."""
        if tokens <= 0 or dur_ns <= 0:
            return
        with self._lock:
            per = dur_ns / tokens
            if self._prefill_samples == 0:
                self._prefill_ns_per_tok = per
            else:
                self._prefill_ns_per_tok += 0.2 * (
                    per - self._prefill_ns_per_tok)
            self._prefill_samples += 1

    def prefill_cost_ns_per_token(self):
        with self._lock:
            return self._prefill_ns_per_tok

    def credit_prefix(self, tenant, rid, tokens):
        """Credit a prefix-cache attach: tokens NOT prefilled, valued at
        the measured per-token prefill cost."""
        if tokens <= 0:
            return
        with self._lock:
            saved_ns = int(tokens * self._prefill_ns_per_tok)
            a = self._acct(tenant)
            a["prefix_saved_tokens"] += tokens
            a["prefix_saved_ns"] += saved_ns
            r = self._reqs.get(rid)
            if r is not None:
                r["prefix_saved_tokens"] += tokens
                r["prefix_saved_ns"] += saved_ns
            if _metrics.enabled():
                _m_tenant_prefix_saved.labels(tenant=tenant).inc(tokens)

    # -- request lifecycle --------------------------------------------

    def request_begin(self, rid, tenant):
        with self._lock:
            self._reqs[rid] = {
                "tenant": tenant, "device_ns": 0, "compile_ns": 0,
                "block_ns": 0, "wire_bytes": 0, "wire_migration_bytes": 0,
                "prefix_saved_tokens": 0, "prefix_saved_ns": 0}

    def request_done(self, rid, new_tokens=0):
        """Close a request's account; returns its cost dict (or None if
        unknown/already closed — idempotent by design, the engine has
        several terminal paths)."""
        with self._lock:
            self._advance(self._clock())
            r = self._reqs.pop(rid, None)
            if r is None:
                return None
            # residual blocks stay owned by the tenant (retained prefix
            # state outlives the request); only the per-rid live view ends
            self._rid_blk.pop(rid, None)
            a = self._acct(r["tenant"])
            a["requests"] += 1
            a["new_tokens"] += int(new_tokens)
            if _metrics.enabled():
                _m_tenant_requests.labels(tenant=r["tenant"]).inc()
            cost = {k: v for k, v in r.items() if k != "tenant"}
            cost["tenant"] = r["tenant"]
            cost["device_ms"] = round(r["device_ns"] / 1e6, 3)
            cost["kv_block_s"] = round(r["block_ns"] / _NS, 6)
            return cost

    # -- reporting ----------------------------------------------------

    def _stats_locked(self):
        self._advance(self._clock())
        tenants = {}
        for t, a in sorted(self._tenants.items()):
            tenants[t] = {
                "device_s": round(a["device_ns"] / _NS, 6),
                "device_ns": a["device_ns"],
                "kv_block_s": round(a["block_ns"] / _NS, 6),
                "kv_block_ns": a["block_ns"],
                "host_byte_s": round(a["host_byte_ns"] / _NS, 6),
                "host_byte_ns": a["host_byte_ns"],
                "wire_bytes": a["wire_bytes"],
                "wire_migration_bytes": a["wire_migration_bytes"],
                "compile_s": round(a["compile_ns"] / _NS, 6),
                "compile_ns": a["compile_ns"],
                "prefix_saved_tokens": a["prefix_saved_tokens"],
                "prefix_saved_s": round(a["prefix_saved_ns"] / _NS, 6),
                "requests": a["requests"],
                "new_tokens": a["new_tokens"],
            }
        dev_sum = sum(a["device_ns"] for a in self._tenants.values())
        blk_sum = sum(a["block_ns"] for a in self._tenants.values())
        host_sum = sum(a["host_byte_ns"] for a in self._tenants.values())
        wire_sum = sum(a["wire_bytes"] + a["wire_migration_bytes"]
                       for a in self._tenants.values())
        comp_sum = sum(a["compile_ns"] for a in self._tenants.values())
        return {
            "enabled": True,
            "tenants": tenants,
            "totals": {
                "busy_ns": self._busy_ns,
                "busy_s": round(self._busy_ns / _NS, 6),
                "occupancy_block_ns": self._occ_block_ns,
                "host_occupancy_byte_ns": self._host_occ_byte_ns,
                "wire_bytes": self._wire_bytes,
                "compile_ns": self._compile_ns,
                "prefill_cost_ns_per_token": round(
                    self._prefill_ns_per_tok, 1),
            },
            "conservation": {
                "device_residual_ns": self._busy_ns - dev_sum,
                "block_residual_ns": self._occ_block_ns - blk_sum,
                "host_residual_byte_ns": (
                    self._host_occ_byte_ns - host_sum),
                "wire_residual_bytes": self._wire_bytes - wire_sum,
                "compile_residual_ns": self._compile_ns - comp_sum,
            },
        }

    def stats(self):
        with self._lock:
            return self._stats_locked()

    def report(self):
        """Billing export for the current window."""
        with self._lock:
            payload = self._stats_locked()
            payload["schema_version"] = 1
            return CostReport(payload)

    def reset(self):
        """Zero the window accounts. Occupancy LEVELS (current block /
        host-byte ownership) carry forward so the next window's
        integrals restart from zero on both sides of the conservation
        equation — reset-coherent."""
        with self._lock:
            self._advance(self._clock())
            self._tenants.clear()
            self._reqs.clear()
            self._rid_blk.clear()
            self._busy_ns = 0
            self._occ_block_ns = 0
            self._host_occ_byte_ns = 0
            self._wire_bytes = 0
            self._compile_ns = 0


def disabled_attribution_stats():
    """The `stats()["attribution"]` block when attribution is off —
    schema-congruent with the enabled block, all zeros (the
    `disabled_tier_stats` convention)."""
    return {
        "enabled": False,
        "tenants": {},
        "totals": {"busy_ns": 0, "busy_s": 0.0, "occupancy_block_ns": 0,
                   "host_occupancy_byte_ns": 0, "wire_bytes": 0,
                   "compile_ns": 0, "prefill_cost_ns_per_token": 0.0},
        "conservation": {"device_residual_ns": 0, "block_residual_ns": 0,
                         "host_residual_byte_ns": 0,
                         "wire_residual_bytes": 0,
                         "compile_residual_ns": 0},
    }
