"""Loss ops.

Reference: paddle/fluid/operators/{softmax_with_cross_entropy,cross_entropy,
bce_loss,sigmoid_cross_entropy_with_logits,smooth_l1_loss,kldiv_loss,
margin_rank_loss,log_loss,huber_loss,hinge_loss,square_error_cost,
sigmoid_focal_loss}_op.* and python/paddle/nn/functional/loss.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ._registry import defop


def _reduce(loss, reduction, weight_sum=None):
    if reduction == "none":
        return loss
    if reduction == "sum":
        return jnp.sum(loss)
    if weight_sum is not None:
        return jnp.sum(loss) / jnp.maximum(weight_sum, 1e-12)
    return jnp.mean(loss)


@defop()
def cross_entropy(input, label, weight=None, ignore_index=-100,  # noqa: A002
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0):
    axis = axis % input.ndim
    logp = jax.nn.log_softmax(input, axis=axis) if use_softmax else jnp.log(
        jnp.maximum(input, 1e-30))
    if soft_label:
        labels = label
        if label_smoothing > 0:
            k = input.shape[axis]
            labels = labels * (1 - label_smoothing) + label_smoothing / k
        loss = -jnp.sum(labels * logp, axis=axis)
        if weight is not None:
            w = jnp.sum(labels * weight, axis=axis)
            loss = loss * w
            return _reduce(loss, reduction, jnp.sum(w))
        return _reduce(loss, reduction)
    lbl = jnp.asarray(label)
    if lbl.ndim == input.ndim and lbl.shape[axis] == 1:
        lbl = jnp.squeeze(lbl, axis)
    lbl = lbl.astype(jnp.int32)
    valid = lbl != ignore_index
    safe_lbl = jnp.where(valid, lbl, 0)
    k = input.shape[axis]
    if label_smoothing > 0:
        onehot = jax.nn.one_hot(safe_lbl, k, axis=axis, dtype=logp.dtype)
        onehot = onehot * (1 - label_smoothing) + label_smoothing / k
        loss = -jnp.sum(onehot * logp, axis=axis)
    else:
        loss = -jnp.take_along_axis(logp, jnp.expand_dims(safe_lbl, axis),
                                    axis=axis).squeeze(axis)
    if weight is not None:
        w = jnp.take(weight, safe_lbl) * valid.astype(logp.dtype)
        loss = loss * w
        loss = jnp.where(valid, loss, 0.0)
        return _reduce(loss, reduction, jnp.sum(w))
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
    return _reduce(loss, reduction)


@defop()
def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, axis=-1,
                               return_softmax=False):
    logp = jax.nn.log_softmax(logits, axis=axis)
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lbl = jnp.asarray(label)
        if lbl.ndim == logits.ndim and lbl.shape[axis] == 1:
            pass
        else:
            lbl = jnp.expand_dims(lbl, axis)
        lbl = lbl.astype(jnp.int32)
        valid = lbl != ignore_index
        loss = -jnp.take_along_axis(logp, jnp.where(valid, lbl, 0), axis=axis)
        loss = jnp.where(valid, loss, 0.0)
    if return_softmax:
        return loss, jax.nn.softmax(logits, axis=axis)
    return loss


@defop()
def binary_cross_entropy(input, label, weight=None, reduction="mean"):  # noqa: A002
    x = jnp.clip(input, 1e-12, 1 - 1e-7)
    loss = -(label * jnp.log(x) + (1 - label) * jnp.log1p(-x))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@defop()
def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None):
    neg_abs = -jnp.abs(logit)
    if pos_weight is not None:
        log_w = (pos_weight - 1) * label + 1
        loss = (1 - label) * logit + log_w * (jnp.log1p(jnp.exp(neg_abs))
                                              + jnp.maximum(-logit, 0.0))
    else:
        loss = jnp.maximum(logit, 0.0) - logit * label + jnp.log1p(jnp.exp(neg_abs))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


sigmoid_cross_entropy_with_logits = binary_cross_entropy_with_logits


@defop()
def mse_loss(input, label, reduction="mean"):  # noqa: A002
    return _reduce(jnp.square(input - label), reduction)


@defop()
def square_error_cost(input, label):  # noqa: A002
    return jnp.square(input - label)


@defop()
def l1_loss(input, label, reduction="mean"):  # noqa: A002
    return _reduce(jnp.abs(input - label), reduction)


@defop()
def smooth_l1_loss(input, label, reduction="mean", delta=1.0):  # noqa: A002
    d = jnp.abs(input - label)
    loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    return _reduce(loss, reduction)


@defop()
def huber_loss(input, label, delta=1.0):  # noqa: A002
    d = jnp.abs(input - label)
    return jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))


@defop()
def kl_div(input, label, reduction="mean"):  # noqa: A002
    loss = label * (jnp.log(jnp.maximum(label, 1e-30)) - input)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


@defop()
def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean"):  # noqa: A002
    lbl = jnp.asarray(label).astype(jnp.int32)
    valid = lbl != ignore_index
    safe = jnp.where(valid, lbl, 0)
    loss = -jnp.take_along_axis(input, jnp.expand_dims(safe, 1), axis=1).squeeze(1)
    w = jnp.ones_like(loss) if weight is None else jnp.take(weight, safe)
    w = w * valid.astype(loss.dtype)
    loss = loss * w
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)
    return _reduce(loss, reduction)


@defop()
def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):  # noqa: A002
    loss = jnp.maximum(-label * (input - other) + margin, 0.0)
    return _reduce(loss, reduction)


@defop()
def hinge_loss(logits, labels):
    return jnp.maximum(1.0 - logits * (2.0 * labels - 1.0), 0.0)


@defop()
def log_loss(input, label, epsilon=1e-4):  # noqa: A002
    return -label * jnp.log(input + epsilon) \
        - (1 - label) * jnp.log(1 - input + epsilon)


@defop()
def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum"):
    p = jax.nn.sigmoid(logit)
    ce = jnp.maximum(logit, 0.0) - logit * label + jnp.log1p(jnp.exp(-jnp.abs(logit)))
    p_t = p * label + (1 - p) * (1 - label)
    a_t = alpha * label + (1 - alpha) * (1 - label)
    loss = a_t * jnp.power(1 - p_t, gamma) * ce
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


@defop()
def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean"):
    cos = jnp.sum(input1 * input2, axis=-1) / jnp.maximum(
        jnp.linalg.norm(input1, axis=-1) * jnp.linalg.norm(input2, axis=-1), 1e-12)
    loss = jnp.where(label == 1, 1 - cos, jnp.maximum(cos - margin, 0.0))
    return _reduce(loss, reduction)


@defop()
def triplet_margin_loss(anchor, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean"):
    def d(a, b):
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a - b) + epsilon, p), axis=-1),
                         1.0 / p)
    dp = d(anchor, positive)
    dn = d(anchor, negative)
    if swap:
        dn = jnp.minimum(dn, d(positive, negative))
    return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)


@defop()
def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean"):
    """CTC via the standard dynamic-programming recursion under lax.scan.

    log_probs: [T, B, C] log-softmaxed; labels: [B, S] padded with any value.
    """
    T, B, C = log_probs.shape
    S = labels.shape[1]
    L = 2 * S + 1
    lab = jnp.asarray(labels).astype(jnp.int32)
    ext = jnp.full((B, L), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    neg_inf = -1e30

    same_as_prev2 = jnp.concatenate(
        [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

    init = jnp.full((B, L), neg_inf)
    init = init.at[:, 0].set(log_probs[0, jnp.arange(B), ext[:, 0]])
    init = init.at[:, 1].set(jnp.where(S > 0, log_probs[0, jnp.arange(B), ext[:, 1]],
                                       neg_inf))

    def lse(a, b):
        m = jnp.maximum(a, b)
        m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
        return jnp.where(jnp.isfinite(m),
                         m + jnp.log(jnp.exp(a - m_safe) + jnp.exp(b - m_safe)), m)

    def step(alpha, logp_t):
        shift1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
        shift2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
        shift2 = jnp.where(same_as_prev2, neg_inf, shift2)
        a = lse(lse(alpha, shift1), shift2)
        emit = jnp.take_along_axis(logp_t, ext, axis=1)
        new = a + emit
        return new, new

    _, alphas = jax.lax.scan(step, init, log_probs[1:])
    alphas = jnp.concatenate([init[None], alphas], axis=0)  # [T, B, L]
    t_idx = jnp.asarray(input_lengths).astype(jnp.int32) - 1
    final = alphas[t_idx, jnp.arange(B)]  # [B, L]
    last = 2 * jnp.asarray(label_lengths).astype(jnp.int32)
    p_blank = jnp.take_along_axis(final, last[:, None], axis=1)[:, 0]
    p_label = jnp.take_along_axis(final, jnp.maximum(last - 1, 0)[:, None], axis=1)[:, 0]
    loss = -lse(p_blank, jnp.where(label_lengths > 0, p_label, neg_inf))
    if reduction == "mean":
        return jnp.mean(loss / jnp.maximum(jnp.asarray(label_lengths), 1))
    return _reduce(loss, reduction)


@defop()
def npair_loss(anchor, positive, labels, l2_reg=0.002):
    sim = jnp.matmul(anchor, positive.T)
    lbl = jnp.asarray(labels).reshape(-1)
    target = (lbl[:, None] == lbl[None, :]).astype(sim.dtype)
    target = target / jnp.sum(target, axis=1, keepdims=True)
    logp = jax.nn.log_softmax(sim, axis=1)
    ce = -jnp.mean(jnp.sum(target * logp, axis=1))
    reg = l2_reg * (jnp.mean(jnp.sum(jnp.square(anchor), axis=1))
                    + jnp.mean(jnp.sum(jnp.square(positive), axis=1))) / 2
    return ce + reg
