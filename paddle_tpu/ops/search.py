"""Search / sort / index ops.

Reference: paddle/fluid/operators/{arg_max,arg_min,argsort,top_k_v2,where_index,
masked_select,unique,index_select,kthvalue,mode,searchsorted}_op.*.
Dynamic-output-shape ops (nonzero, masked_select, unique) are eager-only —
XLA needs static shapes, so inside jit/static graphs use masked alternatives.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ._registry import defop


@defop(nondiff=True)
def argmax(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmax(x, axis=axis, keepdims=keepdim if axis is not None else False)
    return out.astype(dtype_mod.convert_dtype(dtype))


@defop(nondiff=True)
def argmin(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmin(x, axis=axis, keepdims=keepdim if axis is not None else False)
    return out.astype(dtype_mod.convert_dtype(dtype))


@defop(nondiff=True)
def argsort(x, axis=-1, descending=False):
    idx = jnp.argsort(-x if descending else x, axis=axis, stable=True)
    return idx.astype(jnp.int32)


@defop()
def sort(x, axis=-1, descending=False):
    out = jnp.sort(x, axis=axis)
    return jnp.flip(out, axis=axis) if descending else out


def topk_impl(x, k, axis=-1, largest=True, sorted=True):  # noqa: A002
    """Raw (non-defop) top-k: the ONE implementation shared by the
    `topk` op and the sampling subsystem's top-k logit processor
    (paddle_tpu/sampling/processors.py uses it with k = V as the
    descending full sort the filter thresholds derive from).

    The smallest-k path is a stable ascending argsort + gather — NOT
    the `lax.top_k(-x)` negation trick, which (a) wraps for unsigned
    dtypes and INT_MIN (0 negates to 0, so the smallest unsigned value
    ranked LAST), and (b) returned values/indices whose tie order
    disagreed with the largest-k path for duplicate entries. Both
    paths now gather values at the returned indices, so
    `vals == take_along_axis(x, idx)` holds by construction and ties
    prefer the lower index in either direction."""
    axis = axis % x.ndim
    moved = jnp.moveaxis(x, axis, -1)
    if largest:
        vals, idx = jax.lax.top_k(moved, k)
    else:
        order = jnp.argsort(moved, axis=-1, stable=True)
        idx = order[..., :k]
        vals = jnp.take_along_axis(moved, idx, axis=-1)
    return (jnp.moveaxis(vals, -1, axis),
            jnp.moveaxis(idx.astype(jnp.int32), -1, axis))


@defop()
def topk(x, k, axis=-1, largest=True, sorted=True):  # noqa: A002
    return topk_impl(x, k, axis=axis, largest=largest, sorted=sorted)


@defop()
def kthvalue(x, k, axis=-1, keepdim=False):
    axis = axis % x.ndim
    sorted_x = jnp.sort(x, axis=axis)
    idx = jnp.argsort(x, axis=axis, stable=True)
    val = jnp.take(sorted_x, k - 1, axis=axis)
    ind = jnp.take(idx, k - 1, axis=axis).astype(jnp.int32)
    if keepdim:
        val = jnp.expand_dims(val, axis)
        ind = jnp.expand_dims(ind, axis)
    return val, ind


@defop()
def mode(x, axis=-1, keepdim=False):
    axis = axis % x.ndim
    sorted_x = jnp.sort(x, axis=axis)
    n = x.shape[axis]
    same = jnp.concatenate(
        [jnp.ones_like(jnp.take(sorted_x, jnp.array([0]), axis=axis), dtype=jnp.int32),
         (jnp.take(sorted_x, jnp.arange(1, n), axis=axis)
          == jnp.take(sorted_x, jnp.arange(0, n - 1), axis=axis)).astype(jnp.int32)],
        axis=axis)
    run = jax.lax.associative_scan(
        lambda a, b: b * (a + b != b) + (a + b) * (a * b != 0) * 0 + jnp.where(b != 0, a + b, 0) * 0,
        same, axis=axis) if False else _runlen(same, axis)
    best = jnp.argmax(run, axis=axis)
    val = jnp.take_along_axis(sorted_x, jnp.expand_dims(best, axis), axis=axis)
    val_s = jnp.squeeze(val, axis) if not keepdim else val
    # index of last occurrence in original array
    eq = x == (val if keepdim else jnp.expand_dims(val_s, axis))
    idx = jnp.max(jnp.where(eq, jnp.arange(n).reshape(
        [-1 if i == axis else 1 for i in range(x.ndim)]), -1), axis=axis,
        keepdims=keepdim).astype(jnp.int32)
    return val_s, idx


def _runlen(same, axis):
    def f(carry, s):
        run = jnp.where(s != 0, carry + 1, 1)
        return run, run
    moved = jnp.moveaxis(same, axis, 0)
    init = jnp.zeros(moved.shape[1:], moved.dtype)
    _, runs = jax.lax.scan(f, init, moved)
    return jnp.moveaxis(runs, 0, axis)


@defop(nondiff=True)
def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    side = "right" if right else "left"
    if sorted_sequence.ndim == 1:
        out = jnp.searchsorted(sorted_sequence, values, side=side)
    else:
        out = jax.vmap(lambda s, v: jnp.searchsorted(s, v, side=side))(
            sorted_sequence.reshape(-1, sorted_sequence.shape[-1]),
            values.reshape(-1, values.shape[-1]))
        out = out.reshape(values.shape)
    return out.astype(jnp.int32 if out_int32 else jnp.int32)


@defop(nondiff=True)
def bucketize(x, sorted_sequence, out_int32=False, right=False):
    side = "right" if right else "left"
    return jnp.searchsorted(sorted_sequence, x, side=side).astype(
        jnp.int32 if out_int32 else jnp.int32)


# ---- dynamic-shape (eager-only) ----

@defop(nondiff=True)
def nonzero(x, as_tuple=False):
    import numpy as np
    idx = np.nonzero(np.asarray(x))
    if as_tuple:
        return tuple(jnp.asarray(i) for i in idx)
    return jnp.stack([jnp.asarray(i) for i in idx], axis=1).astype(jnp.int32) \
        if idx else jnp.zeros((0, x.ndim), jnp.int32)


@defop()
def masked_select(x, mask):
    import numpy as np
    m = np.asarray(mask)
    return jnp.asarray(x)[jnp.asarray(m)]


@defop()
def masked_fill(x, mask, value):
    return jnp.where(mask, value, x)


@defop(nondiff=True)
def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None):
    import numpy as np
    res = np.unique(np.asarray(x), return_index=return_index,
                    return_inverse=return_inverse, return_counts=return_counts,
                    axis=axis)
    if isinstance(res, tuple):
        return tuple(jnp.asarray(r) for r in res)
    return jnp.asarray(res)


@defop(nondiff=True)
def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None):
    import numpy as np
    arr = np.asarray(x)
    if axis is None:
        arr = arr.reshape(-1)
        keep = np.concatenate([[True], arr[1:] != arr[:-1]])
        out = arr[keep]
        rets = [jnp.asarray(out)]
        if return_inverse:
            rets.append(jnp.asarray(np.cumsum(keep) - 1))
        if return_counts:
            idx = np.flatnonzero(keep)
            counts = np.diff(np.append(idx, arr.size))
            rets.append(jnp.asarray(counts))
        return tuple(rets) if len(rets) > 1 else rets[0]
    # axis version: drop a slice when it equals the previous slice along
    # `axis` (eager-only like unique — output shape is data-dependent)
    arr_m = np.moveaxis(arr, axis, 0)
    if arr_m.shape[0] == 0:
        keep = np.zeros(0, bool)
    else:
        flat = arr_m.reshape(arr_m.shape[0], -1)
        same = (flat[1:] == flat[:-1]).all(axis=1)
        keep = np.concatenate([[True], ~same])
    out = np.moveaxis(arr_m[keep], 0, axis)
    rets = [jnp.asarray(out)]
    if return_inverse:
        rets.append(jnp.asarray(np.cumsum(keep) - 1))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, arr_m.shape[0]))
        rets.append(jnp.asarray(counts))
    return tuple(rets) if len(rets) > 1 else rets[0]


@defop()
def median(x, axis=None, keepdim=False):
    return jnp.median(x, axis=axis, keepdims=keepdim)


@defop()
def quantile(x, q, axis=None, keepdim=False):
    return jnp.quantile(x, q, axis=axis, keepdims=keepdim)


@defop(nondiff=True)
def histogram(x, bins=100, min=0, max=0):  # noqa: A002
    lo, hi = (min, max) if (min != 0 or max != 0) else (jnp.min(x), jnp.max(x))
    hist, _ = jnp.histogram(x, bins=bins, range=(lo, hi))
    return hist
