"""Shape/layout manipulation ops.

Reference: paddle/fluid/operators/{reshape,transpose,concat,split,stack,slice,
strided_slice,gather,gather_nd,scatter,scatter_nd_add,tile,expand,pad,flip,
roll,squeeze,unsqueeze,flatten,unbind,unstack,where_index}_op.* and
python/paddle/tensor/manipulation.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ._registry import defop


def _dim(s):
    """Normalize a target dim: plain ints stay ints; jax.export symbolic
    dims (batch-polymorphic jit.save) pass through untouched."""
    try:
        return int(s)
    except Exception:  # symbolic dimension — no concrete value
        return s


@defop()
def reshape(x, shape):
    return jnp.reshape(x, tuple(_dim(s) for s in shape))


@defop()
def transpose(x, perm):
    return jnp.transpose(x, tuple(int(p) for p in perm))


@defop()
def t(x):
    if x.ndim < 2:
        return x
    return jnp.swapaxes(x, -1, -2)


@defop()
def moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


@defop()
def swapaxes(x, axis1, axis2):
    return jnp.swapaxes(x, axis1, axis2)


@defop()
def concat(xs, axis=0):
    return jnp.concatenate(list(xs), axis=int(axis))


@defop()
def stack(xs, axis=0):
    return jnp.stack(list(xs), axis=int(axis))


@defop()
def split(x, num_or_sections, axis=0):
    axis = int(axis)
    if isinstance(num_or_sections, int):
        return tuple(jnp.split(x, num_or_sections, axis=axis))
    # sections list, -1 allowed once (infer)
    secs = list(num_or_sections)
    if -1 in secs:
        known = sum(s for s in secs if s != -1)
        secs[secs.index(-1)] = x.shape[axis] - known
    idx = []
    acc = 0
    for s in secs[:-1]:
        acc += s
        idx.append(acc)
    return tuple(jnp.split(x, idx, axis=axis))


@defop()
def chunk(x, chunks, axis=0):
    return tuple(jnp.array_split(x, chunks, axis=int(axis)))


@defop()
def unstack(x, axis=0, num=None):
    return tuple(jnp.moveaxis(x, axis, 0))


unbind = unstack


@defop()
def squeeze(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = tuple(a % x.ndim for a in axes)
    axes = tuple(a for a in axes if x.shape[a] == 1)
    return jnp.squeeze(x, axis=axes) if axes else x


@defop()
def unsqueeze(x, axis):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    final_nd = x.ndim + len(axes)
    for a in sorted(a % final_nd for a in axes):
        x = jnp.expand_dims(x, a)
    return x


@defop()
def flatten(x, start_axis=0, stop_axis=-1):
    nd = x.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0
    shape = x.shape[:s] + (-1,) + x.shape[e + 1:]
    return jnp.reshape(x, shape)


@defop()
def slice(x, axes, starts, ends):  # noqa: A001
    idx = [jnp.s_[:]] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        idx[a] = jnp.s_[s:e]
    return x[tuple(idx)]


@defop()
def strided_slice(x, axes, starts, ends, strides):
    idx = [jnp.s_[:]] * x.ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = jnp.s_[s:e:st]
    return x[tuple(idx)]


@defop()
def gather(x, index, axis=0):
    index = jnp.asarray(index)
    if index.ndim == 2 and index.shape[1] == 1:
        index = index[:, 0]
    return jnp.take(x, index, axis=int(axis))


@defop()
def gather_nd(x, index):
    index = jnp.asarray(index)
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


@defop()
def take_along_axis(x, indices, axis):
    return jnp.take_along_axis(x, indices, axis=axis)


@defop()
def put_along_axis(x, indices, values, axis, reduce="assign"):
    if reduce == "add":
        return jnp.put_along_axis(x, indices, values, axis=axis, inplace=False, mode="add") \
            if hasattr(jnp, "put_along_axis") else _put_along(x, indices, values, axis, True)
    return _put_along(x, indices, values, axis, False)


def _put_along(x, indices, values, axis, add):
    axis = axis % x.ndim
    grids = jnp.meshgrid(*[jnp.arange(s) for s in indices.shape], indexing="ij")
    idx = list(grids)
    idx[axis] = indices
    values = jnp.broadcast_to(jnp.asarray(values, x.dtype), indices.shape)
    if add:
        return x.at[tuple(idx)].add(values)
    return x.at[tuple(idx)].set(values)


@defop()
def scatter(x, index, updates, overwrite=True):
    index = jnp.asarray(index)
    if index.ndim == 2 and index.shape[1] == 1:
        index = index[:, 0]
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


@defop()
def scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(jnp.asarray(index), -1, 0))
    return x.at[idx].add(updates)


@defop()
def scatter_nd(index, updates, shape):
    base = jnp.zeros(tuple(shape), jnp.asarray(updates).dtype)
    idx = tuple(jnp.moveaxis(jnp.asarray(index), -1, 0))
    return base.at[idx].add(updates)


@defop()
def tile(x, repeat_times):
    return jnp.tile(x, tuple(int(r) for r in repeat_times))


@defop()
def expand(x, shape):
    shape = list(shape)
    # paddle: -1 keeps original dim
    nd_new = len(shape)
    x_shape = (1,) * (nd_new - x.ndim) + tuple(x.shape)
    out_shape = tuple(x_shape[i] if shape[i] == -1 else int(shape[i])
                      for i in range(nd_new))
    return jnp.broadcast_to(jnp.reshape(x, x_shape), out_shape)


@defop()
def expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)


@defop()
def broadcast_to(x, shape):
    return jnp.broadcast_to(x, tuple(shape))


@defop()
def broadcast_tensors(xs):
    shape = jnp.broadcast_shapes(*[x.shape for x in xs])
    return tuple(jnp.broadcast_to(x, shape) for x in xs)


@defop()
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):  # noqa: A002
    pad = list(pad)
    nd = x.ndim
    if len(pad) == 2 * nd:
        # full per-dim spec: [d0_lo, d0_hi, d1_lo, d1_hi, ...] paddle uses
        # flattened [lo,hi] per dim starting from dim 0
        width = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # partial spec applies to trailing spatial dims (NCHW: last len/2 dims;
        # paddle convention: pad is [left,right,top,bottom,...] over spatial
        # dims in reverse order)
        k = len(pad) // 2
        width = [(0, 0)] * nd
        if data_format.endswith("C"):  # NHWC/NLC/NDHWC: spatial dims before C
            dims = list(range(nd - 1 - k, nd - 1))
        else:
            dims = list(range(nd - k, nd))
        for i, d in enumerate(reversed(dims)):
            width[d] = (pad[2 * i], pad[2 * i + 1])
    if mode == "constant":
        return jnp.pad(x, width, constant_values=value)
    jmode = {"reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    return jnp.pad(x, width, mode=jmode)


@defop()
def flip(x, axis):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return jnp.flip(x, axis=tuple(axes))


@defop()
def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=tuple(axes))


@defop()
def roll(x, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis)


@defop()
def cast(x, dtype):
    return jnp.asarray(x).astype(dtype_mod.convert_dtype(dtype))


@defop()
def repeat_interleave(x, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@defop()
def index_select(x, index, axis=0):
    index = jnp.asarray(index)
    if index.ndim > 1:
        index = index.reshape(-1)
    return jnp.take(x, index, axis=axis)


@defop()
def index_sample(x, index):
    # x: [N, D], index: [N, K] -> out[i, k] = x[i, index[i, k]]
    return jnp.take_along_axis(x, jnp.asarray(index), axis=1)


@defop()
def where(condition, x=None, y=None):
    if x is None and y is None:
        raise ValueError("where with only condition: use nonzero")
    return jnp.where(condition, x, y)


@defop(nondiff=True)
def shard_index(x, index_num, nshards, shard_id, ignore_value=-1):
    size = index_num // nshards
    shard = x // size
    local = x % size
    return jnp.where(shard == shard_id, local, ignore_value)


@defop()
def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@defop()
def as_complex(x):
    return jax.lax.complex(x[..., 0], x[..., 1])


@defop()
def real(x):
    return jnp.real(x)


@defop()
def imag(x):
    return jnp.imag(x)


@defop()
def conj(x):
    return jnp.conj(x)


@defop()
def crop(x, shape, offsets=None):
    offsets = offsets or [0] * x.ndim
    idx = tuple(jnp.s_[o:o + s] for o, s in zip(offsets, shape))
    return x[idx]


@defop()
def getitem(x, idx):
    return x[idx]


@defop()
def setitem(x, idx, value):
    return x.at[idx].set(value)
