"""Op definition machinery — the TPU-native "operator registry".

Reference: paddle/fluid/framework/op_registry.h + the 349-file operator library.
Rework: each op is ONE pure JAX function. The `defop` wrapper gives it the
three execution paths of the reference for free:
  * dygraph eager   — run now; record a jax.vjp pullback Node if grads needed
                      (replaces per-op GradOpMaker + handwritten grad kernels);
  * dygraph no-grad — run now, nothing recorded;
  * static graph    — append an op node to the current Program (shape inference
                      via jax.eval_shape, replacing InferShape), executed later
                      as one fused XLA computation.
Stochastic ops declare `stochastic=True` and receive an explicit PRNG `key`
kwarg (eager: drawn from the global generator; static: threaded per-run).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core import mode, rng
from ..core.autograd import Node, functional_trace_enabled, grad_enabled
from ..core.tensor import Tensor

OPS: dict = {}


def _is_tensor_leaf(x):
    return isinstance(x, Tensor)


def _flatten(args, kwargs):
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs), is_leaf=_is_tensor_leaf)
    return leaves, treedef


def _wrap_outputs(res, record_node, name, diff_tensors, vjp_fn,
                  pure_fn=None, keep_grad=False):
    multi = isinstance(res, (tuple, list))
    outs_raw = list(res) if multi else [res]
    sg = not (record_node or keep_grad)
    outs = [None if o is None else Tensor(o, stop_gradient=sg)
            for o in outs_raw]
    if record_node:
        live = [o for o in outs if o is not None]
        node = Node(vjp_fn, diff_tensors, live, name, multi, pure_fn=pure_fn)
        node._out_mask = [o is not None for o in outs]
        for o in live:
            o._node = node
    if multi:
        return tuple(outs) if isinstance(res, tuple) else outs
    return outs[0]


def _amp_cast_fn(fn, name):
    """Wrap fn to run in the AMP compute dtype when the policy says so
    (ref: fluid/contrib/mixed_precision auto-insertion of cast ops)."""
    from ..amp import amp_dtype, amp_should_cast
    if not amp_should_cast(name):
        return fn
    from ..core.dtype import convert_dtype
    dt = convert_dtype(amp_dtype())

    def wrapped(*a, **k):
        def cast(x):
            if hasattr(x, "dtype") and hasattr(x, "astype") and \
                    jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
                return jnp.asarray(x).astype(dt)
            return x
        a = jax.tree_util.tree_map(cast, a)
        out = fn(*a, **k)
        return jax.tree_util.tree_map(
            lambda o: o.astype(jnp.float32)
            if hasattr(o, "dtype") and jnp.issubdtype(o.dtype, jnp.floating)
            and o.dtype == dt else o, out)
    return wrapped


def _plain_tuple(res):
    """NamedTuple results (jnp.linalg.svd/qr/slogdet...) are normalized to
    plain tuples: jax.vjp's cotangent structure must match the primal
    output pytree, and the tape feeds plain-tuple cotangents (the
    reference's svd/qr return plain tuples too)."""
    return tuple(res) if isinstance(res, tuple) and hasattr(res, "_fields") \
        else res


def _call_plain(fn, *a, **k):
    return _plain_tuple(fn(*a, **k))


def apply_op(fn, name, args, kwargs, nondiff=False, stochastic=False):
    from ..amp import amp_enabled
    if amp_enabled():
        fn = _amp_cast_fn(fn, name)
    if mode.in_static_mode():
        hook = mode.static_hook()
        if hook is not None:
            return hook(name, fn, args, kwargs,
                        {"nondiff": nondiff, "stochastic": stochastic})
    if stochastic and kwargs.get("key") is None:
        kwargs = dict(kwargs)
        kwargs["key"] = rng.next_key()

    leaves, treedef = _flatten(args, kwargs)
    for leaf in leaves:
        if type(leaf).__name__ == "Variable" and hasattr(leaf, "block"):
            # a static-Program Variable reached an EAGER op: the guard was
            # entered without enabling static mode (2.0 defaults to
            # dygraph, like the reference) — fail with guidance instead of
            # a cryptic jax abstraction error
            raise RuntimeError(
                f"op '{name}' received a static Program Variable while in "
                "dygraph mode; call paddle.enable_static() before building "
                "static programs (fluid-style code runs under static mode)")
    vals = [l._value if isinstance(l, Tensor) else l for l in leaves]

    diff_idx = []
    if not nondiff and grad_enabled():
        for i, l in enumerate(leaves):
            if (isinstance(l, Tensor) and not l.stop_gradient
                    and jnp.issubdtype(l._value.dtype, jnp.inexact)):
                diff_idx.append(i)

    if not diff_idx:
        a2, k2 = jax.tree_util.tree_unflatten(treedef, vals)
        res = fn(*a2, **k2)
        return _wrap_outputs(res, False, name, [], None)

    if functional_trace_enabled() and any(
            isinstance(leaves[i]._value, jax.core.Tracer) for i in diff_idx):
        # Executing under an outer jax transform that owns differentiation
        # (functional_trace regions: train-step builders, functional_call,
        # executor lowering, to_static): the eager tape is dead weight —
        # the outer AD differentiates the primal ops directly. Recording
        # would also BREAK kernels with custom_vjp rules: the inner
        # jax.vjp consumes the rule, so an outer grad then differentiates
        # the raw forward (pallas flash has no jvp rule → silent XLA
        # fallback for three rounds, r4 finding). Call the op directly;
        # outputs keep stop_gradient=False so dispatch semantics hold.
        # (Outside functional_trace — e.g. dygraph backward() inside a
        # user shard_map — the tape still records as before.)
        a2, k2 = jax.tree_util.tree_unflatten(treedef, vals)
        res = fn(*a2, **k2)
        return _wrap_outputs(res, False, name, [], None, keep_grad=True)

    diff_tensors = [leaves[i] for i in diff_idx]

    def pure(*diff_vals):
        v = list(vals)
        for i, dv in zip(diff_idx, diff_vals):
            v[i] = dv
        a2, k2 = jax.tree_util.tree_unflatten(treedef, v)
        return fn(*a2, **k2)

    res, vjp_fn = jax.vjp(pure, *[t._value for t in diff_tensors])
    return _wrap_outputs(res, True, name, diff_tensors, vjp_fn, pure_fn=pure)


def defop(name=None, nondiff=False, stochastic=False):
    """Register a pure JAX function as a framework op."""
    def deco(fn):
        opname = name or fn.__name__
        # normalize namedtuple returns ONCE at registration (not per call:
        # eager dispatch is the hot path); only linalg-style ops ever
        # return them
        fn = functools.partial(_call_plain, fn)

        @functools.wraps(fn.func)
        def wrapper(*args, **kwargs):
            return apply_op(fn, opname, args, kwargs, nondiff, stochastic)

        wrapper.__opname__ = opname
        wrapper.__raw_fn__ = fn
        wrapper.__nondiff__ = nondiff
        wrapper.__stochastic__ = stochastic
        OPS[opname] = wrapper
        return wrapper
    return deco


def raw(x):
    """Unwrap Tensor → jax array (pass through everything else)."""
    if isinstance(x, Tensor):
        return x._value
    if isinstance(x, (list, tuple)):
        return type(x)(raw(e) for e in x)
    return x


def as_jax(x, dtype=None):
    if isinstance(x, Tensor):
        x = x._value
    x = jnp.asarray(x)
    return x if dtype is None else x.astype(dtype)
