"""Neural-net ops: activations, conv, pooling, normalization, dropout,
embedding, attention-adjacent utilities.

Reference: paddle/fluid/operators/{activation,conv,pool,batch_norm,layer_norm,
group_norm,instance_norm,dropout,lookup_table_v2,one_hot_v2,interpolate,
pixel_shuffle,unfold,softmax}_op.* and python/paddle/nn/functional/.
TPU-first: convs/matmuls go through lax.conv_general_dilated / dot_general so
XLA tiles them onto the MXU; elementwise activations fuse into neighbours.
"""
from __future__ import annotations

import functools as _functools
import math as _math
import os as _os

import jax
import jax.numpy as jnp

from ._registry import defop

# ------------------------------------------------------------ activations ---

@defop()
def relu(x):
    return jax.nn.relu(x)


@defop()
def relu6(x):
    return jax.nn.relu6(x)


@defop()
def leaky_relu(x, negative_slope=0.01):
    return jax.nn.leaky_relu(x, negative_slope)


@defop()
def prelu(x, weight):
    w = jnp.asarray(weight)
    if w.size > 1:  # per-channel on axis 1 (NCHW)
        shape = [1] * x.ndim
        shape[1] = w.size
        w = w.reshape(shape)
    return jnp.where(x >= 0, x, w * x)


@defop()
def elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha)


@defop()
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@defop()
def celu(x, alpha=1.0):
    return jax.nn.celu(x, alpha)


@defop()
def gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=bool(approximate))


@defop()
def sigmoid(x):
    return jax.nn.sigmoid(x)


@defop()
def log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


@defop()
def hardsigmoid(x, slope=1 / 6, offset=0.5):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


@defop()
def hardswish(x):
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


@defop()
def hardtanh(x, min=-1.0, max=1.0):  # noqa: A002
    return jnp.clip(x, min, max)


@defop()
def swish(x):
    return jax.nn.silu(x)


silu = swish


@defop()
def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


@defop()
def softplus(x, beta=1.0, threshold=20.0):
    bx = beta * x
    return jnp.where(bx > threshold, x, jnp.log1p(jnp.exp(bx)) / beta)


@defop()
def softsign(x):
    return jax.nn.soft_sign(x)


@defop()
def softshrink(x, threshold=0.5):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0))


@defop()
def hardshrink(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


@defop()
def tanhshrink(x):
    return x - jnp.tanh(x)


@defop()
def thresholded_relu(x, threshold=1.0):
    return jnp.where(x > threshold, x, 0.0)


@defop()
def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


@defop()
def maxout(x, groups, axis=1):
    axis = axis % x.ndim
    c = x.shape[axis]
    shape = x.shape[:axis] + (c // groups, groups) + x.shape[axis + 1:]
    return jnp.max(jnp.reshape(x, shape), axis=axis + 1)


@defop()
def glu(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


@defop()
def softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


@defop()
def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


@defop(stochastic=True)
def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, key=None):
    g = -jnp.log(-jnp.log(jax.random.uniform(key, x.shape, x.dtype, 1e-20, 1.0)))
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        y_hard = jnp.zeros_like(y)
        y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False) \
            if hasattr(jnp, "put_along_axis") else y_hard.at[..., :].set(
                jax.nn.one_hot(jnp.squeeze(idx, axis), y.shape[axis], dtype=y.dtype))
        y = jax.lax.stop_gradient(y_hard - y) + y
    return y


# ------------------------------------------------------------------ conv ----

def _pair(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(e) for e in v)
    return (int(v),) * n


def _conv_padding(padding, nsp, stride=None, dilation=None):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * nsp
    padding = list(padding)
    if len(padding) == nsp and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * nsp:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(nsp)]
    if all(isinstance(p, (list, tuple)) for p in padding):
        return [tuple(p) for p in padding]
    raise ValueError(f"bad padding {padding}")


def _conv(x, weight, bias, stride, padding, dilation, groups, nsp,
          channels_last=False):
    stride = _pair(stride, nsp)
    dilation = _pair(dilation, nsp)
    pad = _conv_padding(padding, nsp)
    sp = "DHW"[3 - nsp:]
    if channels_last:
        lhs_spec = "N" + sp + "C"
        out_spec = "N" + sp + "C"
    else:
        lhs_spec = "NC" + sp
        out_spec = "NC" + sp
    rhs_spec = "OI" + sp
    dn = jax.lax.conv_dimension_numbers(x.shape, weight.shape,
                                        (lhs_spec, rhs_spec, out_spec))
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=None)
    if bias is not None:
        bshape = [1] * out.ndim
        bshape[out.ndim - 1 if channels_last else 1] = bias.shape[0]
        out = out + jnp.reshape(bias, bshape)
    return out


@defop()
def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 channels_last=data_format == "NLC")


@defop()
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 channels_last=data_format == "NHWC")


@defop()
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 channels_last=data_format == "NDHWC")


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, nsp, channels_last=False):
    stride = _pair(stride, nsp)
    dilation = _pair(dilation, nsp)
    opad = _pair(output_padding, nsp)
    sp = "DHW"[3 - nsp:]
    lhs_spec = ("N" + sp + "C") if channels_last else ("NC" + sp)
    rhs_spec = "IO" + sp  # paddle transpose-conv weight: [in, out/groups, *k]
    # transposed conv == convolution (not correlation) of the stride-dilated
    # input with the kernel → flip the spatial dims
    weight = jnp.flip(weight, axis=tuple(range(2, 2 + nsp)))
    dn = jax.lax.conv_dimension_numbers(x.shape, weight.shape,
                                        (lhs_spec, rhs_spec, lhs_spec))
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _conv_padding(padding, nsp)
        # transposed conv padding: effective lo/hi = dilation*(k-1) - pad
        pad = []
        for i in range(nsp):
            eff = dilation[i] * (weight.shape[2 + i] - 1)
            pad.append((eff - p[i][0], eff - p[i][1] + opad[i]))
    if groups > 1:
        xs = jnp.split(x, groups, axis=(x.ndim - 1) if channels_last else 1)
        ws = jnp.split(weight, groups, axis=0)
        outs = [jax.lax.conv_general_dilated(
            xg, wg, window_strides=(1,) * nsp, padding=pad,
            lhs_dilation=stride, rhs_dilation=dilation, dimension_numbers=dn)
            for xg, wg in zip(xs, ws)]
        out = jnp.concatenate(outs, axis=(x.ndim - 1) if channels_last else 1)
    else:
        out = jax.lax.conv_general_dilated(
            x, weight, window_strides=(1,) * nsp, padding=pad,
            lhs_dilation=stride, rhs_dilation=dilation, dimension_numbers=dn)
    if bias is not None:
        bshape = [1] * out.ndim
        bshape[out.ndim - 1 if channels_last else 1] = bias.shape[0]
        out = out + jnp.reshape(bias, bshape)
    return out


@defop()
def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, data_format="NCL"):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, data_format == "NLC")


@defop()
def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, data_format="NCHW"):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format == "NHWC")


@defop()
def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, data_format="NCDHW"):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format == "NDHWC")


# --------------------------------------------------------------- pooling ----

def _pool_dims(x_ndim, nsp, kernel, stride, padding, channels_last=False):
    kernel = _pair(kernel, nsp)
    stride = _pair(stride if stride is not None else kernel, nsp)
    pad = _conv_padding(padding, nsp)
    if channels_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        pads = ("SAME" if pad == "SAME" else "VALID") if isinstance(pad, str) \
            else [(0, 0)] + list(pad) + [(0, 0)]
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        pads = pad if isinstance(pad, str) else [(0, 0), (0, 0)] + list(pad)
    return window, strides, pads


@defop()
def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NCHW", return_mask=False):
    window, strides, pads = _pool_dims(x.ndim, 2, kernel_size, stride, padding,
                                       data_format == "NHWC")
    init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    out = jax.lax.reduce_window(x, init, jax.lax.max, window, strides, pads)
    return out


@defop()
def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False):
    window, strides, pads = _pool_dims(x.ndim, 1, kernel_size, stride, padding)
    init = -jnp.inf
    return jax.lax.reduce_window(x, init, jax.lax.max, window, strides, pads)


@defop()
def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NCDHW"):
    window, strides, pads = _pool_dims(x.ndim, 3, kernel_size, stride, padding,
                                       data_format == "NDHWC")
    return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window, strides, pads)


def _avg_pool(x, nsp, kernel_size, stride, padding, exclusive, channels_last):
    window, strides, pads = _pool_dims(x.ndim, nsp, kernel_size, stride, padding,
                                       channels_last)
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, pads)
    if exclusive and not isinstance(pads, str):
        counts = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                       window, strides, pads)
        return summed / counts
    denom = 1
    for k in window:
        denom *= k
    return summed / denom


@defop()
def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False):
    return _avg_pool(x, 1, kernel_size, stride, padding, exclusive, False)


@defop()
def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW"):
    return _avg_pool(x, 2, kernel_size, stride, padding, exclusive,
                     data_format == "NHWC")


@defop()
def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, data_format="NCDHW"):
    return _avg_pool(x, 3, kernel_size, stride, padding, exclusive,
                     data_format == "NDHWC")


def _adaptive_windows(in_size, out_size):
    # emulate adaptive pooling by splitting into near-equal regions
    import numpy as np
    starts = (np.arange(out_size) * in_size // out_size).astype(int)
    ends = ((np.arange(out_size) + 1) * in_size - 1) // out_size + 1
    return starts, ends.astype(int)


def _adaptive_pool(x, output_size, nsp, reducer, channels_last=False):
    out_size = _pair(output_size, nsp)
    sp_off = 1 if channels_last else 2
    for d in range(nsp):
        in_sz = x.shape[sp_off + d]
        o = out_size[d]
        if in_sz % o == 0:
            k = in_sz // o
            shape = x.shape[:sp_off + d] + (o, k) + x.shape[sp_off + d + 1:]
            x = reducer(jnp.reshape(x, shape), axis=sp_off + d + 1)
        else:
            starts, ends = _adaptive_windows(in_sz, o)
            slices = [reducer(jax.lax.slice_in_dim(x, int(s), int(e), axis=sp_off + d),
                              axis=sp_off + d, keepdims=True)
                      for s, e in zip(starts, ends)]
            x = jnp.concatenate(slices, axis=sp_off + d)
    return x


@defop()
def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    return _adaptive_pool(x, output_size, 2, jnp.mean, data_format == "NHWC")


@defop()
def adaptive_max_pool2d(x, output_size, data_format="NCHW"):
    return _adaptive_pool(x, output_size, 2, jnp.max, data_format == "NHWC")


@defop()
def adaptive_avg_pool1d(x, output_size):
    return _adaptive_pool(x, output_size, 1, jnp.mean)


@defop()
def adaptive_max_pool1d(x, output_size):
    return _adaptive_pool(x, output_size, 1, jnp.max)


@defop()
def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    return _adaptive_pool(x, output_size, 3, jnp.mean, data_format == "NDHWC")


# ------------------------------------------------------------------ norm ----

def _bn_channel_axis(data_format, ndim):
    c_axis = 1 if not data_format.endswith("C") or ndim == 2 else ndim - 1
    if data_format in ("NHWC", "NLC", "NDHWC") and ndim > 2:
        c_axis = ndim - 1
    return c_axis


@_functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _bn_train_core(x, mean, var, weight, bias, epsilon, c_axis):
    """Training-mode BN normalize+scale with a MANUAL backward.

    The auto-derived vjp of the mean/var/normalize chain emits 4-5
    separate [C]-reduces over the full feature map per BN layer (dvar,
    dmean, dgamma, dbeta, plus dx's own terms) — measured 19ms/step of
    the ResNet-50 batch-256 step (r5 profile), ~2.4x the HBM roofline
    for the bytes actually needed. The closed-form backward shares TWO
    sums for everything:
        S1 = sum(dy),  S2 = sum(dy * xhat)   over (N, spatial)
        dgamma = S2,   dbeta = S1
        dx = gamma*inv * (dy - S1/n - xhat*S2/n)
    so each map is read once for the reduces (one fused dual-output
    pass) and once for dx (elementwise, fuses into neighbors)."""
    shape = [1] * x.ndim
    shape[c_axis] = x.shape[c_axis]
    inv = jax.lax.rsqrt(var + epsilon).reshape(shape)
    out = (x - mean.reshape(shape)) * inv
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


def _bn_core_fwd(x, mean, var, weight, bias, epsilon, c_axis):
    out = _bn_train_core(x, mean, var, weight, bias, epsilon, c_axis)
    return out, (x, mean, var, weight, bias)


def _bn_core_bwd(epsilon, c_axis, res, dy):
    x, mean, var, weight, bias = res
    shape = [1] * x.ndim
    shape[c_axis] = x.shape[c_axis]
    axes = tuple(i for i in range(x.ndim) if i != c_axis)
    n = x.size // x.shape[c_axis]
    inv = jax.lax.rsqrt(var + epsilon).reshape(shape)
    xhat = (x - mean.reshape(shape)) * inv
    # (a Pallas dual-reduce for these sums was tried in r5: Mosaic
    # SIGABRTs on 56x56 maps whose flattened spatial isn't 128-lane
    # divisible, and only the stem map qualifies — XLA's fusion stays)
    dyf = dy.astype(jnp.float32)
    s1 = jnp.sum(dyf, axis=axes)                       # = dbeta
    s2 = jnp.sum(dyf * xhat.astype(jnp.float32), axis=axes)  # = dgamma
    g = weight.reshape(shape) if weight is not None else 1.0
    dx = (g * inv).astype(dy.dtype) * (
        dy - (s1 / n).reshape(shape).astype(dy.dtype)
        - xhat.astype(dy.dtype) * (s2 / n).reshape(shape).astype(dy.dtype))
    # dmean/dvar: the batch stats are FUNCTIONS of x in training mode —
    # their contribution is already folded into the closed-form dx, so
    # their explicit cotangents here are zero
    dmean = jnp.zeros_like(mean)
    dvar = jnp.zeros_like(var)
    dweight = None if weight is None else s2.astype(weight.dtype)
    dbias = None if bias is None else s1.astype(bias.dtype)
    return dx, dmean, dvar, dweight, dbias


_bn_train_core.defvjp(_bn_core_fwd, _bn_core_bwd)


def _bn_normalize(x, mean, var, weight, bias, epsilon, c_axis):
    # computes in the naturally-promoted dtype (low-precision x with f32
    # stats -> f32 math) and RETURNS promoted; both op-level callers cast
    # back to the input dtype themselves — that cast is the op contract
    # (reference BN returns the input dtype), do not return promoted
    # values from a new op without it
    shape = [1] * x.ndim
    shape[c_axis] = x.shape[c_axis]
    out = (x - mean.reshape(shape)) * jax.lax.rsqrt(
        var.reshape(shape) + epsilon)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


@defop()
def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW",
               use_global_stats=None):
    c_axis = _bn_channel_axis(data_format, x.ndim)
    reduce_axes = tuple(i for i in range(x.ndim) if i != c_axis)
    use_batch = training and not use_global_stats
    if use_batch:
        mean = jnp.mean(x, axis=reduce_axes)
        var = jnp.var(x, axis=reduce_axes)
        n = x.size // x.shape[c_axis]
        unbiased = var * n / max(n - 1, 1)
        new_mean = momentum * running_mean + (1 - momentum) * jax.lax.stop_gradient(mean)
        new_var = momentum * running_var + (1 - momentum) * jax.lax.stop_gradient(unbiased)
        # manual-backward core: the batch stats are stop_gradiented INTO
        # the core (their x-dependence is folded into its closed-form
        # dx), and the backward shares one dual-sum pass for
        # dx/dgamma/dbeta instead of the auto-vjp's 4-5 map reduces
        out = _bn_train_core(x, jax.lax.stop_gradient(mean),
                             jax.lax.stop_gradient(var), weight, bias,
                             epsilon, c_axis)
    else:
        mean, var = running_mean, running_var
        new_mean, new_var = running_mean, running_var
        out = _bn_normalize(x, mean, var, weight, bias, epsilon, c_axis)
    # reference semantics: BN returns the INPUT dtype (normalization
    # computed in the promoted precision of the f32 running stats, then
    # cast back) — without this an AMP bf16 network silently re-promotes
    # to f32 at its first BatchNorm
    return out.astype(x.dtype), new_mean, new_var


@defop()
def sync_batch_norm(x, running_mean, running_var, weight=None, bias=None,
                    momentum=0.9, epsilon=1e-5, data_format="NCHW",
                    sync_axes=("dp",)):
    """Training-mode batch norm with CROSS-REPLICA statistics (ref:
    sync_batch_norm_op + its NCCL stats all-reduce). Moments (sum, sumsq,
    count) are computed in f32 and psummed over each axis in `sync_axes`
    that is bound in the surrounding shard_map/pmap; unbound axes (eager,
    plain pjit where GSPMD already sees the global batch) degrade to
    local = global. Running stats update with the unbiased variance, same
    as `batch_norm`. Returns (out, new_running_mean, new_running_var)."""
    c_axis = _bn_channel_axis(data_format, x.ndim)
    reduce_axes = tuple(i for i in range(x.ndim) if i != c_axis)
    xf = x.astype(jnp.float32)
    n_local = 1
    for i in reduce_axes:
        n_local *= x.shape[i]
    s1 = jnp.sum(xf, axis=reduce_axes)
    s2 = jnp.sum(jnp.square(xf), axis=reduce_axes)
    n = jnp.asarray(float(n_local), jnp.float32)
    for a in (sync_axes or ()):
        try:
            s1, s2, n = jax.lax.psum((s1, s2, n), a)
        except NameError:
            pass  # axis not bound here
    mean = s1 / n
    var = jnp.maximum(s2 / n - jnp.square(mean), 0.0)
    unbiased = var * n / jnp.maximum(n - 1.0, 1.0)
    new_mean = momentum * running_mean \
        + (1 - momentum) * jax.lax.stop_gradient(mean)
    new_var = momentum * running_var \
        + (1 - momentum) * jax.lax.stop_gradient(unbiased)
    out = _bn_normalize(xf, mean, var, weight, bias, epsilon, c_axis)
    return out.astype(x.dtype), new_mean, new_var


@defop()
def layer_norm(x, weight=None, bias=None, epsilon=1e-5, begin_norm_axis=None,
               normalized_ndim=None):
    """Normalize over trailing dims (paddle LayerNorm normalized_shape)."""
    if normalized_ndim is None:
        normalized_ndim = 1 if begin_norm_axis is None else x.ndim - begin_norm_axis
    axes = tuple(range(x.ndim - normalized_ndim, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


@defop()
def rms_norm(x, weight=None, epsilon=1e-6):
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    out = x * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight
    return out


@defop()
def instance_norm(x, weight=None, bias=None, epsilon=1e-5):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        shape = [1, -1] + [1] * (x.ndim - 2)
        out = out * weight.reshape(shape)
    if bias is not None:
        shape = [1, -1] + [1] * (x.ndim - 2)
        out = out + bias.reshape(shape)
    return out


@defop()
def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
               data_format="NCHW"):
    n, c = x.shape[0], x.shape[1]
    g = num_groups
    xg = jnp.reshape(x, (n, g, c // g) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    out = jnp.reshape((xg - mean) * jax.lax.rsqrt(var + epsilon), x.shape)
    shape = [1, c] + [1] * (x.ndim - 2)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


@defop()
def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0):
    sq = jnp.square(x)
    half = size // 2
    c = x.shape[1]
    acc = jnp.zeros_like(x)
    for i in range(-half, half + 1):
        shifted = jnp.roll(sq, i, axis=1)
        mask_lo = max(0, -i)
        mask_hi = c - max(0, i)
        ch = jnp.arange(c).reshape([1, c] + [1] * (x.ndim - 2))
        valid = (ch >= mask_lo) & (ch < mask_hi)
        acc = acc + jnp.where(valid, shifted, 0.0)
    return x / jnp.power(k + alpha * acc, beta)


@defop()
def normalize(x, p=2, axis=1, epsilon=1e-12):
    norm = jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=True),
                     1.0 / p)
    return x / jnp.maximum(norm, epsilon)


# --------------------------------------------------------------- dropout ----

@defop(stochastic=True)
def dropout(x, p=0.5, training=True, mode="upscale_in_train", axis=None,
            key=None):
    if not training or p == 0.0:
        return x if mode == "upscale_in_train" or training else x * (1 - p)
    if p == 1.0:
        return jnp.zeros_like(x)
    shape = list(x.shape)
    if axis is not None:
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        shape = [s if i in axes else 1 for i, s in enumerate(shape)]
    keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
    if mode == "upscale_in_train":
        return jnp.where(keep, x / (1.0 - p), 0.0)
    return jnp.where(keep, x, 0.0)


@defop(stochastic=True)
def dropout2d(x, p=0.5, training=True, data_format="NCHW", key=None):
    if not training or p == 0.0:
        return x
    c_axis = 1 if data_format == "NCHW" else x.ndim - 1
    shape = [x.shape[0]] + [1] * (x.ndim - 1)
    shape[c_axis] = x.shape[c_axis]
    keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
    return jnp.where(keep, x / (1.0 - p), 0.0)


dropout3d = dropout2d


@defop(stochastic=True)
def alpha_dropout(x, p=0.5, training=True, key=None):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    a = (1.0 / ((1 - p) * (1 + p * alpha_p ** 2)) ** 0.5)
    b = -a * alpha_p * p
    return a * jnp.where(keep, x, alpha_p) + b


# ---------------------------------------------------- embedding / one-hot ---

@defop()
def embedding(ids, weight, padding_idx=None, sparse=False):
    if padding_idx is not None:
        vocab = weight.shape[0]
        if not -vocab <= padding_idx < vocab:  # reference range-checks
            raise ValueError(
                f"padding_idx must be within [-{vocab}, {vocab}), "
                f"got {padding_idx}")
        if padding_idx < 0:  # reference normalizes negative indices
            padding_idx += vocab
        # padding row contributes no gradient (ref: lookup_table_v2_op padding_idx)
        frozen_row = jax.lax.stop_gradient(weight[padding_idx])
        weight = weight.at[padding_idx].set(frozen_row)
    if _EMBED_ONEHOT_VJP:
        return _embed_mm_vjp(weight, jnp.asarray(ids))
    return jnp.take(weight, jnp.asarray(ids), axis=0)


# dW via one-hot matmul instead of scatter-add: XLA TPU lowers scatter with
# duplicate indices poorly; the reduction runs on the MXU instead. The
# one-hot only avoids materializing (XLA fuses iota==ids into the GEMM
# operand) when the step is jitted — in pure eager mode each backward
# builds the full [tokens, vocab] array, so this flag is meant for
# jitted/@to_static training. Opt-in until the on-chip microbench
# (scripts/raw_ops_bench.py §6) shows which side wins at model shapes.
_EMBED_ONEHOT_VJP = _os.environ.get("PADDLE_TPU_EMBED_ONEHOT_VJP") == "1"


@_functools.lru_cache(maxsize=None)
def _embed_mm_vjp_for(vocab):
    @jax.custom_vjp
    def f(weight, ids):
        return jnp.take(weight, ids, axis=0)

    def fwd(weight, ids):
        return jnp.take(weight, ids, axis=0), ids

    def bwd(ids, g):
        import numpy as _np
        flat_ids = ids.reshape(-1)
        gf = g.reshape(flat_ids.shape[0], g.shape[-1])
        onehot = jax.nn.one_hot(flat_ids, vocab, dtype=gf.dtype)
        dw = jax.lax.dot_general(onehot, gf, (((0,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        # take() preserves dtype, so g's dtype == weight's dtype
        return (dw.astype(g.dtype),
                _np.zeros(ids.shape, jax.dtypes.float0))

    f.defvjp(fwd, bwd)
    return f


def _embed_mm_vjp(weight, ids):
    return _embed_mm_vjp_for(weight.shape[0])(weight, ids)


@defop(nondiff=True)
def one_hot(x, num_classes):
    return jax.nn.one_hot(jnp.asarray(x), num_classes, dtype=jnp.float32)


@defop()
def label_smooth(label, prior_dist=None, epsilon=0.1):
    k = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / k


# ------------------------------------------------------- linear / matmul ----

@defop()
def linear(x, weight, bias=None):
    out = jnp.matmul(x, weight)  # paddle weight: [in_features, out_features]
    if bias is not None:
        out = out + bias
    return out


# ------------------------------------------------------- image-ish utils ----

@defop()
def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, data_format="NCHW"):
    nsp = x.ndim - 2
    if size is None:
        sf = _pair(scale_factor, nsp)
        if data_format.endswith("C") and x.ndim > 2:
            size = tuple(int(x.shape[1 + i] * sf[i]) for i in range(nsp))
        else:
            size = tuple(int(x.shape[2 + i] * sf[i]) for i in range(nsp))
    else:
        size = _pair(size, nsp)
    if data_format.endswith("C") and x.ndim > 2:
        out_shape = (x.shape[0],) + size + (x.shape[-1],)
    else:
        out_shape = x.shape[:2] + size
    method = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
              "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]
    return jax.image.resize(x, out_shape, method=method)


upsample = interpolate


@defop()
def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    r = upscale_factor
    n, c, h, w = x.shape
    x = jnp.reshape(x, (n, c // (r * r), r, r, h, w))
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return jnp.reshape(x, (n, c // (r * r), h * r, w * r))


@defop()
def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    r = downscale_factor
    n, c, h, w = x.shape
    x = jnp.reshape(x, (n, c, h // r, r, w // r, r))
    x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
    return jnp.reshape(x, (n, c * r * r, h // r, w // r))


@defop()
def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    k = _pair(kernel_sizes, 2)
    s = _pair(strides, 2)
    d = _pair(dilations, 2)
    p = _conv_padding(paddings, 2)
    patches = jax.lax.conv_general_dilated_patches(
        x, filter_shape=k, window_strides=s, padding=p, rhs_dilation=d,
        dimension_numbers=jax.lax.conv_dimension_numbers(
            x.shape, (1, x.shape[1]) + k, ("NCHW", "OIHW", "NCHW")))
    n, ckk, oh, ow = patches.shape
    return jnp.reshape(patches, (n, ckk, oh * ow))


@defop()
def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True):
    n, c, h, w = x.shape
    gx, gy = grid[..., 0], grid[..., 1]
    if align_corners:
        ix = (gx + 1) * (w - 1) / 2
        iy = (gy + 1) * (h - 1) / 2
    else:
        ix = ((gx + 1) * w - 1) / 2
        iy = ((gy + 1) * h - 1) / 2

    def sample(img, yy, xx):
        valid = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
        yy = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xx = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        out = img[jnp.arange(n)[:, None, None, None], jnp.arange(c)[None, :, None, None],
                  yy[:, None], xx[:, None]]
        return jnp.where(valid[:, None], out, 0.0)

    if mode == "nearest":
        return sample(x, jnp.round(iy), jnp.round(ix))
    x0, y0 = jnp.floor(ix), jnp.floor(iy)
    x1, y1 = x0 + 1, y0 + 1
    wa = (x1 - ix) * (y1 - iy)
    wb = (x1 - ix) * (iy - y0)
    wc = (ix - x0) * (y1 - iy)
    wd = (ix - x0) * (iy - y0)
    va = sample(x, y0, x0)
    vb = sample(x, y1, x0)
    vc = sample(x, y0, x1)
    vd = sample(x, y1, x1)
    return (va * wa[:, None] + vb * wb[:, None] + vc * wc[:, None]
            + vd * wd[:, None])


@defop()
def affine_grid(theta, out_shape, align_corners=True):
    n, _, h, w = out_shape
    if align_corners:
        ys = jnp.linspace(-1, 1, h)
        xs = jnp.linspace(-1, 1, w)
    else:
        ys = (jnp.arange(h) * 2 + 1) / h - 1
        xs = (jnp.arange(w) * 2 + 1) / w - 1
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)  # [h, w, 3]
    return jnp.einsum("hwk,nik->nhwi", base, theta)


@defop()
def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.sqrt(jnp.sum(x1 * x1, axis=axis))
    n2 = jnp.sqrt(jnp.sum(x2 * x2, axis=axis))
    return dot / jnp.maximum(n1 * n2, eps)


@defop()
def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False):
    d = jnp.abs(x - y) + epsilon
    return jnp.power(jnp.sum(jnp.power(d, p), axis=-1, keepdims=keepdim), 1.0 / p)


@defop()
def temporal_shift(x, seg_num, shift_ratio=0.25):
    nt, c, h, w = x.shape
    n = nt // seg_num
    x = jnp.reshape(x, (n, seg_num, c, h, w))
    fold = int(c * shift_ratio)
    left = jnp.concatenate([x[:, 1:, :fold], jnp.zeros_like(x[:, :1, :fold])], axis=1)
    right = jnp.concatenate([jnp.zeros_like(x[:, :1, fold:2 * fold]),
                             x[:, :-1, fold:2 * fold]], axis=1)
    mid = x[:, :, 2 * fold:]
    out = jnp.concatenate([left, right, mid], axis=2)
    return jnp.reshape(out, (nt, c, h, w))
