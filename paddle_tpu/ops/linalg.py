"""Linear algebra ops.

Reference: paddle/fluid/operators/{matmul_v2,mul,bmm,addmm,dot,cholesky,
inverse,matrix_power,svd?,norm,dist,p_norm}_op.* and python/paddle/tensor/linalg.py.
matmul/dot_general are the MXU workhorses — keep operands bf16-friendly and let
XLA pick the contraction tiling.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ._registry import defop


@defop()
def matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


@defop()
def mm(x, y):
    return jnp.matmul(x, y)


@defop()
def bmm(x, y):
    return jnp.matmul(x, y)


@defop()
def dot(x, y):
    return jnp.sum(x * y, axis=-1)


@defop()
def addmm(input, x, y, beta=1.0, alpha=1.0):  # noqa: A002
    return beta * input + alpha * jnp.matmul(x, y)


@defop()
def outer(x, y):
    return jnp.outer(x, y)


@defop()
def inner(x, y):
    return jnp.inner(x, y)


@defop()
def multi_dot(xs):
    return jnp.linalg.multi_dot(list(xs))


@defop()
def einsum(equation, *operands):
    return jnp.einsum(equation, *operands)


@defop()
def norm(x, p="fro", axis=None, keepdim=False):
    if p == "fro" and axis is None:
        return jnp.sqrt(jnp.sum(jnp.square(x)))
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    if isinstance(axis, (list, tuple)) and len(axis) == 2:
        return jnp.linalg.norm(x, ord=p if p != "fro" else "fro",
                               axis=tuple(axis), keepdims=keepdim)
    if p == jnp.inf or p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == -jnp.inf or p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    return jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis,
                             keepdims=keepdim), 1.0 / p)


p_norm = norm


@defop()
def dist(x, y, p=2):
    d = jnp.abs(x - y)
    if p == 0:
        return jnp.sum((d != 0).astype(x.dtype))
    if p == float("inf"):
        return jnp.max(d)
    if p == float("-inf"):
        return jnp.min(d)
    return jnp.power(jnp.sum(jnp.power(d, p)), 1.0 / p)


@defop()
def cholesky(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


@defop()
def cholesky_solve(x, y, upper=False):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


@defop()
def inverse(x):
    return jnp.linalg.inv(x)


@defop()
def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


@defop(nondiff=True)
def matrix_rank(x, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(x, rtol=tol)


@defop()
def det(x):
    return jnp.linalg.det(x)


@defop()
def slogdet(x):
    sign, logdet = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logdet])


@defop()
def svd(x, full_matrices=False):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


@defop()
def qr(x, mode="reduced"):
    return jnp.linalg.qr(x, mode=mode)


@defop()
def eigh(x, UPLO="L"):
    w, v = jnp.linalg.eigh(x, UPLO=UPLO)
    return w, v


@defop()
def eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


@defop()
def solve(x, y):
    return jnp.linalg.solve(x, y)


@defop()
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


@defop()
def lstsq(x, y, rcond=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


@defop()
def pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


@defop()
def cross(x, y, axis=-1):
    return jnp.cross(x, y, axis=axis)


@defop()
def bincount(x, weights=None, minlength=0):
    return jnp.bincount(jnp.asarray(x).reshape(-1), weights=weights,
                        minlength=minlength)


@defop()
def mv(x, vec):
    return jnp.matmul(x, vec)


@defop()
def corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


@defop()
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)
