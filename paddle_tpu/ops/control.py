"""Control-flow ops.

Reference: python/paddle/fluid/layers/control_flow.py (cond, while_loop, case,
switch_case — C++ ConditionalBlock/While ops). TPU-first: these ARE
lax.cond/lax.while_loop/lax.switch, so control flow stays inside the compiled
XLA computation instead of bouncing to a host-side interpreter.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ._registry import apply_op, defop, raw


def _wrap(x):
    return jax.tree_util.tree_map(
        lambda v: Tensor(v) if hasattr(v, "shape") and not isinstance(v, Tensor)
        else v, x)


def _unwrap_tree(x):
    return jax.tree_util.tree_map(
        lambda v: v._value if isinstance(v, Tensor) else v, x,
        is_leaf=lambda v: isinstance(v, Tensor))


def cond(pred, true_fn=None, false_fn=None, name=None):
    """paddle.static.nn.cond — lax.cond under the hood. Branch fns take no
    args and may close over Tensors (traced as constants-by-reference)."""
    p = raw(pred)
    p = jnp.asarray(p).reshape(())

    def tf(_):
        return _unwrap_tree(true_fn())

    def ff(_):
        return _unwrap_tree(false_fn())

    out = jax.lax.cond(p.astype(bool), tf, ff, operand=None)
    return _wrap(out)


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """paddle.static.nn.while_loop — lax.while_loop."""
    init = tuple(_unwrap_tree(v) for v in loop_vars)

    def c(state):
        out = cond_fn(*_wrap(list(state)))
        return jnp.asarray(raw(out)).reshape(()).astype(bool)

    def b(state):
        out = body_fn(*_wrap(list(state)))
        out = out if isinstance(out, (list, tuple)) else [out]
        return tuple(_unwrap_tree(v) for v in out)

    final = jax.lax.while_loop(c, b, init)
    return _wrap(list(final))


def case(pred_fn_pairs, default=None, name=None):
    """paddle.static.nn.case — nested lax.cond chain."""
    def build(pairs):
        if not pairs:
            if default is None:
                raise ValueError("case: no default and no predicate matched "
                                 "statically")
            return _unwrap_tree(default())
        pred, fn = pairs[0]
        p = jnp.asarray(raw(pred)).reshape(()).astype(bool)
        return jax.lax.cond(p, lambda _: _unwrap_tree(fn()),
                            lambda _: build(pairs[1:]), operand=None)
    return _wrap(build(list(pred_fn_pairs)))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """paddle.static.nn.switch_case — lax.switch."""
    if isinstance(branch_fns, dict):
        max_idx = max(branch_fns)
        fns = [branch_fns.get(i, default) for i in range(max_idx + 1)]
    else:
        fns = list(branch_fns)
        if fns and isinstance(fns[0], (tuple, list)):
            d = dict(fns)
            max_idx = max(d)
            fns = [d.get(i, default) for i in range(max_idx + 1)]
    if default is not None:
        fns = fns + [default]
    idx = jnp.asarray(raw(branch_index)).reshape(()).astype(jnp.int32)
    idx = jnp.clip(idx, 0, len(fns) - 1)
    out = jax.lax.switch(idx, [(lambda f: lambda _: _unwrap_tree(f()))(f)
                               for f in fns], None)
    return _wrap(out)


@defop(nondiff=True)
def increment_inplace(x, value=1.0):
    return x + value


def fori_loop(lower, upper, body_fn, init):
    """Convenience: lax.fori_loop with Tensor carry."""
    out = jax.lax.fori_loop(int(lower), int(upper),
                            lambda i, s: _unwrap_tree(body_fn(i, _wrap(s))),
                            _unwrap_tree(init))
    return _wrap(out)


def scan(f, init, xs):
    """lax.scan with Tensor pytrees."""
    carry, ys = jax.lax.scan(
        lambda c, x: tuple(_unwrap_tree(f(_wrap(c), _wrap(x)))),
        _unwrap_tree(init), _unwrap_tree(xs))
    return _wrap(carry), _wrap(ys)
