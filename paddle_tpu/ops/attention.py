"""Attention ops.

Reference: the fused attention ops (paddle/fluid/operators/fused/ — north-star
names fused_attention_op) and python/paddle/nn/functional/transformer.py.
TPU-first: `scaled_dot_product_attention` dispatches to the Pallas
flash-attention kernel on TPU (MXU-tiled, online softmax, O(S) memory);
elsewhere it runs the plain einsum path, which XLA fuses well at small S.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ._registry import defop


def _on_tpu():
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


_flash_fallback_seen = set()


def _warn_flash_fallback(e):
    """A silent flash→XLA fallback hid a dead kernel path for three rounds;
    warn once per exception type so it can never hide again."""
    key = type(e).__name__
    if key not in _flash_fallback_seen:
        _flash_fallback_seen.add(key)
        import warnings
        warnings.warn(
            f"flash attention fell back to XLA attention: {key}: "
            f"{str(e)[:200]}", RuntimeWarning, stacklevel=3)


def _xla_attention(q, k, v, mask=None, scale=None, causal=False):
    # q: [B, H, Sq, D]; k/v: [B, H, Sk, D]
    scale = (q.shape[-1] ** -0.5) if scale is None else scale
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(cm, s, -1e30)
    if mask is not None:
        s = s + mask
    w = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", w, v)
    return out, w


@defop(stochastic=True)
def scaled_dot_product_attention(q, k, v, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, scale=None,
                                 return_weights=False, key=None):
    """q,k,v: [B, H, S, D] (head-major). Dispatches to flash attention when
    profitable; the weights output is only materialized when requested."""
    # The Pallas kernels stream K/V (fwd, dq) and Q/dO (dkv) blockwise over
    # an arbitrary grid dim with online-softmax state in VMEM scratch, so
    # per-step residency is a few blocks regardless of sequence length —
    # no VMEM-driven length cap. (The fused one-pass backward, which does
    # pin full Q/dO, self-gates on sq in _fa_bwd.)
    # a [B,1,1,Sk] additive mask (the padding-mask form every BERT-class
    # encoder builds) is a PER-KEY bias the kernel streams natively
    mask_v = attn_mask
    if mask_v is not None and hasattr(mask_v, "_value"):
        mask_v = mask_v._value
    key_bias = None
    if mask_v is not None and getattr(mask_v, "ndim", 0) == 4 \
            and mask_v.shape[1] == 1 and mask_v.shape[2] == 1 \
            and mask_v.shape[0] in (1, q.shape[0]) \
            and mask_v.shape[-1] == k.shape[-2]:
        key_bias = mask_v[:, 0, 0, :]
        if mask_v.shape[0] == 1 and q.shape[0] != 1:  # broadcast batch
            import jax.numpy as _jnp
            key_bias = _jnp.broadcast_to(key_bias,
                                         (q.shape[0], key_bias.shape[-1]))
    use_flash = (_on_tpu()
                 and (attn_mask is None or key_bias is not None)
                 and dropout_p == 0.0
                 and not return_weights and q.shape[-2] >= 128
                 and q.shape[-1] in (32, 64, 128, 256)
                 and q.shape[-2] % 128 == 0 and k.shape[-2] % 128 == 0)
    if use_flash:
        try:
            from .pallas.flash_attention import (flash_attention,
                                                 flash_attention_bias)
            # prescale Q once ([B,H,S,D] pass) instead of scaling every
            # score tile in fwd + bwd recompute (S^2-proportional VPU work);
            # the chain rule through the prescale restores dq's scale
            sc = (q.shape[-1] ** -0.5) if scale is None else scale
            # pallas_call abstractification rejects Tensor wrappers (JAX
            # dropped __jax_array__ support there), while plain jnp ops
            # accept them — unwrap, or the grad trace silently loses the
            # kernel (it did for three rounds: fwd had 12 tpu_custom_calls,
            # fwd+bwd had ZERO)
            from ._registry import raw
            qv, kv, vv = raw(q), raw(k), raw(v)
            if key_bias is None:
                out = flash_attention((qv * sc).astype(qv.dtype), kv, vv,
                                      causal=is_causal, scale=1.0)
            else:
                out = flash_attention_bias(
                    (qv * sc).astype(qv.dtype), kv, vv, raw(key_bias),
                    causal=is_causal, scale=1.0)
            return out, None
        except Exception as e:  # noqa: BLE001
            _warn_flash_fallback(e)
    out, w = _xla_attention(q, k, v, attn_mask, scale, is_causal)
    if dropout_p > 0.0:
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, w.shape)
        w_d = jnp.where(keep, w / (1.0 - dropout_p), 0.0)
        out = jnp.einsum("bhqk,bhkd->bhqd", w_d, v)
    return out, (w if return_weights else None)


def _is_quantized_kv(kv):
    """Duck-typed inference.kv_quant.QuantizedKV check (no import — the
    ops layer must not pull the inference package at module scope)."""
    return hasattr(kv, "codes") and hasattr(kv, "scales")


def paged_decode_attention(q, k_blocks, v_blocks, block_tables, ctx_lens,
                           scale=None):
    """Single-token decode attention over a PAGED KV cache (the
    gather-by-block-table read half of inference/kv_cache.py).

    q: [B, H, Dh] — one new token per sequence.
    k_blocks/v_blocks: [N, BS, H, Dh] — ONE layer's block pool; OR a
        `QuantizedKV` (int8 codes [N, BS, H, Dh], per-vector scales
        [N, BS, H]) for an int8 pool — dequantization happens INSIDE
        the kernel/contraction (the scales fold into the score and
        output einsums), so no bf16 copy of the cache ever
        materializes in HBM.
    block_tables: [B, M] int32 — block ids per sequence, 0-padded.
    ctx_lens: [B] int32 — tokens (cache positions) visible to each query;
        everything at position >= ctx_len is masked by LENGTH, never by
        pad-token value.

    Returns [B, H, Dh] in q's dtype. Dispatches to the Pallas ragged
    kernel on TPU when shapes allow (head_dim lane-sized, block_size a
    lane multiple, heads sublane-aligned); otherwise runs the XLA gather
    path, which materializes the [B, M*BS] gathered keys — correct
    everywhere, but it reads the padded table width instead of streaming
    exactly the live blocks."""
    quant = _is_quantized_kv(k_blocks)
    kcodes = k_blocks.codes if quant else k_blocks
    B, H, Dh = q.shape
    _, BS, _, _ = kcodes.shape
    M = block_tables.shape[1]
    sc = (Dh ** -0.5) if scale is None else scale
    if _on_tpu():
        try:
            from .pallas.paged_attention import (paged_decode_attention_kernel,
                                                 supported_shapes)
            if supported_shapes(Dh, BS, H):
                return paged_decode_attention_kernel(
                    q, k_blocks, v_blocks, block_tables, ctx_lens,
                    scale=float(sc))
        except Exception as e:  # noqa: BLE001
            _warn_flash_fallback(e)
    if quant:
        # gather CODES + per-vector scales; the int8->dt convert fuses
        # into the einsum operand pipeline (the weight-dot ::w8c trick)
        # and the scale vector multiplies the SCORE/PROB tensors — the
        # cache is consumed as raw int8
        k = jnp.transpose(kcodes[block_tables], (0, 3, 1, 2, 4)) \
            .reshape(B, H, M * BS, Dh)
        v = jnp.transpose(v_blocks.codes[block_tables], (0, 3, 1, 2, 4)) \
            .reshape(B, H, M * BS, Dh)
        ks = jnp.transpose(k_blocks.scales[block_tables]
                           .reshape(B, M * BS, H), (0, 2, 1))  # [B,H,C]
        vs = jnp.transpose(v_blocks.scales[block_tables]
                           .reshape(B, M * BS, H), (0, 2, 1))
        s = jnp.einsum("bhd,bhsd->bhs", q, k.astype(q.dtype)) \
            .astype(jnp.float32) * ks.astype(jnp.float32) * sc
        valid = jnp.arange(M * BS)[None, :] < ctx_lens[:, None]
        s = jnp.where(valid[:, None, :], s, -1e30)
        w = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        return jnp.einsum("bhs,bhsd->bhd", w * vs.astype(q.dtype),
                          v.astype(q.dtype))
    # XLA gather path: [B, M, BS, H, Dh] -> [B, H, M*BS, Dh]
    k = jnp.transpose(k_blocks[block_tables], (0, 3, 1, 2, 4)) \
        .reshape(B, H, M * BS, Dh)
    v = jnp.transpose(v_blocks[block_tables], (0, 3, 1, 2, 4)) \
        .reshape(B, H, M * BS, Dh)
    s = jnp.einsum("bhd,bhsd->bhs", q, k).astype(jnp.float32) * sc
    valid = jnp.arange(M * BS)[None, :] < ctx_lens[:, None]  # [B, M*BS]
    s = jnp.where(valid[:, None, :], s, -1e30)
    w = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhs,bhsd->bhd", w, v)


def ragged_prefill_attention(q, k_blocks, v_blocks, block_tables, seg, pos,
                             scale=None, allow_pallas=True):
    """Packed ragged prefill attention over a PAGED KV cache: every token
    of a token-packed multi-sequence stream attends its OWN sequence's
    cache positions [0, pos] — both the K/V this chunk just wrote and
    whatever earlier chunks of the same prompt left in the paged blocks,
    so chunked prefill carries no extra state.

    q: [T, H, Dh] — packed query stream (several prompt chunks).
    k_blocks/v_blocks: [N, BS, H, Dh] — ONE layer's block pool; OR
        `QuantizedKV` (int8 codes + per-vector scales) for an int8
        pool — scales fold into the score/output contractions, the
        cache streams as raw int8.
    block_tables: [B, M] int32 — block ids per slot row, 0-padded.
    seg: [T] int32 — slot row (index into block_tables) of each token.
    pos: [T] int32 — absolute cache position of each token; -1 marks a
        packing-pad token (its output is garbage the caller discards).

    Returns [T, H, Dh] in q's dtype. On TPU with aligned shapes this
    dispatches to the Pallas kernel (ops/pallas/ragged_prefill.py),
    which additionally requires the PACKING CONTRACT: each segment's
    packed region starts at a multiple of Q_TILE=128, so one query tile
    never mixes segments.

    The (seg, pos) row metadata defines the segment-causal masking
    contract shared by the Pallas kernels and the sequence-parallel
    serving seams (`serving_dist.sp_attention` splits this exact key
    set into a resident-pool pass and a rotating fresh-block pass; see
    ops/pallas/unified_attention.py for the normative statement).

    The XLA fallback gathers ONE [B, M*BS, ...] copy per slot ROW
    (never per token — a [T, M*BS, ...] materialization measured 8x
    slower than the sequential prefill at bench shapes), scores every
    query against every row's cache HEAD-MAJOR (one transpose per
    call instead of a relayout inside every batched matmul — a
    measured 3.4x on the same shapes), and applies the row-AND-position
    mask before a joint softmax over all rows — exactly the per-row
    softmax, because only the query's own row has unmasked columns.

    allow_pallas=False forces the XLA fallback even on TPU: the
    sequence-parallel packed trunk (long-context round) runs with
    sp-sharded queries under GSPMD, where a pallas_call is an opaque
    per-device program — the sp-local stream-kernel wiring (tile_base
    shard offsets, ops/pallas/unified_attention.py) is the ROADMAP
    follow-up."""
    quant = _is_quantized_kv(k_blocks)
    kcodes = k_blocks.codes if quant else k_blocks
    T, H, Dh = q.shape
    _, BS, _, _ = kcodes.shape
    B, M = block_tables.shape
    sc = (Dh ** -0.5) if scale is None else scale
    if allow_pallas and _on_tpu():
        try:
            from .pallas.unified_attention import (
                Q_TILE, supported_shapes, unified_ragged_attention_kernel)
            if supported_shapes(Dh, BS, H, T):
                return unified_ragged_attention_kernel(
                    q, k_blocks, v_blocks, block_tables,
                    seg[::Q_TILE], pos[::Q_TILE], scale=float(sc))
        except Exception as e:  # noqa: BLE001
            _warn_flash_fallback(e)
    # row-gather, head-major, joint-row softmax
    if quant:
        k = kcodes[block_tables].reshape(B, M * BS, H, Dh) \
            .transpose(2, 0, 1, 3).astype(q.dtype)        # [H, B, C, Dh]
        v = v_blocks.codes[block_tables].reshape(B, M * BS, H, Dh) \
            .transpose(2, 0, 1, 3).astype(q.dtype)
        ks = k_blocks.scales[block_tables].reshape(B, M * BS, H) \
            .transpose(2, 0, 1)                           # [H, B, C]
        vs = v_blocks.scales[block_tables].reshape(B, M * BS, H) \
            .transpose(2, 0, 1)
    else:
        k = k_blocks[block_tables].reshape(B, M * BS, H, Dh) \
            .transpose(2, 0, 1, 3)                        # [H, B, C, Dh]
        v = v_blocks[block_tables].reshape(B, M * BS, H, Dh) \
            .transpose(2, 0, 1, 3)
        ks = vs = None
    qh = q.transpose(1, 0, 2)                             # [H, T, Dh]
    s = jnp.einsum("htd,hbcd->htbc", qh, k).astype(jnp.float32) * sc
    if quant:  # per-KEY scale rides the score tensor post-contraction
        s = s * ks[:, None].astype(jnp.float32)
    own = seg[:, None] == jnp.arange(B)[None, :]          # [T, B]
    ok = jnp.arange(M * BS)[None, :] <= pos[:, None]      # [T, M*BS]
    mask = own[:, :, None] & ok[:, None, :]               # [T, B, M*BS]
    s = jnp.where(mask[None], s, -1e30)
    w = jax.nn.softmax(
        s.reshape(H, T, B * M * BS), axis=-1
    ).reshape(H, T, B, M * BS).astype(q.dtype)
    if quant:  # per-VALUE scale rides the prob tensor
        w = w * vs[:, None].astype(q.dtype)
    return jnp.einsum("htbc,hbcd->htd", w, v).transpose(1, 0, 2)


def unified_stream_attention(q, k_blocks, v_blocks, block_tables, seg,
                             pos, scale=None):
    """Unified serving-round attention (one-kernel round, r16): score a
    single packed token stream containing MIXED prefill chunks, plain
    decode rows and speculative verify regions in one launch.

    The insight of the merge (Ragged Paged Attention direction) is
    that the segment-causal contract already generalizes all three row
    kinds: a prefill chunk is n stream tokens at positions
    start..start+n-1, a decode row is 1 token at its write position,
    and a verify region is [last_token, draft_1..k] — in every case
    token t attends exactly its own sequence's cache positions
    [0, pos[t]].  So the unified op IS `ragged_prefill_attention` on
    the round's combined stream: the Pallas stream kernel
    (ops/pallas/unified_attention.py) on TPU, the row-gathered
    head-major XLA fallback elsewhere.  This alias exists as the
    documented entry point of the unified decode program
    (`nn.decode` `unified_round`); the argument contract is exactly
    `ragged_prefill_attention`'s."""
    return ragged_prefill_attention(q, k_blocks, v_blocks, block_tables,
                                    seg, pos, scale=scale)


def verify_window_attention(q, k_blocks, v_blocks, block_tables, pos,
                            scale=None):
    """Speculative-verification attention over a PAGED KV cache: a
    DENSE [P, W] window of queries per plan row (each row's last
    emitted token + its draft tokens, W pinned by the verify plan),
    every query attending its OWN row's cache positions [0, pos].

    q: [P, W, H, Dh]; k_blocks/v_blocks: [N, BS, H, Dh] (one layer's
    pool) or `QuantizedKV` codes+scales for an int8 pool (scales fold
    into the contractions); block_tables: [P, M] int32 0-padded; pos:
    [P, W] int32
    absolute cache positions (-1 = region pad; its output is finite
    garbage no readout index touches).

    Semantically this is `ragged_prefill_attention` on the flattened
    [P*W] stream — and on TPU with aligned shapes it IS that call, so
    the verify dispatch rides the same Pallas segment-causal kernel as
    packed prefill. Off TPU the dense layout lets the fallback score
    each row's window against ONLY its own cache ([P, W, C] scores
    instead of the packed fallback's [P*W, P, C] cross-row
    materialization) — the verify dispatch runs every scheduler round,
    and the P-fold waste measurably capped the speculation speedup on
    CPU."""
    quant = _is_quantized_kv(k_blocks)
    kcodes = k_blocks.codes if quant else k_blocks
    P, W, H, Dh = q.shape
    _, BS, _, _ = kcodes.shape
    M = block_tables.shape[1]
    sc = (Dh ** -0.5) if scale is None else scale
    if _on_tpu():
        seg = jnp.repeat(jnp.arange(P, dtype=jnp.int32), W)
        return ragged_prefill_attention(
            q.reshape(P * W, H, Dh), k_blocks, v_blocks, block_tables,
            seg, pos.reshape(-1), scale=sc).reshape(P, W, H, Dh)
    if quant:
        k = kcodes[block_tables].reshape(P, M * BS, H, Dh) \
            .astype(q.dtype)
        v = v_blocks.codes[block_tables].reshape(P, M * BS, H, Dh) \
            .astype(q.dtype)
        ks = k_blocks.scales[block_tables].reshape(P, M * BS, H) \
            .transpose(0, 2, 1)[:, :, None, :]            # [P, H, 1, C]
        vs = v_blocks.scales[block_tables].reshape(P, M * BS, H) \
            .transpose(0, 2, 1)[:, :, None, :]
    else:
        k = k_blocks[block_tables].reshape(P, M * BS, H, Dh)
        v = v_blocks[block_tables].reshape(P, M * BS, H, Dh)
        ks = vs = None
    s = jnp.einsum("pwhd,pchd->phwc", q, k).astype(jnp.float32) * sc
    if quant:
        s = s * ks.astype(jnp.float32)
    ok = jnp.arange(M * BS)[None, None, :] <= pos[:, :, None]
    s = jnp.where(ok[:, None], s, -1e30)
    w = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    if quant:
        w = w * vs.astype(q.dtype)
    return jnp.einsum("phwc,pchd->pwhd", w, v)


@defop()
def fused_multi_head_attention(x, qkv_weight, qkv_bias, out_weight, out_bias,
                               num_heads, attn_mask=None, dropout_p=0.0,
                               is_causal=False):
    """Fused QKV projection + attention + output projection (ref:
    fused_attention_op.cc). One einsum chain; XLA fuses the bias/reshape glue.

    x: [B, S, E]; qkv_weight: [E, 3E]; out_weight: [E, E].
    """
    b, s, e = x.shape
    d = e // num_heads
    qkv = jnp.einsum("bse,ef->bsf", x, qkv_weight)
    if qkv_bias is not None:
        qkv = qkv + qkv_bias
    qkv = qkv.reshape(b, s, 3, num_heads, d)
    q, k, v = (jnp.moveaxis(qkv[:, :, i], 2, 1) for i in range(3))
    out, _ = _xla_attention(q, k, v, attn_mask, None, is_causal)
    out = jnp.moveaxis(out, 1, 2).reshape(b, s, e)
    out = jnp.einsum("bse,ef->bsf", out, out_weight)
    if out_bias is not None:
        out = out + out_bias
    return out


@defop()
def fused_feedforward(x, w1, b1, w2, b2, activation="gelu"):
    """Fused FFN (ref: fused_feedforward_op) — XLA fuses act into the matmul."""
    h = jnp.einsum("bse,ef->bsf", x, w1)
    if b1 is not None:
        h = h + b1
    h = jax.nn.gelu(h) if activation == "gelu" else jax.nn.relu(h)
    out = jnp.einsum("bsf,fe->bse", h, w2)
    if b2 is not None:
        out = out + b2
    return out
