"""Elementwise math, binary ops, reductions, comparisons, logic.

Reference: paddle/fluid/operators/elementwise/*, activation_op.cc, reduce_ops/*,
controlflow/compare_op.cc, python/paddle/tensor/math.py. Each op is a pure JAX
function — XLA fuses chains of these into single kernels, so there is no need
for the reference's fused elementwise kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp_special

from ._registry import defop

# ---------------------------------------------------------------- binary ----

@defop()
def add(x, y):
    return jnp.add(x, y)


@defop()
def subtract(x, y):
    return jnp.subtract(x, y)


@defop()
def multiply(x, y):
    return jnp.multiply(x, y)


@defop()
def divide(x, y):
    return jnp.true_divide(x, y)


@defop(nondiff=True)
def floor_divide(x, y):
    return jnp.floor_divide(x, y)


@defop()
def remainder(x, y):
    return jnp.remainder(x, y)


mod = remainder
floor_mod = remainder


@defop()
def pow(x, y):  # noqa: A001
    return jnp.power(x, y)


@defop()
def maximum(x, y):
    return jnp.maximum(x, y)


@defop()
def minimum(x, y):
    return jnp.minimum(x, y)


@defop()
def fmax(x, y):
    return jnp.fmax(x, y)


@defop()
def fmin(x, y):
    return jnp.fmin(x, y)


@defop()
def atan2(x, y):
    return jnp.arctan2(x, y)


@defop()
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None):
    out = x * scale + bias if bias_after_scale else (x + bias) * scale
    return out


@defop()
def add_n(xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


@defop()
def lerp(x, y, weight):
    return x + weight * (y - x)


# ----------------------------------------------------------------- unary ----

def _unary(name, f, nondiff=False):
    @defop(name=name, nondiff=nondiff)
    def op(x):
        return f(x)
    return op


exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", lambda x: jax.lax.rsqrt(x))
abs = _unary("abs", jnp.abs)  # noqa: A001
square = _unary("square", jnp.square)
reciprocal = _unary("reciprocal", jnp.reciprocal)
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
erf = _unary("erf", jax.lax.erf)
erfinv = _unary("erfinv", jax.lax.erf_inv)
ceil = _unary("ceil", jnp.ceil)
floor = _unary("floor", jnp.floor)
round = _unary("round", jnp.round)  # noqa: A001
trunc = _unary("trunc", jnp.trunc)
sign = _unary("sign", jnp.sign)
neg = _unary("neg", jnp.negative)
digamma = _unary("digamma", jsp_special.digamma)
lgamma = _unary("lgamma", jsp_special.gammaln)
sigmoid_raw = None  # defined in nn_ops (activations)

isnan = _unary("isnan", jnp.isnan, nondiff=True)
isinf = _unary("isinf", jnp.isinf, nondiff=True)
isfinite = _unary("isfinite", jnp.isfinite, nondiff=True)


@defop()
def clip(x, min=None, max=None):  # noqa: A002
    return jnp.clip(x, min, max)


@defop()
def increment(x, value=1.0):
    return x + value


@defop(nondiff=True)
def logical_and(x, y):
    return jnp.logical_and(x, y)


@defop(nondiff=True)
def logical_or(x, y):
    return jnp.logical_or(x, y)


@defop(nondiff=True)
def logical_xor(x, y):
    return jnp.logical_xor(x, y)


@defop(nondiff=True)
def logical_not(x):
    return jnp.logical_not(x)


@defop(nondiff=True)
def bitwise_and(x, y):
    return jnp.bitwise_and(x, y)


@defop(nondiff=True)
def bitwise_or(x, y):
    return jnp.bitwise_or(x, y)


@defop(nondiff=True)
def bitwise_xor(x, y):
    return jnp.bitwise_xor(x, y)


@defop(nondiff=True)
def bitwise_not(x):
    return jnp.bitwise_not(x)


# ----------------------------------------------------------- comparisons ----

@defop(nondiff=True)
def equal(x, y):
    return jnp.equal(x, y)


@defop(nondiff=True)
def not_equal(x, y):
    return jnp.not_equal(x, y)


@defop(nondiff=True)
def less_than(x, y):
    return jnp.less(x, y)


@defop(nondiff=True)
def less_equal(x, y):
    return jnp.less_equal(x, y)


@defop(nondiff=True)
def greater_than(x, y):
    return jnp.greater(x, y)


@defop(nondiff=True)
def greater_equal(x, y):
    return jnp.greater_equal(x, y)


@defop(nondiff=True)
def equal_all(x, y):
    return jnp.array_equal(x, y)


@defop(nondiff=True)
def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@defop(nondiff=True)
def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


# dtype predicates (ref: python/paddle/tensor/attribute.py) — host-side
# answers about the Tensor's dtype, not traced ops
def is_complex(x):
    import jax.numpy as _jnp
    dt = x.dtype if hasattr(x, "dtype") else _jnp.asarray(x).dtype
    return _jnp.issubdtype(dt, _jnp.complexfloating)


def is_floating_point(x):
    import jax.numpy as _jnp
    dt = x.dtype if hasattr(x, "dtype") else _jnp.asarray(x).dtype
    return _jnp.issubdtype(dt, _jnp.floating)


def is_integer(x):
    import jax.numpy as _jnp
    dt = x.dtype if hasattr(x, "dtype") else _jnp.asarray(x).dtype
    return _jnp.issubdtype(dt, _jnp.integer)


# ------------------------------------------------------------ reductions ----

def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


@defop(name="sum")
def sum(x, axis=None, dtype=None, keepdim=False):  # noqa: A001
    return jnp.sum(x, axis=_norm_axis(axis), dtype=dtype, keepdims=keepdim)


@defop()
def mean(x, axis=None, keepdim=False):
    return jnp.mean(x, axis=_norm_axis(axis), keepdims=keepdim)


@defop(name="max")
def max(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.max(x, axis=_norm_axis(axis), keepdims=keepdim)


@defop(name="min")
def min(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.min(x, axis=_norm_axis(axis), keepdims=keepdim)


@defop()
def prod(x, axis=None, keepdim=False, dtype=None):
    return jnp.prod(x, axis=_norm_axis(axis), keepdims=keepdim, dtype=dtype)


@defop(nondiff=True)
def all(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.all(x, axis=_norm_axis(axis), keepdims=keepdim)


@defop(nondiff=True)
def any(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.any(x, axis=_norm_axis(axis), keepdims=keepdim)


@defop()
def logsumexp(x, axis=None, keepdim=False):
    return jsp_special.logsumexp(x, axis=_norm_axis(axis), keepdims=keepdim)


@defop()
def std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=_norm_axis(axis), ddof=1 if unbiased else 0,
                   keepdims=keepdim)


@defop()
def var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=_norm_axis(axis), ddof=1 if unbiased else 0,
                   keepdims=keepdim)


@defop(nondiff=True)
def count_nonzero(x, axis=None, keepdim=False):
    return jnp.count_nonzero(x, axis=_norm_axis(axis), keepdims=keepdim)


@defop()
def cumsum(x, axis=None, dtype=None):
    if axis is None:
        x = jnp.reshape(x, (-1,))
        axis = 0
    return jnp.cumsum(x, axis=axis, dtype=dtype)


@defop()
def cumprod(x, dim=None, dtype=None):
    if dim is None:
        x = jnp.reshape(x, (-1,))
        dim = 0
    return jnp.cumprod(x, axis=dim, dtype=dtype)


@defop()
def cummax(x, axis=None):
    if axis is None:
        x = jnp.reshape(x, (-1,))
        axis = 0
    return jax.lax.cummax(x, axis=axis)


@defop()
def cummin(x, axis=None):
    if axis is None:
        x = jnp.reshape(x, (-1,))
        axis = 0
    return jax.lax.cummin(x, axis=axis)


@defop()
def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@defop()
def kron(x, y):
    return jnp.kron(x, y)


@defop(nondiff=True)
def nan_to_num_(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


@defop()
def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)
