"""Chunked-vocab softmax cross-entropy: the LM loss without the logits.

A causal-LM step's single largest tensor is the logits, [B*S, V] (GPT-2:
8*1024 x 50257 ~ 0.8 GB in bf16, more in f32 softmax temporaries) — it is
written by the head matmul, read by the softmax, and read again by the
backward. This loss scans the vocabulary in chunks with an online
logsumexp (the flash-attention trick applied to the classifier axis, the
same statistics the Megatron vocab-parallel CE in models/gpt2_hybrid.py
psums across mp ranks — here the "ranks" are sequential chunks on one
chip): peak live logits memory drops from [N, V] to [N, V/chunks], and
the backward recomputes each chunk's logits instead of re-reading them
from HBM.

Candidate perf lever for the measured step-time gap (PERF.md round-3:
~1/3 of the 6N ideal, cause unattributed): OFF by default, enabled by
PADDLE_TPU_CHUNKED_CE=<n_chunks>, A/B'd on-chip by the recovery runner.
Numerics are parity-tested against the plain cross-entropy on CPU.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _pad_vocab(w, n_chunks):
    v = w.shape[0]
    v_pad = -(-v // n_chunks) * n_chunks
    if v_pad != v:
        w = jnp.concatenate(
            [w, jnp.zeros((v_pad - v, w.shape[1]), w.dtype)], axis=0)
    return w, v_pad


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def chunked_softmax_xent(x, w, labels, n_chunks, ignore_index=-100):
    """mean over VALID i of [ logsumexp_v(x_i . w_v) - x_i . w_{labels_i} ].

    x: [N, E] final hidden states; w: [V, E] tied embedding / head
    weight; labels: [N] int. Equivalent to
    cross_entropy(x @ w.T, labels) — including the ignore_index
    contract (ignored rows contribute no loss and no gradient; the mean
    divides by the valid count) — with peak logits memory [N, V/chunks].
    """
    loss, _ = _fwd_stats(x, w, labels, n_chunks, ignore_index)
    return loss


def _fwd_stats(x, w, labels, n_chunks, ignore_index):
    n, e = x.shape
    v_true = w.shape[0]
    wp, v_pad = _pad_vocab(w, n_chunks)
    vc = v_pad // n_chunks
    wc = wp.reshape(n_chunks, vc, e)
    xf = x.astype(jnp.float32)

    def body(carry, c):
        m, s, tgt = carry
        logits = (xf @ wc[c].reshape(vc, e).T.astype(jnp.float32))
        col = c * vc + jnp.arange(vc)
        logits = jnp.where(col[None, :] < v_true, logits, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1)
        lid = labels - c * vc
        ok = (lid >= 0) & (lid < vc)
        t = jnp.take_along_axis(
            logits, jnp.clip(lid, 0, vc - 1)[:, None], axis=1)[:, 0]
        tgt = tgt + jnp.where(ok, t, 0.0)
        return (m_new, s, tgt), None

    m0 = jnp.full((n,), NEG_INF, jnp.float32)
    s0 = jnp.zeros((n,), jnp.float32)
    (m, s, tgt), _ = jax.lax.scan(body, (m0, s0, s0),
                                  jnp.arange(n_chunks))
    valid = (labels != ignore_index)
    count = jnp.maximum(jnp.sum(valid), 1)
    nll = (jnp.log(s) + m - tgt) * valid
    return jnp.sum(nll) / count, (m, s)


def _fwd(x, w, labels, n_chunks, ignore_index):
    loss, (m, s) = _fwd_stats(x, w, labels, n_chunks, ignore_index)
    return loss, (x, w, labels, m, s)


def _bwd(n_chunks, ignore_index, res, g):
    x, w, labels, m, s = res
    n, e = x.shape
    v_true = w.shape[0]
    wp, v_pad = _pad_vocab(w, n_chunks)
    vc = v_pad // n_chunks
    wc = wp.reshape(n_chunks, vc, e)
    xf = x.astype(jnp.float32)
    valid = (labels != ignore_index)
    count = jnp.maximum(jnp.sum(valid), 1)
    # ignored rows: zero weight in the mean AND zero softmax gradient
    row_scale = (g / count) * valid.astype(jnp.float32)

    def body(dx, c):
        wcf = wc[c].reshape(vc, e).astype(jnp.float32)
        logits = xf @ wcf.T
        col = c * vc + jnp.arange(vc)
        logits = jnp.where(col[None, :] < v_true, logits, NEG_INF)
        p = jnp.exp(logits - m[:, None]) / s[:, None]
        lid = labels - c * vc
        ok = (lid >= 0) & (lid < vc)
        onehot = (jnp.arange(vc)[None, :] == lid[:, None]) & ok[:, None]
        d = (p - onehot.astype(jnp.float32)) * row_scale[:, None]
        dx = dx + d @ wcf
        dw_c = d.T @ xf  # [Vc, E]
        return dx, dw_c

    dx, dwc = jax.lax.scan(body, jnp.zeros((n, e), jnp.float32),
                           jnp.arange(n_chunks))
    dw = dwc.reshape(v_pad, e)[:v_true]
    return dx.astype(x.dtype), dw.astype(w.dtype), None


chunked_softmax_xent.defvjp(_fwd, _bwd)
