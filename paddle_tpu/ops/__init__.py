"""Unified op namespace + Tensor method patching.

Reference: python/paddle/fluid/dygraph/math_op_patch.py — the reference
monkey-patches arithmetic onto VarBase; we do the same onto Tensor so
`x + y`, `x.mean()`, `x @ w` all route through registered ops (and thus
through autograd + static-graph capture).
"""
from __future__ import annotations

from ._registry import OPS, apply_op, as_jax, defop, raw  # noqa: F401
from .attention import (  # noqa: F401
    fused_feedforward, fused_multi_head_attention,
    paged_decode_attention, ragged_prefill_attention,
    scaled_dot_product_attention,
)
from .control import case, cond, fori_loop, scan, switch_case, while_loop  # noqa: F401
from .creation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .nn_ops import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403

from ..core.tensor import Tensor

# names whose op version shadows a python builtin get aliases
from .math import abs as abs_  # noqa: F401
from .math import max as max_  # noqa: F401
from .math import min as min_  # noqa: F401
from .math import sum as sum_  # noqa: F401


def _patch_tensor():
    import builtins

    from . import linalg, manipulation, math, nn_ops, search

    def binop(fn, reverse=False):
        def method(self, other):
            if reverse:
                return fn(other, self)
            return fn(self, other)
        return method

    T = Tensor
    T.__add__ = binop(math.add)
    T.__radd__ = binop(math.add, True)
    T.__sub__ = binop(math.subtract)
    T.__rsub__ = binop(math.subtract, True)
    T.__mul__ = binop(math.multiply)
    T.__rmul__ = binop(math.multiply, True)
    T.__truediv__ = binop(math.divide)
    T.__rtruediv__ = binop(math.divide, True)
    T.__floordiv__ = binop(math.floor_divide)
    T.__rfloordiv__ = binop(math.floor_divide, True)
    T.__mod__ = binop(math.remainder)
    T.__pow__ = binop(math.pow)
    T.__rpow__ = binop(math.pow, True)
    T.__matmul__ = binop(linalg.matmul)
    T.__rmatmul__ = binop(linalg.matmul, True)
    T.__neg__ = lambda self: math.neg(self)
    T.__abs__ = lambda self: math.abs(self)
    T.__invert__ = lambda self: math.logical_not(self)
    T.__lt__ = binop(math.less_than)
    T.__le__ = binop(math.less_equal)
    T.__gt__ = binop(math.greater_than)
    T.__ge__ = binop(math.greater_equal)
    T.__eq__ = binop(math.equal)
    T.__ne__ = binop(math.not_equal)
    T.__and__ = binop(math.logical_and)
    T.__or__ = binop(math.logical_or)
    T.__xor__ = binop(math.logical_xor)

    def _getitem(self, idx):
        def unwrap_idx(i):
            if isinstance(i, Tensor):
                return i._value
            if isinstance(i, tuple):
                return tuple(unwrap_idx(e) for e in i)
            return i
        return manipulation.getitem(self, unwrap_idx(idx))

    def _setitem(self, idx, value):
        def unwrap_idx(i):
            if isinstance(i, Tensor):
                return i._value
            if isinstance(i, tuple):
                return tuple(unwrap_idx(e) for e in i)
            return i
        out = manipulation.setitem(self, unwrap_idx(idx), value)
        # in-place semantics: replace payload, adopt autograd node
        self._value = out._value
        self._node = out._node
        self.stop_gradient = out.stop_gradient and self.stop_gradient

    T.__getitem__ = _getitem
    T.__setitem__ = _setitem

    # attach op methods (paddle Tensor method surface)
    method_ops = {
        "add": math.add, "subtract": math.subtract, "multiply": math.multiply,
        "divide": math.divide, "pow": math.pow, "matmul": linalg.matmul,
        "mm": linalg.mm, "bmm": linalg.bmm, "dot": linalg.dot,
        "maximum": math.maximum, "minimum": math.minimum, "mod": math.remainder,
        "remainder": math.remainder, "floor_divide": math.floor_divide,
        "abs": math.abs, "exp": math.exp, "log": math.log, "log2": math.log2,
        "log10": math.log10, "log1p": math.log1p, "sqrt": math.sqrt,
        "rsqrt": math.rsqrt, "square": math.square, "reciprocal": math.reciprocal,
        "sin": math.sin, "cos": math.cos, "tan": math.tan, "tanh": math.tanh,
        "asin": math.asin, "acos": math.acos, "atan": math.atan,
        "sinh": math.sinh, "cosh": math.cosh, "erf": math.erf,
        "ceil": math.ceil, "floor": math.floor, "round": math.round,
        "trunc": math.trunc, "sign": math.sign, "clip": math.clip,
        "neg": math.neg, "digamma": math.digamma, "lgamma": math.lgamma,
        "isnan": math.isnan, "isinf": math.isinf, "isfinite": math.isfinite,
        "sum": math.sum, "mean": math.mean, "max": math.max, "min": math.min,
        "prod": math.prod, "all": math.all, "any": math.any, "std": math.std,
        "var": math.var, "logsumexp": math.logsumexp, "cumsum": math.cumsum,
        "cumprod": math.cumprod, "trace": math.trace,
        "equal": math.equal, "not_equal": math.not_equal,
        "less_than": math.less_than, "less_equal": math.less_equal,
        "greater_than": math.greater_than, "greater_equal": math.greater_equal,
        "equal_all": math.equal_all, "allclose": math.allclose,
        "is_complex": math.is_complex,
        "is_floating_point": math.is_floating_point,
        "is_integer": math.is_integer,
        "isclose": math.isclose, "logical_and": math.logical_and,
        "logical_or": math.logical_or, "logical_not": math.logical_not,
        "logical_xor": math.logical_xor, "scale": math.scale,
        "reshape": manipulation.reshape, "transpose": manipulation.transpose,
        "t": manipulation.t, "concat": manipulation.concat,
        "split": manipulation.split, "chunk": manipulation.chunk,
        "squeeze": manipulation.squeeze, "unsqueeze": manipulation.unsqueeze,
        "flatten": manipulation.flatten, "gather": manipulation.gather,
        "gather_nd": manipulation.gather_nd, "scatter": manipulation.scatter,
        "tile": manipulation.tile, "expand": manipulation.expand,
        "expand_as": manipulation.expand_as,
        "broadcast_to": manipulation.broadcast_to, "flip": manipulation.flip,
        "roll": manipulation.roll, "cast": manipulation.cast,
        "index_select": manipulation.index_select,
        "index_sample": manipulation.index_sample,
        "masked_fill": search.masked_fill,
        "masked_select": search.masked_select, "where": manipulation.where,
        "unbind": manipulation.unstack, "repeat_interleave":
            manipulation.repeat_interleave,
        "take_along_axis": manipulation.take_along_axis,
        "put_along_axis": manipulation.put_along_axis,
        "argmax": search.argmax, "argmin": search.argmin,
        "argsort": search.argsort, "sort": search.sort, "topk": search.topk,
        "kthvalue": search.kthvalue, "mode": search.mode,
        "median": search.median, "quantile": search.quantile,
        "nonzero": search.nonzero, "unique": search.unique,
        "norm": linalg.norm, "dist": linalg.dist, "cholesky": linalg.cholesky,
        "inverse": linalg.inverse, "matrix_power": linalg.matrix_power,
        "bincount": linalg.bincount,
        "softmax": nn_ops.softmax, "log_softmax": nn_ops.log_softmax,
        "sigmoid": nn_ops.sigmoid, "relu": nn_ops.relu,
        "tril": tril, "triu": triu, "diag": diag,
        "zero_": None, "fill_": None,
    }
    for name, fn in method_ops.items():
        if fn is None:
            continue
        if not hasattr(T, name):
            setattr(T, name, (lambda f: lambda self, *a, **k: f(self, *a, **k))(fn))


_patch_tensor()
del _patch_tensor
