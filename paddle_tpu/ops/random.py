"""Random sampling ops.

Reference: paddle/fluid/operators/{uniform_random,gaussian_random,randint,
randperm,bernoulli,multinomial,truncated_gaussian_random}_op.*.
All are `stochastic` ops: eager mode draws a key from the global generator
(paddle.seed); jitted/static paths pass `key=` explicitly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ._registry import defop


def _dt(dtype, default="float32"):
    return dtype_mod.convert_dtype(dtype if dtype is not None else default)


@defop(stochastic=True, nondiff=True)
def uniform(shape, dtype=None, min=-1.0, max=1.0, key=None):  # noqa: A002
    return jax.random.uniform(key, tuple(shape), _dt(dtype), min, max)


@defop(stochastic=True, nondiff=True)
def rand(shape, dtype=None, key=None):
    return jax.random.uniform(key, tuple(shape), _dt(dtype))


@defop(stochastic=True, nondiff=True)
def randn(shape, dtype=None, key=None):
    return jax.random.normal(key, tuple(shape), _dt(dtype))


@defop(stochastic=True, nondiff=True)
def normal(mean=0.0, std=1.0, shape=None, key=None):
    base_shape = tuple(shape) if shape is not None else jnp.shape(mean)
    return mean + std * jax.random.normal(key, base_shape, jnp.float32)


gaussian = normal


@defop(stochastic=True, nondiff=True)
def standard_normal(shape, dtype=None, key=None):
    return jax.random.normal(key, tuple(shape), _dt(dtype))


@defop(stochastic=True, nondiff=True)
def randint(low=0, high=None, shape=(1,), dtype="int64", key=None):
    if high is None:
        low, high = 0, low
    return jax.random.randint(key, tuple(shape), low, high, _dt(dtype, "int64"))


@defop(stochastic=True, nondiff=True)
def randint_like(x, low=0, high=None, dtype=None, key=None):
    if high is None:
        low, high = 0, low
    return jax.random.randint(key, x.shape, low, high,
                              _dt(dtype, "int64") if dtype else x.dtype)


@defop(stochastic=True, nondiff=True)
def randperm(n, dtype="int64", key=None):
    return jax.random.permutation(key, n).astype(_dt(dtype, "int64"))


@defop(stochastic=True, nondiff=True)
def bernoulli(x, key=None):
    return jax.random.bernoulli(key, x).astype(jnp.float32)


@defop(stochastic=True, nondiff=True)
def poisson(x, key=None):
    return jax.random.poisson(key, x).astype(jnp.float32)


@defop(stochastic=True, nondiff=True)
def multinomial(x, num_samples=1, replacement=False, key=None):
    logits = jnp.log(jnp.maximum(x, 1e-30))
    if x.ndim == 1:
        logits = logits[None]
    out = jax.random.categorical(key, logits, axis=-1,
                                 shape=(logits.shape[0], num_samples)) \
        if replacement else _sample_without_replacement(key, logits, num_samples)
    return (out[0] if x.ndim == 1 else out).astype(jnp.int64)


def _sample_without_replacement(key, logits, k):
    # Gumbel top-k trick
    g = -jnp.log(-jnp.log(jax.random.uniform(key, logits.shape, minval=1e-20)))
    _, idx = jax.lax.top_k(logits + g, k)
    return idx


@defop(stochastic=True, nondiff=True)
def truncated_normal(shape, mean=0.0, std=1.0, dtype=None, key=None):
    out = jax.random.truncated_normal(key, -2.0, 2.0, tuple(shape), _dt(dtype))
    return out * std + mean


@defop(stochastic=True, nondiff=True)
def uniform_random_like(x, min=-1.0, max=1.0, key=None):  # noqa: A002
    return jax.random.uniform(key, x.shape, x.dtype, min, max)


@defop(stochastic=True, nondiff=True)
def normal_like(x, mean=0.0, std=1.0, key=None):
    return mean + std * jax.random.normal(key, x.shape, x.dtype)


@defop(stochastic=True, nondiff=True)
def shuffle(x, axis=0, key=None):
    return jax.random.permutation(key, x, axis=axis, independent=False)


@defop(stochastic=True, nondiff=True)
def exponential(x, lam=1.0, key=None):
    return jax.random.exponential(key, x.shape, x.dtype) / lam
