"""Tensor creation ops.

Reference: paddle/fluid/operators/fill_constant_op.cc, range_op.cc,
linspace_op.cc, eye_op.cc, tril_triu_op.cc; python/paddle/tensor/creation.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ._registry import defop


def _dt(dtype):
    return dtype_mod.convert_dtype(dtype)


@defop(nondiff=True)
def zeros(shape, dtype=None):
    return jnp.zeros(shape, _dt(dtype))


@defop(nondiff=True)
def ones(shape, dtype=None):
    return jnp.ones(shape, _dt(dtype))


@defop(nondiff=True)
def full(shape, fill_value, dtype=None):
    return jnp.full(shape, fill_value, _dt(dtype) if dtype is not None else None)


@defop()
def zeros_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=_dt(dtype) if dtype else None)


@defop()
def ones_like(x, dtype=None):
    return jnp.ones_like(x, dtype=_dt(dtype) if dtype else None)


@defop()
def full_like(x, fill_value, dtype=None):
    return jnp.full_like(x, fill_value, dtype=_dt(dtype) if dtype else None)


@defop(nondiff=True)
def arange(start=0, end=None, step=1, dtype=None):
    if end is None:
        start, end = 0, start
    return jnp.arange(start, end, step, dtype=_dt(dtype) if dtype else None)


@defop(nondiff=True)
def linspace(start, stop, num, dtype=None):
    return jnp.linspace(start, stop, int(num), dtype=_dt(dtype) if dtype else None)


@defop(nondiff=True)
def logspace(start, stop, num, base=10.0, dtype=None):
    return jnp.logspace(start, stop, int(num), base=base,
                        dtype=_dt(dtype) if dtype else None)


@defop(nondiff=True)
def eye(num_rows, num_columns=None, dtype=None):
    return jnp.eye(num_rows, num_columns, dtype=_dt(dtype))


@defop()
def tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


@defop()
def triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


@defop()
def diag(x, offset=0, padding_value=0):
    if x.ndim == 1 and padding_value != 0:
        d = jnp.diag(x, k=offset)
        mask = jnp.eye(d.shape[0], d.shape[1], k=offset, dtype=bool)
        return jnp.where(mask, d, padding_value)
    return jnp.diag(x, k=offset)


@defop()
def diagflat(x, offset=0):
    return jnp.diagflat(x, k=offset)


@defop()
def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    n = x.shape[-1] + abs(offset)
    base = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    idx = jnp.arange(x.shape[-1])
    r = idx + (-offset if offset < 0 else 0)
    c = idx + (offset if offset > 0 else 0)
    out = base.at[..., r, c].set(x)
    # move the two new dims into position
    nd = out.ndim
    d1, d2 = dim1 % nd, dim2 % nd
    perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
    order = sorted([(d1, nd - 2), (d2, nd - 1)])
    for pos, src in order:
        perm.insert(pos, src)
    return jnp.transpose(out, perm)


@defop()
def meshgrid(*xs):
    # differentiable (ref: paddle.meshgrid backpropagates to its inputs —
    # the grad-autosweep caught the earlier nondiff registration)
    xs = xs[0] if len(xs) == 1 and isinstance(xs[0], (list, tuple)) else xs
    return tuple(jnp.meshgrid(*xs, indexing="ij"))


@defop()
def assign(x):
    return jnp.asarray(x)


@defop()
def clone(x):
    return jnp.asarray(x)


@defop(nondiff=True)
def empty(shape, dtype=None):
    return jnp.zeros(shape, _dt(dtype))


@defop(nondiff=True)
def empty_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=_dt(dtype) if dtype else None)


@defop(nondiff=True)
def complex_(real, imag):
    return jax.lax.complex(real, imag)
