"""Unified ragged paged attention — Pallas TPU kernels over the block
pool, block-table driven (one-kernel serving round, r16).

This module is the MERGE of the former `ragged_prefill.py` and
`paged_attention.py` kernels (both files remain as thin re-export
shims). It holds:

  * the STREAM kernel (`unified_ragged_attention_kernel`) — segment-
    causal attention for a token-packed multi-sequence stream where
    every token attends its OWN sequence's paged-cache positions
    [0, pos].  That one mask generalizes every query shape the serving
    round produces: a prefill chunk (n tokens at positions
    start..start+n-1), a plain decode row (1 token at its write
    position) and a speculative verify region ([last_token,
    draft_1..k]) are all just ragged segments of the same stream, so a
    scheduler round mixing all three is ONE launch of this kernel;
  * the DECODE kernel (`paged_decode_attention_kernel`) — the
    one-token-per-sequence specialization (grid (B, M), heads on the
    sublane axis) kept for the standalone `step`/offline paths, which
    skips the stream kernel's query-tile alignment cost when every
    sequence contributes exactly one token.

Shared machinery (deduplicated here — the per-kernel copies are gone):

  * `kv_operand_specs` — the scalar-prefetched block-index BlockSpec
    construction: the k/v (and int8 scale) index maps read
    `tables[row, m]` from a prefetched table, so the pipeline DMAs
    exactly the pool blocks each query's sequence names and never
    materializes the [.., M*BS, ...] gather copy the XLA fallback
    builds.  Scale tiles ride the SAME prefetched index as their
    codes.
  * `_load_kv` — the int8-KV dequant (quantized-serving round): pools
    may be `QuantizedKV` (codes [N, BS, H, Dh] int8 + per-vector
    scales [N, BS, H]); dequantization happens HERE on the
    VMEM-resident block in flight, so a bf16 copy of the cache never
    exists in HBM.
  * one online-softmax kernel body per query geometry instead of the
    former dense/quant copy-pair per file (4 kernel bodies -> 2).

Layout (matches inference/kv_cache.py):
    q:        [T, H, Dh] stream / [B, H, Dh] decode
    k_blocks: [N, BS, H, Dh]             one layer's pool
    tables:   [B, M] int32               block ids, 0-padded (trash)
    tile_seg: [T // QT] int32            slot row of each query tile
    tile_pos: [T // QT] int32            abs cache position of each
                                         tile's first token; -1 = pad
    ctx_lens: [B] int32                  decode: tokens visible per row

Stream packing contract: the scheduler aligns every segment's packed
region to the QT=128 query tile, so ONE tile never mixes segments —
that keeps the grid a plain (num_q_tiles, M) with the per-tile segment
and start position scalar-prefetched.  KV blocks past a tile's causal
horizon (and pad tiles) still occupy grid steps but are predicated
off — raggedness saves the gather traffic and the compute, not the
grid iterations.

Segment-causal masking contract (normative for every implementation of
stream attention, not just this kernel): a query row carrying
(seg, pos) attends exactly the keys of ITS segment at positions
0 <= kpos <= pos — resident paged-cache positions and fresh stream
rows alike — and pad rows (pos == -1) attend nothing.  The XLA
fallback (`ops.attention.ragged_prefill_attention`) and the
sequence-parallel seams (`serving_dist.sp_attention` ring/ulysses,
where per-row seg/pos metadata must SURVIVE block rotation so
cross-shard causality stays exact) implement this same contract and
are parity-tested against each other.

Per (tile, kv-block) step the score tile is [H, QT, BS] from a
head-batched dot over Dh; online-softmax state (m, l, acc) rides VMEM
scratch across the M dimension exactly like flash_attention.py, with
the extra QT query axis on the lanes (decode: QT folded away, row
stats broadcast over STAT_LANES for (8, 128) tiling).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_TPU_PALLAS = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_TPU_PALLAS = False

NEG_INF = -1e30
Q_TILE = 128    # stream query-tile (and packing alignment) size
STAT_LANES = 8  # decode m/l row stats broadcast for (8, 128) tiling


def supported_shapes(head_dim, block_size, num_heads, total_tokens=None):
    """Shape gate for the compiled TPU kernels (interpret mode takes
    any): head_dim lane-sized, block_size a lane multiple, heads
    sublane-aligned; the stream kernel additionally requires the packed
    length to be query-tile aligned."""
    ok = (head_dim in (32, 64, 128, 256) and block_size % 128 == 0
          and num_heads % 8 == 0)
    if total_tokens is not None:
        ok = ok and total_tokens % Q_TILE == 0
    return ok


def is_quantized(kv):
    """Duck-typed inference.kv_quant.QuantizedKV check (no import — the
    kernel layer must not pull the inference package)."""
    return hasattr(kv, "codes") and hasattr(kv, "scales")


def kv_operand_specs(BS, H, Dh, quant, block_id):
    """The ONE scalar-prefetched block-index construction both kernels
    steer their DMA pipeline with (formerly copy-pasted per kernel):
    `block_id(*grid_and_prefetch_refs) -> pool block` feeds the k/v
    BlockSpec index maps, and for int8 pools the per-vector scale tiles
    ride the SAME index as their codes.  Returns the in_specs list for
    (k[, ks], v[, vs])."""
    kv = pl.BlockSpec((1, BS, H, Dh),
                      lambda *a: (block_id(*a), 0, 0, 0))
    if not quant:
        return [kv, kv]
    sc = pl.BlockSpec((1, BS, H), lambda *a: (block_id(*a), 0, 0))
    return [kv, sc, kv, sc]


def kv_operands(k_blocks, v_blocks):
    """(quant, operand tuple) for a dense or QuantizedKV pool pair —
    the argument-flattening half of `kv_operand_specs`."""
    if is_quantized(k_blocks):
        return True, (k_blocks.codes, k_blocks.scales,
                      v_blocks.codes, v_blocks.scales)
    return False, (k_blocks, v_blocks)


def _load_kv(ref, sref, dt):
    """One pool block from VMEM, dequantized in place when the pool is
    int8 (codes * per-vector scales — elementwise, lane-layout
    friendly).  The int8->dt convert happens on the ONE block in
    flight; no bf16 cache copy ever exists in HBM."""
    x = ref[0]
    if sref is None:
        return x
    return x.astype(dt) * sref[0][..., None].astype(dt)


# ---- stream kernel (prefill chunks / decode rows / verify regions) ----

def _stream_kernel(tile_seg_ref, tile_pos_ref, tables_ref, q_ref,
                   *refs, scale, nm, qt, quant, tile_base):
    if quant:
        k_ref, ks_ref, v_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = refs
    else:
        k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
        ks_ref = vs_ref = None
    qi = pl.program_id(0)
    mi = pl.program_id(1)

    @pl.when(mi == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # abs position of the tile's first query (-1 pad); tile_base shifts
    # a shard-local grid into the GLOBAL prefetch arrays (sp shards)
    q0 = tile_pos_ref[qi + tile_base]
    bs = k_ref.shape[1]

    # a kv block matters iff it starts at or before the tile's LAST
    # query's causal horizon; pad tiles (q0 < 0) skip every block
    @pl.when((q0 >= 0) & (mi * bs <= q0 + qt - 1))
    def _compute():
        q = q_ref[:]  # [H, QT, Dh] — input dtype feeds the MXU full-rate
        k = _load_kv(k_ref, ks_ref, q.dtype)  # [BS, H, Dh]
        v = _load_kv(v_ref, vs_ref, q.dtype)
        # s[h, i, j] = sum_d q[h, i, d] * k[j, h, d]: batch over heads
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * scale  # [H, QT, BS]
        row = q0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        col = mi * bs + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(col <= row, s, NEG_INF)  # segment-causal by abs pos
        m_prev = m_ref[:]                       # [H, QT]
        l_prev = l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2))
        p = jnp.exp(s - m_new[:, :, None])
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_prev * alpha + jnp.sum(p, axis=2)
        # o[h, i, d] += sum_j p[h, i, j] * v[j, h, d]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)  # [H, QT, Dh]
        acc_ref[:] = acc_ref[:] * alpha[:, :, None] + pv
        m_ref[:] = m_new

    @pl.when(mi == nm - 1)
    def _flush():
        l = jnp.maximum(l_ref[:], 1e-30)  # pad tiles flush zeros
        o_ref[:] = (acc_ref[:] / l[:, :, None]).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("scale", "q_tile", "interpret",
                                    "tile_base"))
def unified_ragged_attention_kernel(q, k_blocks, v_blocks, tables,
                                    tile_seg, tile_pos, *, scale=None,
                                    q_tile=None, interpret=False,
                                    tile_base=0):
    """Pallas segment-causal stream attention: ONE launch scores a
    token-packed stream mixing prefill chunks, plain decode rows and
    speculative verify regions (see module docstring for the layout
    and packing contract); returns [T, H, Dh] in q's dtype.
    k_blocks/v_blocks may be `QuantizedKV` (codes [N, BS, H, Dh] int8,
    scales [N, BS, H]) — the scale tiles ride the same
    scalar-prefetched block index as their codes and dequant happens
    in VMEM (`_load_kv`).  q_tile defaults to the production
    Q_TILE=128 (interpret-mode tests shrink it to exercise tiny
    shapes).

    tile_base (long-context round): static tile offset into the
    scalar-prefetched tile_seg/tile_pos arrays — a SEQUENCE-PARALLEL
    shard holding tiles [base, base + T_local/QT) of a global packed
    stream passes its LOCAL q slice with the GLOBAL prefetch arrays
    and tile_base=base, and the block-index maps (`tb[ts[qi+base], m]`)
    DMA exactly the pool blocks the shard's own tiles name.  0 (the
    default) is the exact pre-round single-stream kernel."""
    quant, operands = kv_operands(k_blocks, v_blocks)
    qt = Q_TILE if q_tile is None else int(q_tile)
    tile_base = int(tile_base)
    T, H, Dh = q.shape
    _, BS, _, _ = operands[0].shape
    M = tables.shape[1]
    if T % qt:
        raise ValueError(f"packed length {T} not a multiple of the "
                         f"query tile {qt}")
    NQ = T // qt
    if tile_base < 0 or tile_base + NQ > tile_seg.shape[0]:
        raise ValueError(
            f"tile_base {tile_base} + local tiles {NQ} exceeds the "
            f"global tile arrays ({tile_seg.shape[0]} tiles)")
    scale = (Dh ** -0.5) if scale is None else float(scale)

    qh = q.transpose(1, 0, 2)  # [H, T, Dh]: heads ride the sublane axis
    q_spec = pl.BlockSpec((H, qt, Dh),
                          lambda qi, m, ts, tp, tb: (0, qi, 0))
    in_specs = [q_spec] + kv_operand_specs(
        BS, H, Dh, quant,
        lambda qi, m, ts, tp, tb: tb[ts[qi + tile_base], m])
    kernel = functools.partial(_stream_kernel, scale=scale, nm=M,
                               qt=qt, quant=quant, tile_base=tile_base)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # tile_seg, tile_pos, tables steer the DMA
        grid=(NQ, M),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((H, qt, Dh),
                               lambda qi, m, ts, tp, tb: (0, qi, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, qt, Dh), jnp.float32),
            pltpu.VMEM((H, qt), jnp.float32),
            pltpu.VMEM((H, qt), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((H, T, Dh), q.dtype),
        interpret=interpret,
    )(tile_seg.astype(jnp.int32), tile_pos.astype(jnp.int32),
      tables.astype(jnp.int32), qh, *operands)
    return out.transpose(1, 0, 2)


# ---- decode kernel (one token per sequence) ---------------------------

def _decode_kernel(tables_ref, lens_ref, q_ref, *refs, scale, nm, quant):
    if quant:
        k_ref, ks_ref, v_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = refs
    else:
        k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    mi = pl.program_id(1)

    @pl.when(mi == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    ctx = lens_ref[b]
    bs = k_ref.shape[1]

    @pl.when(mi * bs < ctx)
    def _compute():
        q = q_ref[0]  # [H, Dh] — input dtype feeds the MXU at full rate
        k = _load_kv(k_ref, ks_ref, q.dtype)  # [BS, H, Dh]
        v = _load_kv(v_ref, vs_ref, q.dtype)
        # s[h, t] = sum_d q[h, d] * k[t, h, d]: batch over heads
        s = jax.lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * scale  # [H, BS]
        pos = mi * bs + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos < ctx, s, NEG_INF)
        m_prev = m_ref[:, 0:1]
        l_prev = l_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        # o[h, d] += sum_t p[h, t] * v[t, h, d]: same head-batched form
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)  # [H, Dh]
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(mi == nm - 1)
    def _flush():
        l = jnp.maximum(l_ref[:, 0:1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("scale", "interpret"))
def paged_decode_attention_kernel(q, k_blocks, v_blocks, tables, ctx_lens,
                                  *, scale=None, interpret=False):
    """Pallas ragged paged decode attention — the one-token-per-sequence
    specialization of the stream kernel (grid (B, M), no query-tile
    alignment cost).  Returns [B, H, Dh] in q's dtype; QuantizedKV
    pools dequantize in VMEM exactly like the stream kernel."""
    quant, operands = kv_operands(k_blocks, v_blocks)
    B, H, Dh = q.shape
    _, BS, _, _ = operands[0].shape
    M = tables.shape[1]
    scale = (Dh ** -0.5) if scale is None else float(scale)

    q_spec = pl.BlockSpec((1, H, Dh), lambda b, m, tab, cl: (b, 0, 0))
    in_specs = [q_spec] + kv_operand_specs(
        BS, H, Dh, quant, lambda b, m, tab, cl: tab[b, m])
    kernel = functools.partial(_decode_kernel, scale=scale, nm=M,
                               quant=quant)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # tables, ctx_lens steer the DMA pipeline
        grid=(B, M),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, H, Dh), lambda b, m, tab, cl: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, Dh), jnp.float32),
            pltpu.VMEM((H, STAT_LANES), jnp.float32),
            pltpu.VMEM((H, STAT_LANES), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, Dh), q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), ctx_lens.astype(jnp.int32), q, *operands)
