"""Pallas TPU kernels (flash attention, fused norms)."""
