"""Flash attention — Pallas TPU kernel.

Replaces the reference's fused_attention CUDA op (north-star: "fused_attention
→ Pallas flash-attn"). Blockwise online-softmax: each grid step owns one Q
block in VMEM, streams K/V blocks from VMEM, and accumulates on the MXU in
f32 (inputs stay bf16 — the MXU multiplies bf16 natively and accumulates f32
via preferred_element_type; casting inputs to f32 would quarter the MXU rate
and double VMEM traffic). O(S) memory instead of the O(S²) score matrix.

Forward emits the per-row LSE so the backward (also Pallas) can recompute
probabilities blockwise without a second softmax pass — the standard
flash-attention training recipe (dq kernel + dkv kernel, delta = rowsum(dO·O)).
"""
from __future__ import annotations

import functools
import os as _os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_TPU_PALLAS = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_TPU_PALLAS = False

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
NEG_INF = -1e30


def _compiler_params(semantics):
    if not _HAS_TPU_PALLAS:
        return {}
    cls = getattr(pltpu, "CompilerParams", None) or \
        getattr(pltpu, "TPUCompilerParams", None)
    if cls is None:
        return {}
    try:
        return {"compiler_params": cls(dimension_semantics=semantics)}
    except Exception:
        return {}


LSE_LANES = 8  # lse/delta rows are broadcast over 8 sublanes to satisfy
               # the TPU (8, 128)-tile layout for non-vector shapes


def _fwd_kernel(q_ref, k_ref, v_ref, *refs, scale, causal, nk,
                has_bias=False):
    if has_bias:
        bias_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
    else:
        o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
        bias_ref = None
    # Streaming layout: grid = (b*h, nq, nk), K/V blocks arrive one per grid
    # step on the innermost ("arbitrary") dim — nothing larger than a block
    # is ever resident in VMEM, so sequence length is unbounded. Online
    # softmax state (acc, m, l) is carried in VMEM scratch across k steps.
    # q_ref: [bq, d]; k_ref/v_ref: [bk, d]; lse_ref: [bq, LSE_LANES].
    bq, d = q_ref.shape
    bk = k_ref.shape[0]
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: tiles strictly above the diagonal contribute nothing; tiles
    # strictly below need no mask — only diagonal-straddling tiles pay for
    # the iota+select (at S=1024/b=512 that's 2 of every 3 executed tiles,
    # at long S a vanishing fraction)
    run = (ki * bk < (qi + 1) * bq) if causal else (ki >= 0)
    diag = ((ki + 1) * bk > qi * bq) if causal else False

    def _compute(apply_mask):
        q = q_ref[:]  # keep input dtype — bf16 feeds the MXU at full rate
        k = k_ref[:]
        v = v_ref[:]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if scale != 1.0:
            s = s * scale
        if bias_ref is not None:
            # per-key additive bias (padding masks, ALiBi-style): one
            # [8, bk] sublane-broadcast tile per k block (TPU blocks need
            # 8x128-aligned shapes); row 0 broadcasts over the q rows
            s = s + bias_ref[0:1, :].astype(jnp.float32)
        if apply_mask:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_prev = m_ref[:, 0:1]
        l_prev = l_ref[:, 0:1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        @pl.when(run & diag)
        def _compute_diag():
            _compute(True)

        @pl.when(run & jnp.logical_not(diag))
        def _compute_full():
            _compute(False)
    else:
        @pl.when(run)
        def _compute_all():
            _compute(False)

    @pl.when(ki == nk - 1)
    def _flush():
        l = jnp.maximum(l_ref[:, 0:1], 1e-30)
        o_ref[:] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse_ref[:] = jnp.broadcast_to(m_ref[:, 0:1] + jnp.log(l),
                                      lse_ref.shape)


def _fwd_kernel_lanes(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                      l_ref, *, scale, causal, nk):
    """Forward variant with LANE-REPLICATED online-softmax state: m/l live as
    [bq, 128] registers holding the row statistic in every lane (the stock
    TPU kernel's layout), so the `s - m` / `acc * alpha` broadcasts are
    register tiles instead of cross-lane broadcasts from a [bq, 1] slice.
    Opt-in via PADDLE_TPU_FA_LANES=1 for on-chip A/B; requires bk % 128 == 0
    and d <= 128 (the default 512/64 config qualifies)."""
    bq, d = q_ref.shape
    bk = k_ref.shape[0]
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    run = (ki * bk < (qi + 1) * bq) if causal else (ki >= 0)
    diag = ((ki + 1) * bk > qi * bq) if causal else False

    def _compute(apply_mask):
        q = q_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if scale != 1.0:
            s = s * scale
        if apply_mask:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_prev = m_ref[:]                      # [bq, 128] replicated
        l_prev = l_ref[:]
        m_cur = jnp.max(s, axis=1)[:, None]    # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)     # [bq, 128] replicated
        p = jnp.exp(s - jnp.tile(m_new, (1, bk // 128)))
        alpha = jnp.exp(m_prev - m_new)        # [bq, 128]
        l_ref[:] = l_prev * alpha + jnp.sum(p, axis=1)[:, None]
        acc_ref[:] = acc_ref[:] * alpha[:, :d] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    if causal:
        @pl.when(run & diag)
        def _compute_diag():
            _compute(True)

        @pl.when(run & jnp.logical_not(diag))
        def _compute_full():
            _compute(False)
    else:
        @pl.when(run)
        def _compute_all():
            _compute(False)

    @pl.when(ki == nk - 1)
    def _flush():
        l = jnp.maximum(l_ref[:], 1e-30)       # [bq, 128] replicated
        o_ref[:] = (acc_ref[:] / l[:, :d]).astype(o_ref.dtype)
        lse_ref[:] = (m_ref[:, :LSE_LANES] +
                      jnp.log(l[:, :LSE_LANES]))


_FA_LANES = _os.environ.get("PADDLE_TPU_FA_LANES") == "1"


def _divisor_block(size, block):
    """Largest block <= `block` that divides `size` — 128-aligned when
    possible (TPU lane width); sub-128 blocks only appear in interpret-mode
    tests with tiny shapes."""
    b = min(block, size)
    if b >= 128 and size % 128 == 0:
        b -= b % 128
        while size % b:
            b -= 128
    else:
        while size % b:
            b -= 1
    return b


def _block_sizes(sq, sk, block_q, block_k):
    bq = _divisor_block(sq, block_q)
    bk = _divisor_block(sk, block_k)
    # keep the f32 score block under ~2MB of VMEM (only binds when a caller
    # passes blocks larger than the 512 defaults)
    while bq > 128 and bq * bk * 4 > 2 * 1024 * 1024:
        bq = _divisor_block(sq, bq // 2)
    return bq, bk


def _flash_fwd_lse(q, k, v, scale, causal, block_q, block_k, interpret,
                   bias=None):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq, bk = _block_sizes(sq, sk, block_q, block_k)
    q3 = q.reshape(b * h, sq, d)
    k3 = k.reshape(b * h, sk, d)
    v3 = v.reshape(b * h, sk, d)
    nk = sk // bk
    grid = (b * h, sq // bq, nk)
    has_bias = bias is not None
    use_lanes = _FA_LANES and bk % 128 == 0 and d <= 128 and not has_bias
    kernel = functools.partial(
        _fwd_kernel_lanes if use_lanes else _fwd_kernel,
        scale=scale, causal=causal, nk=nk)
    if not use_lanes:
        kernel = functools.partial(kernel, has_bias=has_bias)
    ml_lanes = 128 if use_lanes else LSE_LANES
    mem_kwargs = {}
    if _HAS_TPU_PALLAS and not interpret:
        mem_kwargs = {"memory_space": pltpu.VMEM}
    in_specs = [
        pl.BlockSpec((None, bq, d), lambda i, j, kk: (i, j, 0),
                     **mem_kwargs),
        pl.BlockSpec((None, bk, d), lambda i, j, kk: (i, kk, 0),
                     **mem_kwargs),
        pl.BlockSpec((None, bk, d), lambda i, j, kk: (i, kk, 0),
                     **mem_kwargs),
    ]
    operands = [q3, k3, v3]
    if has_bias:
        # per-key additive bias, pre-tiled to [b*h, sk] f32
        in_specs.append(pl.BlockSpec((None, 8, bk),
                                     lambda i, j, kk: (i, 0, kk),
                                     **mem_kwargs))
        operands.append(bias)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
                   jax.ShapeDtypeStruct((b * h, sq, LSE_LANES), jnp.float32)),
        grid=grid,
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((None, bq, d), lambda i, j, kk: (i, j, 0),
                         **mem_kwargs),
            pl.BlockSpec((None, bq, LSE_LANES), lambda i, j, kk: (i, j, 0),
                         **mem_kwargs),
        ),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32),
                        pltpu.VMEM((bq, ml_lanes), jnp.float32),
                        pltpu.VMEM((bq, ml_lanes), jnp.float32)],
        interpret=interpret,
        **_compiler_params(("parallel", "parallel", "arbitrary")),
    )(*operands)
    return out.reshape(b, h, sq, d), lse


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
                   scale, causal, nk, has_bias=False):
    if has_bias:
        bias_ref, dq_ref, dq_acc = refs
    else:
        dq_ref, dq_acc = refs
        bias_ref = None
    # Streaming: grid = (b*h, nq, nk); dq_i = scale * sum_j ds_ij @ k_j
    # accumulated in VMEM scratch across the k steps, flushed on the last.
    bq, d = q_ref.shape
    bk = k_ref.shape[0]
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = (ki * bk < (qi + 1) * bq) if causal else (ki >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[:]
        do = do_ref[:]
        lse = lse_ref[:, 0:1]
        delta = delta_ref[:, 0:1]
        k = k_ref[:]
        v = v_ref[:]
        p, ds = _tile_p_ds(q, k, v, do, lse, delta, scale, causal,
                           qi * bq, ki * bk,
                           None if bias_ref is None else bias_ref[:])
        dq_acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _flush():
        acc = dq_acc[:] * scale if scale != 1.0 else dq_acc[:]
        dq_ref[:] = acc.astype(dq_ref.dtype)


def _tile_p_ds(q, k, v, do, lse, delta, scale, causal, q_pos0, k_pos0,
               bias=None):
    """Shared backward tile math: recompute probabilities from the stored LSE
    and form ds = p * (dO·v^T - delta). Used by all three backward kernels so
    masking/lse/dtype fixes land in exactly one place. Returns (p, ds) with
    p in the dO dtype and ds in the k dtype (MXU-ready)."""
    bq, bk = q.shape[0], k.shape[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if scale != 1.0:
        s = s * scale
    if bias is not None:
        s = s + bias[0:1, :].astype(jnp.float32)
    if causal:
        q_pos = q_pos0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_pos = k_pos0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    p = jnp.exp(s - lse)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = (p * (dp - delta)).astype(k.dtype)
    return p.astype(do.dtype), ds


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
                    scale, causal, nq, has_bias=False):
    if has_bias:
        (bias_ref, dk_ref, dv_ref, dbias_ref,
         dk_acc, dv_acc, db_acc) = refs
    else:
        dk_ref, dv_ref, dk_acc, dv_acc = refs
        bias_ref = dbias_ref = db_acc = None
    # Streaming: grid = (b*h, nk, nq); Q/dO blocks arrive on the innermost
    # dim; dk_j / dv_j accumulate in VMEM scratch, flushed on the last step.
    bk, d = k_ref.shape
    bq = q_ref.shape[0]
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)
        if db_acc is not None:
            db_acc[:] = jnp.zeros_like(db_acc)

    # causal: q blocks strictly before the diagonal see nothing of this k blk
    run = ((qi + 1) * bq > ki * bk) if causal else (qi >= 0)

    @pl.when(run)
    def _compute():
        k = k_ref[:]
        v = v_ref[:]
        q = q_ref[:]
        do = do_ref[:]
        lse = lse_ref[:, 0:1]
        delta = delta_ref[:, 0:1]
        p, ds = _tile_p_ds(q, k, v, do, lse, delta, scale, causal,
                           qi * bq, ki * bk,
                           None if bias_ref is None else bias_ref[:])
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if db_acc is not None:
            # dL/dbias_k = sum over q rows of ds (bias enters s additively,
            # after the scale) — accumulated across streamed q blocks
            col = jnp.sum(ds.astype(jnp.float32), axis=0)
            db_acc[:] += jnp.broadcast_to(col[None, :], db_acc.shape)

    @pl.when(qi == nq - 1)
    def _flush():
        acc = dk_acc[:] * scale if scale != 1.0 else dk_acc[:]
        dk_ref[:] = acc.astype(dk_ref.dtype)
        dv_ref[:] = dv_acc[:].astype(dv_ref.dtype)
        if db_acc is not None:
            dbias_ref[:] = db_acc[:]


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      *refs, scale, causal, block_q, sq, nk,
                      has_bias=False):
    if has_bias:
        bias_ref, dq_ref, dk_ref, dv_ref, dbias_ref, dq_acc = refs
    else:
        dq_ref, dk_ref, dv_ref, dq_acc = refs
        bias_ref = dbias_ref = None
    """One-pass backward: grid over k-blocks (sequential per (b,h) row), q
    streamed inside. Computes p = exp(s - lse) ONCE per (i,j) tile and feeds
    all three grads: dv_j += p^T dO_i, dk_j += ds^T q_i, and dq_i accumulated
    across j in a VMEM scratch flushed on the last k-block. Versus separate
    dq/dkv kernels this halves the exp work and drops two of seven dots."""
    bk, d = k_ref.shape
    ki = pl.program_id(1)
    k = k_ref[:]
    v = v_ref[:]

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    nq = sq // block_q
    first_q = (ki * bk) // block_q if causal else 0

    def body(i, carry):
        dk_acc, dv_acc, db_acc = carry
        q = q_ref[pl.ds(i * block_q, block_q), :]
        do = do_ref[pl.ds(i * block_q, block_q), :]
        lse = lse_ref[pl.ds(i * block_q, block_q), 0:1]
        delta = delta_ref[pl.ds(i * block_q, block_q), 0:1]
        p, ds = _tile_p_ds(q, k, v, do, lse, delta, scale, causal,
                           i * block_q, ki * bk,
                           None if bias_ref is None else bias_ref[:])
        dv_acc = dv_acc + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc = dk_acc + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dbias_ref is not None:
            col = jnp.sum(ds.astype(jnp.float32), axis=0, keepdims=True)
            db_acc = db_acc + jnp.broadcast_to(col, db_acc.shape)
        dq_tile = jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dq_acc[pl.ds(i * block_q, block_q), :] += dq_tile
        return dk_acc, dv_acc, db_acc

    z = jnp.zeros((bk, d), jnp.float32)
    zb = jnp.zeros((8, bk), jnp.float32)
    dk_acc, dv_acc, db_acc = jax.lax.fori_loop(first_q, nq, body, (z, z, zb))
    dk_ref[:] = ((dk_acc * scale) if scale != 1.0 else dk_acc) \
        .astype(dk_ref.dtype)
    dv_ref[:] = dv_acc.astype(dv_ref.dtype)
    if dbias_ref is not None:
        # dL/dbias for this k block: sum of ds over all q rows
        dbias_ref[:] = db_acc

    @pl.when(ki == nk - 1)
    def _flush():
        acc = dq_acc[:] * scale if scale != 1.0 else dq_acc[:]
        dq_ref[:] = acc.astype(dq_ref.dtype)


def _delta_kernel(o_ref, do_ref, delta_ref):
    # delta = rowsum(dO * O), written pre-broadcast over LSE_LANES. Doing
    # this in Pallas instead of XLA matters: the minor-axis (d=64) reduce
    # plus the 8-lane broadcast measured 1.26ms/layer at GPT-2-small batch
    # 16 as an XLA fusion (~5x over the bandwidth bound, r4 per-op
    # profile); here it is one streaming pass at copy speed.
    d = jnp.sum(o_ref[...].astype(jnp.float32) *
                do_ref[...].astype(jnp.float32), axis=1, keepdims=True)
    delta_ref[...] = jnp.broadcast_to(d, (d.shape[0], LSE_LANES))


def _delta_rows(o3, do3, interpret):
    """[b*h, sq, d] x2 -> broadcast delta [b*h, sq, LSE_LANES] f32."""
    bh, sq, d = o3.shape
    bq = next((b for b in (512, 256, 128) if sq % b == 0), sq)
    mem_kwargs = {}
    if _HAS_TPU_PALLAS and not interpret:
        mem_kwargs = {"memory_space": pltpu.VMEM}
    row = pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0), **mem_kwargs)
    out = pl.BlockSpec((None, bq, LSE_LANES), lambda i, j: (i, j, 0),
                       **mem_kwargs)
    return pl.pallas_call(
        _delta_kernel,
        out_shape=jax.ShapeDtypeStruct((bh, sq, LSE_LANES), jnp.float32),
        grid=(bh, sq // bq),
        in_specs=[row, row],
        out_specs=out,
        interpret=interpret,
        **_compiler_params(("parallel", "arbitrary")),
    )(o3, do3)


def _flash_bwd_fused(q, k, v, o, lse, g, scale, causal, block_q, block_k,
                     interpret, bias=None):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq, bk = _block_sizes(sq, sk, block_q, block_k)
    q3, k3, v3 = (x.reshape(b * h, x.shape[2], d) for x in (q, k, v))
    do3 = g.reshape(b * h, sq, d)
    delta3 = _delta_rows(o.reshape(b * h, sq, d), do3, interpret)
    mem_kwargs = {}
    if _HAS_TPU_PALLAS and not interpret:
        mem_kwargs = {"memory_space": pltpu.VMEM}
    scratch = [pltpu.VMEM((sq, d), jnp.float32)]

    qfull = pl.BlockSpec((None, sq, d), lambda i, j: (i, 0, 0), **mem_kwargs)
    kcol = pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0), **mem_kwargs)
    vec_full = pl.BlockSpec((None, sq, LSE_LANES), lambda i, j: (i, 0, 0),
                            **mem_kwargs)
    in_specs = [qfull, kcol, kcol, qfull, vec_full, vec_full]
    operands = [q3, k3, v3, do3, lse, delta3]
    out_shape = [jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
                 jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
                 jax.ShapeDtypeStruct((b * h, sk, d), v.dtype)]
    biascol = pl.BlockSpec((None, 8, bk), lambda i, j: (i, 0, j),
                           **mem_kwargs)
    out_specs = [qfull, kcol, kcol]
    if bias is not None:
        in_specs.append(biascol)
        operands.append(bias)
        out_shape.append(jax.ShapeDtypeStruct((b * h, 8, sk), jnp.float32))
        out_specs.append(biascol)
    outs = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, scale=scale, causal=causal,
                          block_q=bq, sq=sq, nk=sk // bk,
                          has_bias=bias is not None),
        out_shape=tuple(out_shape),
        grid=(b * h, sk // bk),
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        scratch_shapes=scratch,
        interpret=interpret,
        **_compiler_params(("parallel", "arbitrary")),
    )(*operands)
    dq, dk, dv = outs[:3]
    dbias3 = outs[3] if bias is not None else None
    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d), dbias3)


def _flash_bwd(q, k, v, o, lse, g, scale, causal, block_q, block_k,
               interpret, bias=None):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq, bk = _block_sizes(sq, sk, block_q, block_k)
    q3, k3, v3 = (x.reshape(b * h, x.shape[2], d) for x in (q, k, v))
    do3 = g.reshape(b * h, sq, d)
    lse3 = lse  # already [b*h, sq, LSE_LANES]
    delta3 = _delta_rows(o.reshape(b * h, sq, d), do3, interpret)
    mem_kwargs = {}
    if _HAS_TPU_PALLAS and not interpret:
        mem_kwargs = {"memory_space": pltpu.VMEM}

    nq, nk = sq // bq, sk // bk
    # dq pass: grid (bh, nq, nk) — q row pinned per j, k/v streamed on kk
    qrow = pl.BlockSpec((None, bq, d), lambda i, j, kk: (i, j, 0),
                        **mem_kwargs)
    kstream = pl.BlockSpec((None, bk, d), lambda i, j, kk: (i, kk, 0),
                           **mem_kwargs)
    vec_row = pl.BlockSpec((None, bq, LSE_LANES), lambda i, j, kk: (i, j, 0),
                           **mem_kwargs)
    dq_specs = [qrow, kstream, kstream, qrow, vec_row, vec_row]
    dq_ops = [q3, k3, v3, do3, lse3, delta3]
    if bias is not None:
        dq_specs.append(pl.BlockSpec((None, 8, bk),
                                      lambda i, j, kk: (i, 0, kk),
                                      **mem_kwargs))
        dq_ops.append(bias)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal, nk=nk,
                          has_bias=bias is not None),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        grid=(b * h, nq, nk),
        in_specs=dq_specs,
        out_specs=qrow,
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
        **_compiler_params(("parallel", "parallel", "arbitrary")),
    )(*dq_ops)

    # dkv pass: grid (bh, nk, nq) — k/v column pinned per j, q/dO streamed
    kcol = pl.BlockSpec((None, bk, d), lambda i, j, qq: (i, j, 0),
                        **mem_kwargs)
    qstream = pl.BlockSpec((None, bq, d), lambda i, j, qq: (i, qq, 0),
                           **mem_kwargs)
    vec_stream = pl.BlockSpec((None, bq, LSE_LANES),
                              lambda i, j, qq: (i, qq, 0), **mem_kwargs)
    dkv_specs = [qstream, kcol, kcol, qstream, vec_stream, vec_stream]
    dkv_ops = [q3, k3, v3, do3, lse3, delta3]
    dkv_out_shape = [jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
                     jax.ShapeDtypeStruct((b * h, sk, d), v.dtype)]
    dkv_out_specs = [kcol, kcol]
    dkv_scratch = [pltpu.VMEM((bk, d), jnp.float32),
                   pltpu.VMEM((bk, d), jnp.float32)]
    if bias is not None:
        biascol = pl.BlockSpec((None, 8, bk), lambda i, j, qq: (i, 0, j),
                               **mem_kwargs)
        dkv_specs.append(biascol)
        dkv_ops.append(bias)
        dkv_out_shape.append(
            jax.ShapeDtypeStruct((b * h, 8, sk), jnp.float32))
        dkv_out_specs.append(biascol)
        dkv_scratch.append(pltpu.VMEM((8, bk), jnp.float32))
    outs = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal, nq=nq,
                          has_bias=bias is not None),
        out_shape=tuple(dkv_out_shape),
        grid=(b * h, nk, nq),
        in_specs=dkv_specs,
        out_specs=tuple(dkv_out_specs),
        scratch_shapes=dkv_scratch,
        interpret=interpret,
        **_compiler_params(("parallel", "parallel", "arbitrary")),
    )(*dkv_ops)
    dk, dv = outs[:2]
    dbias3 = outs[2] if bias is not None else None

    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d), dbias3)


def _reference_attention(q, k, v, scale, causal):
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal=False, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    interpret=False):
    """q,k,v: [B,H,S,D]. S must be a multiple of 128."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    out, _ = _flash_fwd_lse(q, k, v, scale, causal, block_q, block_k,
                            interpret)
    return out


def _fa_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    out, lse = _flash_fwd_lse(q, k, v, scale, causal, block_q, block_k,
                              interpret)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    if scale is None:
        scale = q.shape[-1] ** -0.5
    # Fused single-pass backward VMEM residency per (b,h) grid row:
    # dq_acc scratch (sq*d f32) + q, dO inputs and dq output window
    # (sq*d bf16 each) + lse/delta (~sq*8 f32 each) + double-buffered
    # k/v/dk/dv column blocks. Budget the sq-proportional part (~10 bytes
    # per sq*d element) at 8MB of the ~16MB core; larger shapes take the
    # two-kernel path whose dkv pass pins only q/dO (no f32 accumulator).
    if _HAS_TPU_PALLAS and q.shape[2] * q.shape[3] * 10 <= 8 * 1024 * 1024:
        return _flash_bwd_fused(q, k, v, out, lse, g, scale, causal, block_q,
                                block_k, interpret)[:3]
    return _flash_bwd(q, k, v, out, lse, g, scale, causal, block_q, block_k,
                      interpret)[:3]


flash_attention.defvjp(_fa_fwd, _fa_bwd)


def _tile_bias(bias, b, h):
    """[B, Sk] f32 -> [b*h, 8, Sk]: head-tiled with an 8-sublane broadcast
    so the per-k-block tile is a TPU-aligned [8, bk] block."""
    sk = bias.shape[-1]
    return jnp.broadcast_to(bias.astype(jnp.float32)[:, None, None, :],
                            (b, h, 8, sk)).reshape(b * h, 8, sk)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def flash_attention_bias(q, k, v, bias, causal=False, scale=None,
                         block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                         interpret=False):
    """Flash attention with a PER-KEY additive bias [B, Sk] f32 — the
    [B,1,1,S] additive-mask form BERT-class encoders build (padding in
    any pattern, per-key score offsets). Per-QUERY-relative biases
    (ALiBi's -m*|q-k|) are NOT expressible per-key and take the XLA
    path. The bias is tiled over heads and streamed to the kernels one
    k-block at a time; its cotangent is the true per-key gradient
    (sum of dS over q rows and heads, accumulated in the backward
    kernels), so trainable biases match the XLA path's grad."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    bias3 = _tile_bias(bias, q.shape[0], q.shape[1])
    out, _ = _flash_fwd_lse(q, k, v, scale, causal, block_q, block_k,
                            interpret, bias3)
    return out


def _fab_fwd(q, k, v, bias, causal, scale, block_q, block_k, interpret):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    bias3 = _tile_bias(bias, q.shape[0], q.shape[1])
    out, lse = _flash_fwd_lse(q, k, v, scale, causal, block_q, block_k,
                              interpret, bias3)
    return out, (q, k, v, bias, bias3, out, lse)


def _fab_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, bias, bias3, out, lse = res
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if _HAS_TPU_PALLAS and q.shape[2] * q.shape[3] * 10 <= 8 * 1024 * 1024:
        dq, dk, dv, db3 = _flash_bwd_fused(q, k, v, out, lse, g, scale,
                                           causal, block_q, block_k,
                                           interpret, bias3)
    else:
        dq, dk, dv, db3 = _flash_bwd(q, k, v, out, lse, g, scale, causal,
                                     block_q, block_k, interpret, bias3)
    # kernels emit per-(b,h) column sums [b*h, 8, sk] (8 identical sublane
    # rows); the [B, Sk] bias broadcast over heads, so its cotangent sums
    # over h. This is the TRUE gradient — a trainable per-key bias (e.g.
    # learned ALiBi-style offsets) now matches the XLA path's grad.
    b, h = q.shape[0], q.shape[1]
    sk = k.shape[2]
    dbias = db3.reshape(b, h, 8, sk)[:, :, 0, :].sum(axis=1)
    if bias.shape[0] == 1 and b > 1:  # broadcast batch: sum its cotangent
        dbias = dbias.sum(axis=0, keepdims=True)
    return dq, dk, dv, dbias.astype(bias.dtype)


flash_attention_bias.defvjp(_fab_fwd, _fab_bwd)
