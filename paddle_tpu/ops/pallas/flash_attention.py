"""Flash attention — Pallas TPU kernel.

Replaces the reference's fused_attention CUDA op (north-star: "fused_attention
→ Pallas flash-attn"). Blockwise online-softmax: each grid step owns one
128-aligned Q block in VMEM, streams K/V blocks, and accumulates on the MXU in
f32. O(S) memory instead of the O(S²) score matrix.

Forward is the Pallas kernel; backward (custom_vjp) recomputes attention
blockwise with einsums that XLA fuses — standard flash-attn training recipe.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_TPU_PALLAS = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_TPU_PALLAS = False

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_k, sk):
    # q_ref: [bq, d]; k_ref/v_ref: [sk, d]; o_ref: [bq, d]
    bq = q_ref.shape[0]
    d = q_ref.shape[1]
    qi = pl.program_id(1)  # q block index
    q = q_ref[:].astype(jnp.float32) * scale

    nk = sk // block_k
    if causal:
        # only blocks up to and including the diagonal contribute
        nk_eff = jnp.minimum(((qi + 1) * bq + block_k - 1) // block_k, nk)
    else:
        nk_eff = nk

    def body(j, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                           (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, nk_eff, body, (acc0, m0, l0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    q3 = q.reshape(b * h, sq, d)
    k3 = k.reshape(b * h, sk, d)
    v3 = v.reshape(b * h, sk, d)
    grid = (b * h, sq // bq)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_k=bk, sk=sk)
    mem_kwargs = {}
    if _HAS_TPU_PALLAS and not interpret:
        mem_kwargs = {"memory_space": pltpu.VMEM}
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0), **mem_kwargs),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0), **mem_kwargs),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0), **mem_kwargs),
        ],
        out_specs=pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0),
                               **mem_kwargs),
        interpret=interpret,
    )(q3, k3, v3)
    return out.reshape(b, h, sq, d)


def _reference_attention(q, k, v, scale, causal):
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal=False, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    interpret=False):
    """q,k,v: [B,H,S,D]. S must be a multiple of the block size."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)


def _fa_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = flash_attention(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _fa_bwd(causal, scale, block_q, block_k, interpret, res, g):
    # recompute-based backward: O(S^2) scores per (b,h) but no saved
    # activations; XLA fuses the chain. A fully blockwise pallas backward is a
    # later optimization.
    q, k, v = res
    if scale is None:
        scale = q.shape[-1] ** -0.5

    def f(q, k, v):
        return _reference_attention(q, k, v, scale, causal)

    _, vjp = jax.vjp(f, q, k, v)
    return vjp(g)


flash_attention.defvjp(_fa_fwd, _fa_bwd)
