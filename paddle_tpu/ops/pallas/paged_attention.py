"""Paged decode attention — Pallas TPU kernel (ragged, block-table driven).

Single-token decode attention over a paged KV cache (Ragged Paged
Attention, arXiv:2604.15464 direction): each sequence's keys/values live
in pool blocks named by a per-sequence block table, so the kernel
gathers by table instead of assuming one contiguous cache slab.

Layout (matches inference/kv_cache.py):
    q:        [B, H, Dh]                  one new token per sequence
    k_blocks: [N, BS, H, Dh]              one layer's pool
    tables:   [B, M] int32                block ids, 0-padded (trash)
    ctx_lens: [B]    int32                tokens visible to the query

Grid is (B, M) with the block tables SCALAR-PREFETCHED: the k/v
BlockSpec index_map reads `tables[b, m]`, so the pipeline DMAs exactly
the pool blocks the table names — the gather never materializes a
[B, M*BS, ...] copy in HBM the way the XLA gather path does. Blocks past
a sequence's length still occupy grid steps (they stream the shared
trash block and are predicated off) — raggedness saves the gather
traffic and the compute, not the grid iterations.

Heads ride the sublane axis (the query is a single token): scores for
one (sequence, block) step are an [H, BS] tile from a head-batched
dot over Dh, and online-softmax state (m, l, acc) is carried in VMEM
scratch across the M dimension exactly like flash_attention.py.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_TPU_PALLAS = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_TPU_PALLAS = False

NEG_INF = -1e30
STAT_LANES = 8  # m/l row stats broadcast over 8 lanes for (8,128) tiling


def supported_shapes(head_dim, block_size, num_heads):
    """Shape gate for the compiled TPU kernel (interpret mode takes any)."""
    return (head_dim in (32, 64, 128, 256) and block_size % 128 == 0
            and num_heads % 8 == 0)


def _kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
            acc_ref, m_ref, l_ref, *, scale, nm):
    b = pl.program_id(0)
    mi = pl.program_id(1)

    @pl.when(mi == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    ctx = lens_ref[b]
    bs = k_ref.shape[1]

    @pl.when(mi * bs < ctx)
    def _compute():
        q = q_ref[0]  # [H, Dh] — input dtype feeds the MXU at full rate
        k = k_ref[0]  # [BS, H, Dh]
        v = v_ref[0]
        # s[h, t] = sum_d q[h, d] * k[t, h, d): batch over heads
        s = jax.lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * scale  # [H, BS]
        pos = mi * bs + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos < ctx, s, NEG_INF)
        m_prev = m_ref[:, 0:1]
        l_prev = l_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        # o[h, d] += sum_t p[h, t] * v[t, h, d]: same head-batched form
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)  # [H, Dh]
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(mi == nm - 1)
    def _flush():
        l = jnp.maximum(l_ref[:, 0:1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _kernel_quant(tables_ref, lens_ref, q_ref, k_ref, ks_ref, v_ref,
                  vs_ref, o_ref, acc_ref, m_ref, l_ref, *, scale, nm):
    """int8-KV variant (quantized-serving round): the pool streams as
    raw int8 codes + per-vector scales; dequantization happens HERE in
    VMEM on the one block in flight — the bf16 cache never exists in
    HBM, which is the entire point (decode is cache-READ bound)."""
    b = pl.program_id(0)
    mi = pl.program_id(1)

    @pl.when(mi == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    ctx = lens_ref[b]
    bs = k_ref.shape[1]

    @pl.when(mi * bs < ctx)
    def _compute():
        q = q_ref[0]  # [H, Dh]
        dt = q.dtype
        # per-vector dequant on the VMEM-resident block: [BS, H, Dh]
        # codes * [BS, H, 1] scales — elementwise, lane-layout friendly
        k = k_ref[0].astype(dt) * ks_ref[0][..., None].astype(dt)
        v = v_ref[0].astype(dt) * vs_ref[0][..., None].astype(dt)
        s = jax.lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * scale  # [H, BS]
        pos = mi * bs + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos < ctx, s, NEG_INF)
        m_prev = m_ref[:, 0:1]
        l_prev = l_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)  # [H, Dh]
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(mi == nm - 1)
    def _flush():
        l = jnp.maximum(l_ref[:, 0:1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("scale", "interpret"))
def paged_decode_attention_kernel(q, k_blocks, v_blocks, tables, ctx_lens,
                                  *, scale=None, interpret=False):
    """Pallas ragged paged decode attention. See module docstring for the
    layout; returns [B, H, Dh] in q's dtype. k_blocks/v_blocks may be
    `QuantizedKV` (codes [N, BS, H, Dh] int8, scales [N, BS, H]) — the
    scale tiles ride the same scalar-prefetched block index as their
    codes and dequant happens in VMEM (`_kernel_quant`)."""
    quant = hasattr(k_blocks, "codes")
    B, H, Dh = q.shape
    kcodes = k_blocks.codes if quant else k_blocks
    _, BS, _, _ = kcodes.shape
    M = tables.shape[1]
    scale = (Dh ** -0.5) if scale is None else float(scale)

    kv_spec = pl.BlockSpec((1, BS, H, Dh),
                           lambda b, m, tab, cl: (tab[b, m], 0, 0, 0))
    sc_spec = pl.BlockSpec((1, BS, H),
                           lambda b, m, tab, cl: (tab[b, m], 0, 0))
    if quant:
        in_specs = [
            pl.BlockSpec((1, H, Dh), lambda b, m, tab, cl: (b, 0, 0)),
            kv_spec, sc_spec, kv_spec, sc_spec,
        ]
        kernel = functools.partial(_kernel_quant, scale=scale, nm=M)
        operands = (q, k_blocks.codes, k_blocks.scales,
                    v_blocks.codes, v_blocks.scales)
    else:
        in_specs = [
            pl.BlockSpec((1, H, Dh), lambda b, m, tab, cl: (b, 0, 0)),
            kv_spec, kv_spec,
        ]
        kernel = functools.partial(_kernel, scale=scale, nm=M)
        operands = (q, k_blocks, v_blocks)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # tables, ctx_lens steer the DMA pipeline
        grid=(B, M),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, H, Dh), lambda b, m, tab, cl: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, Dh), jnp.float32),
            pltpu.VMEM((H, STAT_LANES), jnp.float32),
            pltpu.VMEM((H, STAT_LANES), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, Dh), q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), ctx_lens.astype(jnp.int32), *operands)
