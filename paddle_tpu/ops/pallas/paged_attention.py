"""Paged decode attention — compatibility shim (r16).

The kernel moved into `unified_attention.py` when the serving round was
collapsed to one launch: the one-token-per-sequence decode kernel is
the (B, M)-grid specialization of the unified segment-causal stream
kernel, and the two share the scalar-prefetched block-index
construction and the int8-KV in-VMEM dequant there.  This module keeps
the historical import path and names.
"""
from __future__ import annotations

from .unified_attention import (  # noqa: F401
    _HAS_TPU_PALLAS,
    NEG_INF,
    STAT_LANES,
    paged_decode_attention_kernel,
    pltpu,
    supported_shapes,
)
