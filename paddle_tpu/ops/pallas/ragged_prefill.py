"""Packed ragged prefill attention — Pallas TPU kernel (segment-causal,
block-table driven).

The serving scheduler concatenates every admitted prompt chunk this
round into ONE token-packed stream (Ragged Paged Attention,
arXiv:2604.15464 direction; Sarathi-style chunked prefill bounds the
per-dispatch token budget). Each packed token attends its OWN sequence's
paged-cache positions [0, pos] — which covers both the tokens this chunk
just wrote and the K/V that earlier chunks of the same prompt left in
the paged blocks, so chunked prefill needs no extra state carrier.

Layout (matches inference/kv_cache.py):
    q:        [T, H, Dh]              packed query stream
    k_blocks: [N, BS, H, Dh]          one layer's pool
    tables:   [B, M] int32            block ids per slot row, 0-padded
    tile_seg: [T // QT] int32         slot row of each query tile
    tile_pos: [T // QT] int32         absolute cache position of each
                                      tile's first token; -1 = pad tile

Packing contract: the scheduler aligns every segment's packed region to
the QT=128 query tile, so ONE tile never mixes segments — that keeps
the grid a plain (num_q_tiles, M) with the per-tile segment and start
position SCALAR-PREFETCHED, the same trick the decode kernel uses: the
k/v BlockSpec index map reads `tables[tile_seg[qi], m]`, so the
pipeline DMAs exactly the pool blocks each tile's sequence names and
never materializes the [T, M*BS, ...] gather copy the XLA fallback
builds. KV blocks past a tile's causal horizon (and pad tiles) still
occupy grid steps but are predicated off.

Per (tile, kv-block) step the score tile is [H, QT, BS] from a
head-batched dot over Dh; online-softmax state (m, l, acc) rides VMEM
scratch across the M dimension exactly like paged_attention.py, with
the extra QT query axis on the lanes.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_TPU_PALLAS = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_TPU_PALLAS = False

NEG_INF = -1e30
Q_TILE = 128  # query-tile (and packing alignment) size


def supported_shapes(head_dim, block_size, num_heads, total_tokens):
    """Shape gate for the compiled TPU kernel (interpret mode takes any)."""
    return (head_dim in (32, 64, 128, 256) and block_size % 128 == 0
            and num_heads % 8 == 0 and total_tokens % Q_TILE == 0)


def _kernel(tile_seg_ref, tile_pos_ref, tables_ref, q_ref, k_ref, v_ref,
            o_ref, acc_ref, m_ref, l_ref, *, scale, nm, qt):
    qi = pl.program_id(0)
    mi = pl.program_id(1)

    @pl.when(mi == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q0 = tile_pos_ref[qi]  # abs position of the tile's first query; -1 pad
    bs = k_ref.shape[1]

    # a kv block matters iff it starts at or before the tile's LAST
    # query's causal horizon; pad tiles (q0 < 0) skip every block
    @pl.when((q0 >= 0) & (mi * bs <= q0 + qt - 1))
    def _compute():
        q = q_ref[:]  # [H, QT, Dh] — input dtype feeds the MXU full-rate
        k = k_ref[0]  # [BS, H, Dh]
        v = v_ref[0]
        # s[h, i, j] = sum_d q[h, i, d] * k[j, h, d]: batch over heads
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * scale  # [H, QT, BS]
        row = q0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        col = mi * bs + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(col <= row, s, NEG_INF)  # segment-causal by abs pos
        m_prev = m_ref[:]                       # [H, QT]
        l_prev = l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2))
        p = jnp.exp(s - m_new[:, :, None])
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_prev * alpha + jnp.sum(p, axis=2)
        # o[h, i, d] += sum_j p[h, i, j] * v[j, h, d]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)  # [H, QT, Dh]
        acc_ref[:] = acc_ref[:] * alpha[:, :, None] + pv
        m_ref[:] = m_new

    @pl.when(mi == nm - 1)
    def _flush():
        l = jnp.maximum(l_ref[:], 1e-30)  # pad tiles flush zeros
        o_ref[:] = (acc_ref[:] / l[:, :, None]).astype(o_ref.dtype)


def _kernel_quant(tile_seg_ref, tile_pos_ref, tables_ref, q_ref, k_ref,
                  ks_ref, v_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref,
                  *, scale, nm, qt):
    """int8-KV variant (quantized-serving round): the block pool
    streams as raw int8 codes + per-vector scales and is dequantized
    HERE on the VMEM-resident block — no bf16 cache copy in HBM."""
    qi = pl.program_id(0)
    mi = pl.program_id(1)

    @pl.when(mi == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q0 = tile_pos_ref[qi]
    bs = k_ref.shape[1]

    @pl.when((q0 >= 0) & (mi * bs <= q0 + qt - 1))
    def _compute():
        q = q_ref[:]  # [H, QT, Dh]
        dt = q.dtype
        k = k_ref[0].astype(dt) * ks_ref[0][..., None].astype(dt)
        v = v_ref[0].astype(dt) * vs_ref[0][..., None].astype(dt)
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * scale  # [H, QT, BS]
        row = q0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        col = mi * bs + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(col <= row, s, NEG_INF)
        m_prev = m_ref[:]
        l_prev = l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2))
        p = jnp.exp(s - m_new[:, :, None])
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_prev * alpha + jnp.sum(p, axis=2)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)  # [H, QT, Dh]
        acc_ref[:] = acc_ref[:] * alpha[:, :, None] + pv
        m_ref[:] = m_new

    @pl.when(mi == nm - 1)
    def _flush():
        l = jnp.maximum(l_ref[:], 1e-30)
        o_ref[:] = (acc_ref[:] / l[:, :, None]).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("scale", "q_tile", "interpret"))
def ragged_prefill_attention_kernel(q, k_blocks, v_blocks, tables,
                                    tile_seg, tile_pos, *, scale=None,
                                    q_tile=None, interpret=False):
    """Pallas packed ragged prefill attention. See module docstring for
    the layout and packing contract; returns [T, H, Dh] in q's dtype.
    k_blocks/v_blocks may be `QuantizedKV` (codes [N, BS, H, Dh] int8,
    scales [N, BS, H]) — the scale tiles ride the same
    scalar-prefetched block index as their codes and dequant happens in
    VMEM (`_kernel_quant`). q_tile defaults to the production
    Q_TILE=128 (interpret-mode tests shrink it to exercise tiny
    shapes)."""
    quant = hasattr(k_blocks, "codes")
    qt = Q_TILE if q_tile is None else int(q_tile)
    T, H, Dh = q.shape
    kcodes = k_blocks.codes if quant else k_blocks
    _, BS, _, _ = kcodes.shape
    M = tables.shape[1]
    if T % qt:
        raise ValueError(f"packed length {T} not a multiple of the "
                         f"query tile {qt}")
    NQ = T // qt
    scale = (Dh ** -0.5) if scale is None else float(scale)

    qh = q.transpose(1, 0, 2)  # [H, T, Dh]: heads ride the sublane axis
    q_spec = pl.BlockSpec((H, qt, Dh),
                          lambda qi, m, ts, tp, tb: (0, qi, 0))
    kv_spec = pl.BlockSpec(
        (1, BS, H, Dh),
        lambda qi, m, ts, tp, tb: (tb[ts[qi], m], 0, 0, 0))
    sc_spec = pl.BlockSpec(
        (1, BS, H), lambda qi, m, ts, tp, tb: (tb[ts[qi], m], 0, 0))
    if quant:
        in_specs = [q_spec, kv_spec, sc_spec, kv_spec, sc_spec]
        kernel = functools.partial(_kernel_quant, scale=scale, nm=M,
                                   qt=qt)
        operands = (qh, k_blocks.codes, k_blocks.scales,
                    v_blocks.codes, v_blocks.scales)
    else:
        in_specs = [q_spec, kv_spec, kv_spec]
        kernel = functools.partial(_kernel, scale=scale, nm=M, qt=qt)
        operands = (qh, k_blocks, v_blocks)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # tile_seg, tile_pos, tables steer the DMA
        grid=(NQ, M),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((H, qt, Dh),
                               lambda qi, m, ts, tp, tb: (0, qi, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, qt, Dh), jnp.float32),
            pltpu.VMEM((H, qt), jnp.float32),
            pltpu.VMEM((H, qt), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((H, T, Dh), q.dtype),
        interpret=interpret,
    )(tile_seg.astype(jnp.int32), tile_pos.astype(jnp.int32),
      tables.astype(jnp.int32), *operands)
    return out.transpose(1, 0, 2)
