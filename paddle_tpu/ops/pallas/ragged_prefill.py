"""Packed ragged prefill attention — compatibility shim (r16).

The kernel moved into `unified_attention.py` when the serving round was
collapsed to one launch: segment-causal attention over a token-packed
stream is the SAME program whether the segments are prefill chunks,
plain decode rows or speculative verify regions, so the former
per-case kernel copies (and their copy-pasted scalar-prefetch
block-index construction) live once there.  This module keeps the
historical import path and names.
"""
from __future__ import annotations

from .unified_attention import (  # noqa: F401
    _HAS_TPU_PALLAS,
    NEG_INF,
    Q_TILE,
    pltpu,
    supported_shapes,
    unified_ragged_attention_kernel,
)

# historical name: the packed-prefill dispatch is one caller of the
# unified stream kernel
ragged_prefill_attention_kernel = unified_ragged_attention_kernel
