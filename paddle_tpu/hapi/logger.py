"""hapi logger setup (ref: python/paddle/hapi/logger.py): a configured
`paddle_tpu.hapi` logger for progress callbacks; setup_logger mirrors the
reference entry point."""
from __future__ import annotations

import logging
import sys


def setup_logger(output=None, name="paddle_tpu.hapi", log_level=logging.INFO):
    logger = logging.getLogger(name)
    logger.propagate = False
    logger.setLevel(log_level)
    fmt = logging.Formatter(
        "%(asctime)s %(name)s %(levelname)s: %(message)s")
    if not any(isinstance(h, logging.StreamHandler)
               and not isinstance(h, logging.FileHandler)
               for h in logger.handlers):
        h = logging.StreamHandler(stream=sys.stdout)
        h.setFormatter(fmt)
        logger.addHandler(h)
    if output is not None:
        fname = output if output.endswith((".txt", ".log")) \
            else output + "/log.txt"
        import os
        os.makedirs(os.path.dirname(fname) or ".", exist_ok=True)
        # re-entrant setup must not duplicate file sinks
        if not any(isinstance(h, logging.FileHandler)
                   and getattr(h, "baseFilename", None)
                   == os.path.abspath(fname) for h in logger.handlers):
            fh = logging.FileHandler(fname)
            fh.setFormatter(fmt)
            logger.addHandler(fh)
    return logger
