"""paddle.hapi.progressbar module path (ref: hapi/progressbar.py)."""
import sys
import time


class ProgressBar:
    """Minimal terminal progress bar with the reference's update
    contract: update(current_num, values=[(name, val), ...])."""

    def __init__(self, num=None, width=30, verbose=1, start=True,
                 file=sys.stdout):
        self._num = num
        self._width = width
        self._verbose = verbose
        self._file = file
        self._start = time.time() if start else None

    def start(self):
        self._start = time.time()

    def update(self, current_num, values=None):
        if self._verbose == 0:
            return
        metrics = " - ".join(f"{k}: {v:.4f}" if isinstance(v, float)
                             else f"{k}: {v}" for k, v in (values or []))
        if self._num:
            frac = min(current_num / self._num, 1.0)
            filled = int(frac * self._width)
            bar = "=" * filled + ">" * (filled < self._width) + \
                "." * (self._width - filled - 1)
            line = f"\r{current_num}/{self._num} [{bar}] {metrics}"
        else:
            line = f"\rstep {current_num} {metrics}"
        self._file.write(line)
        if self._num and current_num >= self._num:
            self._file.write("\n")
        self._file.flush()


__all__ = ["ProgressBar"]
