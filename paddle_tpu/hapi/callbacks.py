"""hapi callbacks (ref: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import time

import numpy as np


class Callback:
    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_begin(self, mode, logs=None):
        pass

    def on_end(self, mode, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_batch_begin(self, mode, step, logs=None):
        pass

    def on_batch_end(self, mode, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks=None):
        self.callbacks = list(callbacks or [])

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=10, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = 0
        self._t0 = time.time()

    def on_batch_end(self, mode, step, logs=None):
        self.steps += 1
        if self.verbose >= 2 and step % self.log_freq == 0:
            loss = logs[0] if isinstance(logs, (list, tuple)) else logs
            if isinstance(loss, tuple):
                loss = loss[0]
            print(f"[{mode}] epoch {getattr(self, 'epoch', 0)} "
                  f"step {step}: loss={loss}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            print(f"Epoch {epoch} done in {dt:.1f}s: {logs}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = min_delta
        self.best = None
        self.wait = 0
        self.mode = "min" if mode in ("auto", "min") else "max"

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        better = (self.best is None
                  or (self.mode == "min" and cur < self.best - self.min_delta)
                  or (self.mode == "max" and cur > self.best + self.min_delta))
        if better:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def on_batch_end(self, mode, step, logs=None):
        if mode == "train" and self.by_step:
            self._step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            self._step()

    def _step(self):
        opt = getattr(self.model, "_optimizer", None)
        if opt is not None and hasattr(opt._lr, "step"):
            opt._lr.step()


class ReduceLROnPlateau(Callback):
    """Reduce LR when a monitored metric plateaus (ref:
    python/paddle/hapi/callbacks.py ReduceLROnPlateau)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        self._best = None
        self._wait = 0
        self._cool = 0

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(cur[0] if hasattr(cur, "__len__") else cur)
        better = (self._best is None
                  or (self.mode == "max" and cur > self._best + self.min_delta)
                  or (self.mode != "max" and cur < self._best - self.min_delta))
        if better:
            self._best = cur
            self._wait = 0
            return
        if self._cool > 0:
            self._cool -= 1
            return
        self._wait += 1
        if self._wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is not None:
                lr = max(float(opt.get_lr()) * self.factor, self.min_lr)
                opt.set_lr(lr)
                if self.verbose:
                    print(f"ReduceLROnPlateau: lr -> {lr:.2e}")
            self._wait = 0
            self._cool = self.cooldown


class VisualDL(Callback):
    """Scalar logging callback (ref: python/paddle/hapi/callbacks.py
    VisualDL). The visualdl package isn't available in this environment, so
    scalars append to a jsonl file under log_dir — same information, greppable."""

    def __init__(self, log_dir="vdl_log"):
        self.log_dir = log_dir
        self._step = 0

    def _write(self, tag, logs):
        import json
        import os
        os.makedirs(self.log_dir, exist_ok=True)
        rec = {"tag": tag, "step": self._step}
        if not isinstance(logs, dict):
            logs = {"value": logs} if logs is not None else {}
        for k, v in logs.items():
            try:
                rec[k] = float(np.ravel(np.asarray(v, dtype=np.float64))[0])
            except (TypeError, ValueError):
                continue
        with open(os.path.join(self.log_dir, "scalars.jsonl"), "a") as f:
            f.write(json.dumps(rec) + "\n")

    def on_batch_end(self, mode, step, logs=None):
        if mode != "train":
            return
        self._step += 1
        if self._step % 10 == 0:
            self._write("train", logs)

    def on_end(self, mode, logs=None):
        self._write(mode, logs)
