"""hapi callbacks (ref: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import time

import numpy as np

from ..observability import log as _log
from ..observability import metrics as _metrics
from ..observability import tracing as _tracing

_logger = _log.get_logger(__name__)


class Callback:
    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_begin(self, mode, logs=None):
        pass

    def on_end(self, mode, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_batch_begin(self, mode, step, logs=None):
        pass

    def on_batch_end(self, mode, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks=None):
        self.callbacks = list(callbacks or [])

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=10, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = 0
        self._t0 = time.time()

    def on_batch_end(self, mode, step, logs=None):
        self.steps += 1
        if self.verbose >= 2 and step % self.log_freq == 0:
            loss = logs[0] if isinstance(logs, (list, tuple)) else logs
            if isinstance(loss, tuple):
                loss = loss[0]
            _logger.info("[%s] epoch %s step %s: loss=%s", mode,
                         getattr(self, "epoch", 0), step, loss)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            _logger.info("Epoch %s done in %.1fs: %s", epoch, dt, logs)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = min_delta
        self.best = None
        self.wait = 0
        self.mode = "min" if mode in ("auto", "min") else "max"

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        better = (self.best is None
                  or (self.mode == "min" and cur < self.best - self.min_delta)
                  or (self.mode == "max" and cur > self.best + self.min_delta))
        if better:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def on_batch_end(self, mode, step, logs=None):
        if mode == "train" and self.by_step:
            self._step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            self._step()

    def _step(self):
        opt = getattr(self.model, "_optimizer", None)
        if opt is not None and hasattr(opt._lr, "step"):
            opt._lr.step()


class ReduceLROnPlateau(Callback):
    """Reduce LR when a monitored metric plateaus (ref:
    python/paddle/hapi/callbacks.py ReduceLROnPlateau)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        self._best = None
        self._wait = 0
        self._cool = 0

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(cur[0] if hasattr(cur, "__len__") else cur)
        better = (self._best is None
                  or (self.mode == "max" and cur > self._best + self.min_delta)
                  or (self.mode != "max" and cur < self._best - self.min_delta))
        if better:
            self._best = cur
            self._wait = 0
            return
        if self._cool > 0:
            self._cool -= 1
            return
        self._wait += 1
        if self._wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is not None:
                lr = max(float(opt.get_lr()) * self.factor, self.min_lr)
                opt.set_lr(lr)
                if self.verbose:
                    _logger.info("ReduceLROnPlateau: lr -> %.2e", lr)
            self._wait = 0
            self._cool = self.cooldown


class TelemetryCallback(Callback):
    """Training telemetry into the observability registry (ISSUE 2):
    per-step wall-time and loss histograms, a step counter, and an
    epoch gauge — plus one `train_step` span per batch so a traced
    training window lines up with serving traces in the same JSONL.
    Model.fit attaches one automatically whenever telemetry is enabled
    (PADDLE_TPU_TELEMETRY=1 / observability.enable()); all updates
    no-op when it is off, so it is always safe to attach."""

    # step-time buckets: 1ms (CPU-tiny smoke) .. 30s (big-model chip steps)
    _STEP_BUCKETS = (.001, .005, .01, .025, .05, .1, .25, .5, 1.0, 2.5,
                     5.0, 10.0, 30.0)

    def __init__(self, prefix="train"):
        self.prefix = prefix
        self._h_step = _metrics.histogram(
            f"{prefix}_step_seconds", "wall time of one train step",
            buckets=self._STEP_BUCKETS)
        self._h_loss = _metrics.histogram(
            f"{prefix}_loss", "per-step loss",
            buckets=(.01, .1, .5, 1, 2, 5, 10, 100))
        self._c_steps = _metrics.counter(
            f"{prefix}_steps_total", "train steps completed")
        self._g_epoch = _metrics.gauge(
            f"{prefix}_epoch", "current epoch")
        self._t0 = None
        self._span = None

    def on_epoch_begin(self, epoch, logs=None):
        self._g_epoch.set(epoch)

    def on_batch_begin(self, mode, step, logs=None):
        if mode != "train":
            return
        self._t0 = time.perf_counter()
        if _tracing.enabled():
            self._span = _tracing.span("train_step", step=step)
            self._span.__enter__()

    def on_batch_end(self, mode, step, logs=None):
        if mode != "train":
            return
        if self._span is not None:
            self._span.__exit__(None, None, None)
            self._span = None
        if self._t0 is not None:
            self._h_step.observe(time.perf_counter() - self._t0)
            self._t0 = None
        self._c_steps.inc()
        loss = logs[0] if isinstance(logs, (list, tuple)) and logs \
            else logs
        if isinstance(loss, tuple):
            loss = loss[0]
        try:
            self._h_loss.observe(float(np.ravel(np.asarray(loss))[0]))
        except (TypeError, ValueError):
            pass  # non-scalar logs (metrics dicts) — step time still lands


class VisualDL(Callback):
    """Scalar logging callback (ref: python/paddle/hapi/callbacks.py
    VisualDL). The visualdl package isn't available in this environment, so
    scalars append to a jsonl file under log_dir — same information, greppable."""

    def __init__(self, log_dir="vdl_log"):
        self.log_dir = log_dir
        self._step = 0

    def _write(self, tag, logs):
        import json
        import os
        os.makedirs(self.log_dir, exist_ok=True)
        rec = {"tag": tag, "step": self._step}
        if not isinstance(logs, dict):
            logs = {"value": logs} if logs is not None else {}
        for k, v in logs.items():
            try:
                rec[k] = float(np.ravel(np.asarray(v, dtype=np.float64))[0])
            except (TypeError, ValueError):
                continue
        with open(os.path.join(self.log_dir, "scalars.jsonl"), "a") as f:
            f.write(json.dumps(rec) + "\n")

    def on_batch_end(self, mode, step, logs=None):
        if mode != "train":
            return
        self._step += 1
        if self._step % 10 == 0:
            self._write("train", logs)

    def on_end(self, mode, logs=None):
        self._write(mode, logs)
