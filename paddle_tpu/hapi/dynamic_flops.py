"""paddle.hapi.dynamic_flops module path (ref: hapi/dynamic_flops.py) —
binds the flops counter (static_flops implements the shared logic)."""
from .static_flops import flops  # noqa: F401

__all__ = ["flops"]
