"""paddle.flops — per-layer FLOPs profiler.

Reference: python/paddle/hapi/static_flops.py + dynamic_flops.py — counts
multiply-accumulates per layer via forward hooks on a dummy forward.
Same design here: one dummy forward with zeros, post-hooks record each
leaf layer's FLOPs from its input/output shapes. `custom_ops` maps layer
classes to `fn(layer, input_shape, output_shape) -> flops` overrides.
"""
from __future__ import annotations

import numpy as np

from .. import nn


def _numel(shape):
    return int(np.prod([d for d in shape if d is not None])) if shape else 0


def _linear(layer, in_shape, out_shape):
    # [.., in] @ [in, out]: 2*in*out per output row
    batch = _numel(out_shape[:-1])
    return 2 * batch * layer.weight.shape[0] * layer.weight.shape[1]


def _conv(layer, in_shape, out_shape):
    w = layer.weight
    out_elems = _numel(out_shape)
    per_out = 2 * _numel(w.shape[1:])  # cin/groups * kh * kw MACs
    return out_elems * per_out


def _norm(layer, in_shape, out_shape):
    return 5 * _numel(in_shape)  # mean, var, normalize, scale, shift


def _pool(layer, in_shape, out_shape):
    return _numel(out_shape) * 9  # window reduce, kernel-size bounded est.

def _embedding(layer, in_shape, out_shape):
    return 0  # gather: no MACs


def _act(layer, in_shape, out_shape):
    return _numel(out_shape)


_DEFAULT = [
    (nn.Linear, _linear),
    (nn.Conv2D, _conv),
    (nn.Conv3D, _conv) if hasattr(nn, "Conv3D") else None,
    (nn.Conv2DTranspose, _conv) if hasattr(nn, "Conv2DTranspose") else None,
    (nn.Embedding, _embedding),
    (nn.ReLU, _act),
    (nn.GELU, _act) if hasattr(nn, "GELU") else None,
    (nn.Sigmoid, _act) if hasattr(nn, "Sigmoid") else None,
]


def _norm_classes():
    out = []
    for name in ("BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
                 "LayerNorm", "GroupNorm", "InstanceNorm2D", "SyncBatchNorm"):
        cls = getattr(nn, name, None)
        if cls is not None:
            out.append(cls)
    return tuple(out)


def _pool_classes():
    out = []
    for name in ("MaxPool2D", "AvgPool2D", "AdaptiveAvgPool2D",
                 "AdaptiveMaxPool2D"):
        cls = getattr(nn, name, None)
        if cls is not None:
            out.append(cls)
    return tuple(out)


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Count FLOPs of one forward at `input_size` (ref: paddle.flops).
    Returns the total; prints a per-layer table when print_detail."""
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    custom_ops = custom_ops or {}
    table = {cls: fn for item in _DEFAULT if item
             for cls, fn in [item]}
    norms = _norm_classes()
    pools = _pool_classes()

    rows = []
    handles = []

    def make_hook(layer):
        def hook(lyr, inputs, outputs):
            x = inputs[0] if isinstance(inputs, (list, tuple)) else inputs
            in_shape = tuple(getattr(x, "shape", ()) or ())
            y = outputs[0] if isinstance(outputs, (list, tuple)) \
                else outputs
            out_shape = tuple(getattr(y, "shape", ()) or ())
            cls = type(lyr)
            if cls in custom_ops:
                fl = custom_ops[cls](lyr, in_shape, out_shape)
            elif cls in table:
                fl = table[cls](lyr, in_shape, out_shape)
            elif isinstance(lyr, norms):
                fl = _norm(lyr, in_shape, out_shape)
            elif isinstance(lyr, pools):
                fl = _pool(lyr, in_shape, out_shape)
            else:
                return
            rows.append((type(lyr).__name__, in_shape, out_shape, int(fl)))
        return hook

    leaves = [m for _, m in net.named_sublayers()
              if not m._sub_layers] or [net]
    for m in leaves:
        handles.append(m.register_forward_post_hook(make_hook(m)))

    was_training = net.training
    net.eval()
    try:
        x = Tensor(jnp.zeros(tuple(input_size), jnp.float32))
        net(x)
    finally:
        if was_training:
            net.train()
        for h in handles:  # remove only OUR hooks, not the user's
            h.remove()

    total = sum(r[3] for r in rows)
    if print_detail:
        print(f"{'Layer':<20}{'Input':<22}"  # cli-print: flops table
              f"{'Output':<22}{'FLOPs':>14}")
        for name, i, o, fl in rows:
            print(f"{name:<20}{str(i):<22}"  # cli-print
                  f"{str(o):<22}{fl:>14,}")
    print(f"Total Flops: {total}     Total Params: "  # cli-print
          f"{sum(int(np.prod(p.shape)) for p in net.parameters())}")
    return total
