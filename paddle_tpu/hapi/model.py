"""Model — fit/evaluate/predict (ref: python/paddle/hapi/model.py).

The train loop drives a fused jitted train step (params+opt pytrees in, new
state out) — the whole step is one XLA computation, matching the reference's
Executor-with-fused-graph performance model rather than op-by-op eager.
"""
from __future__ import annotations

import time

import numpy as np

from ..core.tensor import Tensor
from ..io import DataLoader, Dataset
from ..metric import Metric
from . import callbacks as cb_mod


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]
        return self

    # ---- core steps ------------------------------------------------------
    def train_batch(self, inputs, labels=None):
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(labels, (list, tuple)) \
            else [labels]
        out = self.network(*[_as_tensor(i) for i in inputs])
        loss = self._loss(out, *[_as_tensor(l) for l in labels]) \
            if labels is not None else out
        loss_t = loss if isinstance(loss, Tensor) else loss[0]
        loss_t.backward()
        self._optimizer.step()
        self._optimizer.clear_grad()
        metrics = self._compute_metrics(out, labels)
        return ([float(loss_t.numpy())], metrics) if metrics else \
            [float(loss_t.numpy())]

    def eval_batch(self, inputs, labels=None):
        from ..core.autograd import no_grad
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(labels, (list, tuple)) \
            else [labels]
        with no_grad():
            out = self.network(*[_as_tensor(i) for i in inputs])
            loss = self._loss(out, *[_as_tensor(l) for l in labels]) \
                if self._loss and labels is not None else None
        metrics = self._compute_metrics(out, labels)
        if loss is not None:
            loss_t = loss if isinstance(loss, Tensor) else loss[0]
            return ([float(loss_t.numpy())], metrics) if metrics else \
                [float(loss_t.numpy())]
        return metrics

    def predict_batch(self, inputs):
        from ..core.autograd import no_grad
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        with no_grad():
            out = self.network(*[_as_tensor(i) for i in inputs])
        return out

    def _compute_metrics(self, out, labels):
        res = {}
        for m in self._metrics:
            inp = m.compute(out, *(_as_tensor(l) for l in labels)) \
                if labels is not None else m.compute(out)
            res[m.name()] = m.update(inp)
        return res or None

    # ---- loops -----------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        loader = train_data if isinstance(train_data, DataLoader) else \
            DataLoader(train_data, batch_size=batch_size, shuffle=shuffle,
                       drop_last=drop_last, num_workers=num_workers)
        cbs = cb_mod.CallbackList(callbacks or
                                  [cb_mod.ProgBarLogger(log_freq, verbose)])
        cbs.set_model(self)
        cbs.on_begin("train")
        history = []
        it_count = 0
        for epoch in range(epochs):
            cbs.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            for step, batch in enumerate(loader):
                cbs.on_batch_begin("train", step, None)
                inputs, labels = _split_batch(batch)
                logs = self.train_batch(inputs, labels)
                cbs.on_batch_end("train", step, logs)
                it_count += 1
                if num_iters is not None and it_count >= num_iters:
                    break
            epoch_logs = {"loss": logs[0] if isinstance(logs, list) else logs}
            history.append(epoch_logs)
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_data, batch_size=batch_size, verbose=0)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(f"{save_dir}/{epoch}")
            cbs.on_epoch_end(epoch, epoch_logs)
            if self.stop_training or (num_iters is not None
                                      and it_count >= num_iters):
                break
        cbs.on_end("train")
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = eval_data if isinstance(eval_data, DataLoader) else \
            DataLoader(eval_data, batch_size=batch_size, num_workers=num_workers)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            inputs, labels = _split_batch(batch)
            logs = self.eval_batch(inputs, labels)
            if isinstance(logs, tuple):
                losses.append(logs[0][0])
            elif isinstance(logs, list):
                losses.append(logs[0])
        result = {}
        if losses:
            result["loss"] = [float(np.mean(losses))]
        for m in self._metrics:
            result[m.name()] = m.accumulate()
        return result

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else \
            DataLoader(test_data, batch_size=batch_size, num_workers=num_workers)
        outputs = []
        for batch in loader:
            inputs, _ = _split_batch(batch)
            out = self.predict_batch(inputs)
            outputs.append(out.numpy() if isinstance(out, Tensor)
                           else [o.numpy() for o in out])
        if stack_outputs and outputs and isinstance(outputs[0], np.ndarray):
            return [np.concatenate(outputs)]
        return [outputs]

    # ---- io --------------------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io import save as fsave
        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as fload
        self.network.set_state_dict(fload(path + ".pdparams"))
        import os
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fload(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .. import summary as _summary
        return _summary(self.network, input_size)


def _as_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def _split_batch(batch):
    if isinstance(batch, (tuple, list)):
        if len(batch) >= 2:
            return [batch[0]], list(batch[1:])
        return [batch[0]], None
    return [batch], None
