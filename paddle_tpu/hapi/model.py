"""Model — fit/evaluate/predict (ref: python/paddle/hapi/model.py).

The train loop drives a fused jitted train step (params+opt pytrees in, new
state out) — the whole step is one XLA computation, matching the reference's
Executor-with-fused-graph performance model rather than op-by-op eager.
"""
from __future__ import annotations

import time

import numpy as np

from ..core.tensor import Tensor
from ..io import DataLoader, Dataset
from ..metric import Metric
from . import callbacks as cb_mod


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = list(inputs) if inputs is not None else []
        self._labels = list(labels) if labels is not None else []
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False
        self._use_jit = False
        self._jit_state = None  # (compiled_fn, opt_state) once built

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, jit=True):
        """jit=True (default): train_batch runs as ONE fused XLA computation
        (forward + backward + optimizer update), the TPU perf path. Falls back
        to eager per-op execution when the loss/model isn't traceable."""
        self._optimizer = optimizer
        self._loss = loss
        self._use_jit = jit
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]
        return self

    # ---- fused jitted step ----------------------------------------------
    def _build_jit_step(self):
        import jax

        from ..core import rng as rng_mod
        from ..core.autograd import no_grad

        net, loss_layer, optimizer = self.network, self._loss, self._optimizer

        def pure_step(params, buffers, opt_state, raw_inputs, raw_labels,
                      key, lr):
            saved_p, saved_b = net.functional_state()
            rng_saved = (rng_mod._default_generator._key,
                         rng_mod._default_generator._count)
            rng_mod._default_generator._key = key
            rng_mod._default_generator._count = 0
            try:
                def loss_of(p):
                    net.load_functional_state(p, buffers)
                    with no_grad():
                        out = net(*[Tensor(x) for x in raw_inputs])
                        loss = loss_layer(out, *[Tensor(l) for l in raw_labels])
                    loss_t = loss if isinstance(loss, Tensor) else loss[0]
                    out_raw = jax.tree_util.tree_map(
                        lambda t: t._value if isinstance(t, Tensor) else t,
                        out, is_leaf=lambda t: isinstance(t, Tensor))
                    _, new_bufs = net.functional_state()
                    return loss_t._value, (out_raw, new_bufs)

                (loss_v, (out_raw, new_bufs)), grads = \
                    jax.value_and_grad(loss_of, has_aux=True)(params)
                clip = optimizer._grad_clip
                if clip is not None and hasattr(clip, "clip_tree"):
                    grads = clip.clip_tree(grads)
                new_params, new_opt = optimizer.functional_update(
                    params, grads, opt_state, lr=lr)
                return loss_v, out_raw, new_params, new_bufs, new_opt
            finally:
                net.load_functional_state(saved_p, saved_b)
                (rng_mod._default_generator._key,
                 rng_mod._default_generator._count) = rng_saved

        return jax.jit(pure_step, donate_argnums=(0, 2))

    def _jit_train_batch(self, inputs, labels):
        import jax
        import jax.numpy as jnp

        from ..core import rng as rng_mod
        if self._jit_state is None:
            params, _ = self.network.functional_state()
            opt_state = self._optimizer.functional_init(params)
            self._jit_state = [self._build_jit_step(), opt_state]
        step_fn, opt_state = self._jit_state
        params, buffers = self.network.functional_state()
        raw_in = [i._value if isinstance(i, Tensor) else jnp.asarray(np.asarray(i))
                  for i in inputs]
        raw_lb = [l._value if isinstance(l, Tensor) else jnp.asarray(np.asarray(l))
                  for l in (labels or [])]
        lr = jnp.asarray(self._optimizer.get_lr(), jnp.float32)
        loss_v, out_raw, new_params, new_bufs, new_opt = step_fn(
            params, buffers, opt_state, raw_in, raw_lb,
            rng_mod.next_key(), lr)
        self.network.load_functional_state(new_params, new_bufs)
        self._jit_state[1] = new_opt
        self._optimizer._step_count += 1
        out_t = jax.tree_util.tree_map(Tensor, out_raw)
        metrics = self._compute_metrics(out_t, labels)
        lv = float(np.asarray(loss_v))
        return ([lv], metrics) if metrics else [lv]

    # ---- core steps ------------------------------------------------------
    def train_batch(self, inputs, labels=None):
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(labels, (list, tuple)) \
            else [labels]
        if self._use_jit and labels is not None:
            try:
                return self._jit_train_batch(inputs, labels)
            except Exception:
                self._use_jit = False  # fall back to eager permanently
        out = self.network(*[_as_tensor(i) for i in inputs])
        loss = self._loss(out, *[_as_tensor(l) for l in labels]) \
            if labels is not None else out
        loss_t = loss if isinstance(loss, Tensor) else loss[0]
        loss_t.backward()
        self._optimizer.step()
        self._optimizer.clear_grad()
        metrics = self._compute_metrics(out, labels)
        return ([float(loss_t.numpy())], metrics) if metrics else \
            [float(loss_t.numpy())]

    def eval_batch(self, inputs, labels=None):
        from ..core.autograd import no_grad
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(labels, (list, tuple)) \
            else [labels]
        with no_grad():
            out = self.network(*[_as_tensor(i) for i in inputs])
            loss = self._loss(out, *[_as_tensor(l) for l in labels]) \
                if self._loss and labels is not None else None
        metrics = self._compute_metrics(out, labels)
        if loss is not None:
            loss_t = loss if isinstance(loss, Tensor) else loss[0]
            return ([float(loss_t.numpy())], metrics) if metrics else \
                [float(loss_t.numpy())]
        return metrics

    def predict_batch(self, inputs):
        from ..core.autograd import no_grad
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        with no_grad():
            out = self.network(*[_as_tensor(i) for i in inputs])
        return out

    def _compute_metrics(self, out, labels):
        res = {}
        for m in self._metrics:
            inp = m.compute(out, *(_as_tensor(l) for l in labels)) \
                if labels is not None else m.compute(out)
            res[m.name()] = m.update(inp)
        return res or None

    # ---- loops -----------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        loader = train_data if isinstance(train_data, DataLoader) else \
            DataLoader(train_data, batch_size=batch_size, shuffle=shuffle,
                       drop_last=drop_last, num_workers=num_workers)
        cb_list = list(callbacks or
                       [cb_mod.ProgBarLogger(log_freq, verbose)])
        # telemetry on and no explicit TelemetryCallback -> attach one,
        # so `fit` feeds the step-time/loss histograms for free
        from ..observability import metrics as _obs_metrics
        if _obs_metrics.enabled() and not any(
                isinstance(c, cb_mod.TelemetryCallback) for c in cb_list):
            cb_list.append(cb_mod.TelemetryCallback())
        cbs = cb_mod.CallbackList(cb_list)
        cbs.set_model(self)
        cbs.on_begin("train")
        history = []
        it_count = 0
        for epoch in range(epochs):
            cbs.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            for step, batch in enumerate(loader):
                cbs.on_batch_begin("train", step, None)
                inputs, labels = _split_batch(batch)
                logs = self.train_batch(inputs, labels)
                cbs.on_batch_end("train", step, logs)
                it_count += 1
                if num_iters is not None and it_count >= num_iters:
                    break
            epoch_logs = {"loss": logs[0] if isinstance(logs, list) else logs}
            history.append(epoch_logs)
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_data, batch_size=batch_size, verbose=0)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(f"{save_dir}/{epoch}")
            cbs.on_epoch_end(epoch, epoch_logs)
            if self.stop_training or (num_iters is not None
                                      and it_count >= num_iters):
                break
        cbs.on_end("train")
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = eval_data if isinstance(eval_data, DataLoader) else \
            DataLoader(eval_data, batch_size=batch_size, num_workers=num_workers)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            inputs, labels = _split_batch(batch)
            logs = self.eval_batch(inputs, labels)
            if isinstance(logs, tuple):
                losses.append(logs[0][0])
            elif isinstance(logs, list):
                losses.append(logs[0])
        result = {}
        if losses:
            result["loss"] = [float(np.mean(losses))]
        for m in self._metrics:
            result[m.name()] = m.accumulate()
        return result

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else \
            DataLoader(test_data, batch_size=batch_size, num_workers=num_workers)
        outputs = []
        for batch in loader:
            inputs, _ = _split_batch(batch)
            out = self.predict_batch(inputs)
            outputs.append(out.numpy() if isinstance(out, Tensor)
                           else [o.numpy() for o in out])
        if stack_outputs and outputs and isinstance(outputs[0], np.ndarray):
            return [np.concatenate(outputs)]
        return [outputs]

    # ---- io --------------------------------------------------------------
    def save(self, path, training=True):
        """training=True: checkpoint (params + opt state). training=False:
        deployment artifact via jit.save — serialized StableHLO + npz,
        loadable by inference.create_predictor with no model class (ref:
        hapi/model.py save -> jit.save when training=False). Needs the
        Model's `inputs` InputSpecs (as the reference does)."""
        if not training:
            from .. import jit
            if not self._inputs:
                raise ValueError(
                    "Model.save(training=False) needs Model(network, "
                    "inputs=[InputSpec(...)]) to trace the forward")
            jit.save(self.network, path, input_spec=self._inputs)
            return
        from ..framework.io import save as fsave
        fsave(self.network.state_dict(), path + ".pdparams")
        if self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as fload
        self.network.set_state_dict(fload(path + ".pdparams"))
        import os
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fload(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .. import summary as _summary
        return _summary(self.network, input_size)


def _as_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def _split_batch(batch):
    if isinstance(batch, (tuple, list)):
        if len(batch) >= 2:
            return [batch[0]], list(batch[1:])
        return [batch[0]], None
    return [batch], None
