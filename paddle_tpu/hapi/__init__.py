"""High-level API (ref: python/paddle/hapi/)."""
from __future__ import annotations

from . import callbacks  # noqa: F401
from . import logger  # noqa: F401
from . import model_summary  # noqa: F401
from .model import Model  # noqa: F401
from .static_flops import flops  # noqa: F401


def summary(net, input_size=None, dtypes=None, input=None):  # noqa: A002
    """ref: hapi/model_summary.py — delegate to the top-level impl."""
    import paddle_tpu
    return paddle_tpu.summary(net, input_size, dtypes, input)
