"""High-level API (ref: python/paddle/hapi/)."""
from __future__ import annotations

from . import callbacks  # noqa: F401
from .model import Model  # noqa: F401
