"""hapi.model_summary — the reference's canonical home of `summary`
(ref: python/paddle/hapi/model_summary.py); the implementation lives in
the top-level paddle_tpu.summary, shared with Model.summary()."""
from __future__ import annotations

__all__ = ["summary"]


def summary(net, input_size=None, dtypes=None, input=None):  # noqa: A002
    import paddle_tpu
    return paddle_tpu.summary(net, input_size, dtypes, input)
