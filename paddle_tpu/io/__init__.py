"""paddle.io — Dataset / Sampler / DataLoader.

Reference: python/paddle/io/ + python/paddle/fluid/dataloader/. The DataLoader
prefetch pipeline is backed by the native C++ worker core (csrc/) when built;
falls back to a Python thread pool. Host-side batching feeds device transfers
once per step (minimizing host↔HBM traffic).
"""
from __future__ import annotations

import itertools
import math
import queue
import threading

import numpy as np

from ..core import rng as rng_mod
from ..core.tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = [t.numpy() if isinstance(t, Tensor) else np.asarray(t)
                        for t in tensors]

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            item = ds[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __iter__(self):
        for ds in self.datasets:
            yield from ds


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self.cum[-1])

    def __getitem__(self, idx):
        ds_idx = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if ds_idx == 0 else int(self.cum[ds_idx - 1])
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset, self.indices = dataset, list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    idx = np.random.permutation(len(dataset))
    out, off = [], 0
    for n in lengths:
        out.append(Subset(dataset, idx[off:off + n].tolist()))
        off += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        return iter(np.random.choice(len(p), self.num_samples,
                                     replace=self.replacement, p=p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards indices across data-parallel ranks (ref:
    python/paddle/io/__init__.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_rank, get_world_size
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            g = np.random.RandomState(self.epoch)
            indices = g.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[:self.total_size - len(indices)]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        return tuple(default_collate_fn([b[i] for b in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return Tensor(np.stack([b.numpy() for b in batch]))
    arr = np.stack([np.asarray(b) for b in batch])
    return Tensor(arr)


_loader_fallback_seen = set()


def _warn_loader_fallback(what, e):
    """A silent perf-path downgrade hid the dead flash backward for three
    rounds (r4 finding) — loader fallbacks warn once per (path, error)."""
    key = (what, type(e).__name__)
    if key not in _loader_fallback_seen:
        _loader_fallback_seen.add(key)
        import warnings
        warnings.warn(f"DataLoader fell back from {what}: "
                      f"{type(e).__name__}: {str(e)[:160]}", RuntimeWarning,
                      stacklevel=3)


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_shared_memory = use_shared_memory
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.persistent_workers = persistent_workers and num_workers > 0
        self._pool = None  # live PersistentLoaderPool when enabled
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def close(self):
        """Release the persistent worker pool (no-op otherwise)."""
        if self._pool is not None:
            self._pool.close()
            self._pool = None

    def __del__(self):  # pragma: no cover - gc path
        try:
            self.close()
        except Exception:
            pass

    def _iter_batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        else:
            for idx_batch in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idx_batch])

    def __iter__(self):
        if self.num_workers <= 0:
            yield from self._iter_batches()
            return
        yield from self._multiprocess_iter()

    def _multiprocess_iter(self):
        """Worker processes do __getitem__ + collate (ref:
        fluid/dataloader/dataloader_iter.py); batches travel through shared
        memory into the C++ byte-queue. Falls back to the single-process
        thread prefetcher if process spawn fails (e.g. sandboxed)."""
        from .worker import MultiprocessLoaderIter
        if self.persistent_workers:
            try:
                if self._pool is None or self._pool._shutdown:
                    self._pool = MultiprocessLoaderIter(
                        self.dataset, self.collate_fn, None,
                        self.num_workers, self.prefetch_factor,
                        self.timeout, self.worker_init_fn,
                        self.use_shared_memory,
                        iterable_batch_size=(self.batch_size
                                             if self._iterable_mode
                                             else None),
                        iterable_drop_last=(self.drop_last
                                            if self._iterable_mode
                                            else False),
                        persistent=True)
            except Exception as e:
                _warn_loader_fallback("persistent worker pool", e)
                yield from self._prefetch_iter()
                return
            yield from self._pool.epoch(
                None if self._iterable_mode else list(self.batch_sampler))
            return
        try:
            if self._iterable_mode:
                it = MultiprocessLoaderIter(
                    self.dataset, self.collate_fn, None, self.num_workers,
                    self.prefetch_factor, self.timeout, self.worker_init_fn,
                    self.use_shared_memory,
                    iterable_batch_size=self.batch_size,
                    iterable_drop_last=self.drop_last)
        except Exception as e:  # construction only: a mid-stream failure
            # must NOT restart iteration (duplicate batches); and a silent
            # perf downgrade hid a dead kernel path for rounds — warn.
            _warn_loader_fallback("worker processes", e)
            yield from self._prefetch_iter()
            return
        try:
            if not self._iterable_mode:
                it = MultiprocessLoaderIter(
                    self.dataset, self.collate_fn,
                    list(self.batch_sampler), self.num_workers,
                    self.prefetch_factor, self.timeout, self.worker_init_fn,
                    self.use_shared_memory)
        except Exception as e:
            _warn_loader_fallback("worker processes", e)
            yield from self._prefetch_iter()
            return
        yield from it

    def _prefetch_iter(self):
        """Single-process background prefetch: native C++ ring buffer when
        available, otherwise a Python thread."""
        prefetcher = None
        try:
            from .native_loader import NativePrefetcher
            prefetcher = NativePrefetcher(self._iter_batches(),
                                          depth=self.num_workers *
                                          self.prefetch_factor)
        except Exception as e:  # construction only — see worker fallback
            _warn_loader_fallback("native C++ prefetcher", e)
        if prefetcher is not None:
            yield from prefetcher
            return
        q: queue.Queue = queue.Queue(maxsize=self.num_workers * self.prefetch_factor)
        sentinel = object()

        def worker():
            try:
                for b in self._iter_batches():
                    q.put(b)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item


def get_worker_info():
    from .worker import get_worker_info as _gwi
    return _gwi()


class Transform:
    """Base dataset transform callable (ref: the reference io namespace
    re-export; vision transforms subclass the same contract)."""

    def __call__(self, data):
        return data
