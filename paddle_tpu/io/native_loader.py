"""Python binding for the native runtime (csrc/native_runtime.cpp).

Builds the shared library with g++ on first use (cached beside the source)
and exposes:
  * NativePrefetcher — background-thread batch prefetch through the C++
    bounded byte-queue; ctypes releases the GIL around pushes/pops so the
    producer's numpy work and the consumer's device feed overlap.
  * HostArena — size-bucketed staging allocator.
Falls back cleanly (ImportError) when no compiler is available; DataLoader
then uses its pure-Python thread prefetcher.
"""
from __future__ import annotations

import ctypes
import os
import pickle
import subprocess
import threading

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "csrc", "native_runtime.cpp")
_SO = os.path.join(os.path.dirname(_SRC), "libpaddle_tpu_native.so")

_lib = None
_lib_lock = threading.Lock()


def _build():
    cxx = os.environ.get("CXX", "g++")
    cmd = [cxx, "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _SO,
           "-pthread"]
    subprocess.run(cmd, check=True, capture_output=True)


def get_lib():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO) or (os.path.getmtime(_SO)
                                       < os.path.getmtime(_SRC)):
            _build()
        lib = ctypes.CDLL(_SO)
        lib.ptq_create.restype = ctypes.c_void_p
        lib.ptq_create.argtypes = [ctypes.c_size_t, ctypes.c_size_t]
        lib.ptq_push.restype = ctypes.c_int
        lib.ptq_push.argtypes = [ctypes.c_void_p,
                                 ctypes.POINTER(ctypes.c_uint8),
                                 ctypes.c_size_t]
        lib.ptq_peek_size.restype = ctypes.c_int64
        lib.ptq_peek_size.argtypes = [ctypes.c_void_p]
        lib.ptq_pop.restype = ctypes.c_int64
        lib.ptq_pop.argtypes = [ctypes.c_void_p,
                                ctypes.POINTER(ctypes.c_uint8),
                                ctypes.c_size_t]
        lib.ptq_pop_timed.restype = ctypes.c_int64
        lib.ptq_pop_timed.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_uint8),
                                      ctypes.c_size_t, ctypes.c_int64]
        lib.ptq_push_tagged.restype = ctypes.c_int
        lib.ptq_push_tagged.argtypes = [ctypes.c_void_p, ctypes.c_uint8,
                                        ctypes.POINTER(ctypes.c_uint8),
                                        ctypes.c_size_t]
        lib.ptq_size.restype = ctypes.c_int64
        lib.ptq_size.argtypes = [ctypes.c_void_p]
        lib.ptq_close.argtypes = [ctypes.c_void_p]
        lib.ptq_destroy.argtypes = [ctypes.c_void_p]
        lib.arena_create.restype = ctypes.c_void_p
        lib.arena_create.argtypes = [ctypes.c_size_t]
        lib.arena_alloc.restype = ctypes.c_void_p
        lib.arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
        lib.arena_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.arena_reserved_bytes.restype = ctypes.c_int64
        lib.arena_reserved_bytes.argtypes = [ctypes.c_void_p]
        lib.arena_destroy.argtypes = [ctypes.c_void_p]
        lib.ms_scan.restype = ctypes.c_longlong
        lib.ms_scan.argtypes = [ctypes.POINTER(ctypes.c_char),
                                ctypes.c_longlong, ctypes.c_int,
                                ctypes.POINTER(ctypes.c_longlong)]
        lib.ms_fill.restype = ctypes.c_int
        lib.ms_fill.argtypes = [ctypes.POINTER(ctypes.c_char),
                                ctypes.c_longlong, ctypes.c_int,
                                ctypes.POINTER(ctypes.c_uint8),
                                ctypes.POINTER(ctypes.c_longlong),
                                ctypes.POINTER(ctypes.c_void_p),
                                ctypes.c_longlong]
        _lib = lib
        return lib


def parse_multislot(data, slot_meta):
    """Parse a MultiSlot text buffer natively into padded per-slot arrays.

    data: bytes of slot-formatted lines. slot_meta: [(name, np_dtype,
    fixed_width_or_None), ...] as produced by fluid.dataset_feed's
    _slot_meta. Returns {name: [n_samples, width] ndarray}; raises
    ValueError on malformed input (same contract as the Python parser).
    """
    lib = get_lib()
    n_slots = len(slot_meta)
    if n_slots == 0:
        raise ValueError("no slots configured (set_use_var first)")
    # zero-copy when handed a bytearray: terminate IN PLACE (strtol/
    # strtof need it) instead of materializing a second dataset-sized
    # buffer
    if isinstance(data, bytearray):
        if not data.endswith(b"\0"):
            data.append(0)
    else:
        data = bytearray(data) + b"\0"
    length = len(data) - 1
    cbuf = (ctypes.c_char * len(data)).from_buffer(data)
    widths = (ctypes.c_longlong * n_slots)()
    n = lib.ms_scan(cbuf, length, n_slots, widths)
    if n < 0:
        raise ValueError("malformed MultiSlot data (token/slot mismatch)")
    out = {}
    ptrs = (ctypes.c_void_p * n_slots)()
    is_float = (ctypes.c_uint8 * n_slots)()
    final_w = (ctypes.c_longlong * n_slots)()
    for s, (name, dtype, fixed) in enumerate(slot_meta):
        w = int(widths[s])
        if fixed:
            w = max(w, int(fixed))  # parse buffer must hold every token
        is_float[s] = 1 if np.dtype(dtype) == np.float32 else 0
        arr = np.zeros((int(n), w),
                       np.float32 if is_float[s] else np.int64)
        out[name] = arr
        final_w[s] = w
        ptrs[s] = arr.ctypes.data_as(ctypes.c_void_p)
    if n and lib.ms_fill(cbuf, length, n_slots, is_float, final_w,
                         ptrs, n) != 0:
        raise ValueError("malformed MultiSlot data (value parse failed)")
    for s, (name, dtype, fixed) in enumerate(slot_meta):
        if fixed and out[name].shape[1] != int(fixed):
            out[name] = out[name][:, : int(fixed)]
    return out


def _serialize_batch(batch):
    """Split a batch into (metadata, concatenated raw bytes). Tensors/ndarrays
    travel as raw buffers; everything else via pickle in the metadata."""
    from ..core.tensor import Tensor
    arrays = []

    def strip(obj):
        if isinstance(obj, Tensor):
            a = obj.numpy()
            arrays.append(np.ascontiguousarray(a))
            return ("__arr__", len(arrays) - 1, a.dtype.str, a.shape, True)
        if isinstance(obj, np.ndarray):
            arrays.append(np.ascontiguousarray(obj))
            return ("__arr__", len(arrays) - 1, obj.dtype.str, obj.shape, False)
        if isinstance(obj, (list, tuple)):
            return type(obj)(strip(o) for o in obj)
        if isinstance(obj, dict):
            return {k: strip(v) for k, v in obj.items()}
        return obj

    meta = strip(batch)
    payload = b"".join(a.tobytes() for a in arrays)
    header = pickle.dumps((meta, [a.nbytes for a in arrays]))
    return (len(header).to_bytes(8, "little") + header + payload)


def _deserialize_batch(buf):
    from ..core.tensor import Tensor
    hlen = int.from_bytes(buf[:8], "little")
    meta, sizes = pickle.loads(bytes(buf[8:8 + hlen]))
    offset = 8 + hlen
    arrays = []
    for n in sizes:
        arrays.append(bytes(buf[offset:offset + n]))
        offset += n

    def rebuild(obj):
        if isinstance(obj, tuple) and len(obj) == 5 and obj[0] == "__arr__":
            _, idx, dtype, shape, is_tensor = obj
            a = np.frombuffer(arrays[idx], dtype=np.dtype(dtype)).reshape(shape)
            return Tensor(a) if is_tensor else a
        if isinstance(obj, tuple):
            return tuple(rebuild(o) for o in obj)
        if isinstance(obj, list):
            return [rebuild(o) for o in obj]
        if isinstance(obj, dict):
            return {k: rebuild(v) for k, v in obj.items()}
        return obj

    return rebuild(meta)


class NativePrefetcher:
    """Iterate `source_iter` on a background thread; batches flow through the
    C++ bounded queue as raw bytes."""

    def __init__(self, source_iter, depth=4, capacity_mb=512):
        self._lib = get_lib()
        self._q = self._lib.ptq_create(depth, capacity_mb << 20)
        self._exc = None
        self._thread = threading.Thread(target=self._producer,
                                        args=(source_iter,), daemon=True)
        self._thread.start()

    def _producer(self, source_iter):
        try:
            for batch in source_iter:
                data = _serialize_batch(batch)
                buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
                if self._lib.ptq_push(self._q, buf, len(data)) != 0:
                    return
        except Exception as e:  # surface on the consumer side
            self._exc = e
        finally:
            self._lib.ptq_close(self._q)

    def __iter__(self):
        try:
            while True:
                n = self._lib.ptq_peek_size(self._q)
                if n < 0:
                    break
                out = (ctypes.c_uint8 * n)()
                got = self._lib.ptq_pop(self._q, out, n)
                if got < 0:
                    break
                yield _deserialize_batch(memoryview(out))
            if self._exc is not None:
                raise self._exc
        finally:
            self._lib.ptq_destroy(self._q)
            self._q = None


class HostArena:
    """Size-bucketed host staging allocator (ref role: fluid memory pools)."""

    def __init__(self, limit_bytes=4 << 30):
        self._lib = get_lib()
        self._a = self._lib.arena_create(limit_bytes)

    def alloc(self, nbytes) -> int:
        p = self._lib.arena_alloc(self._a, nbytes)
        if not p:
            raise MemoryError(f"arena alloc of {nbytes} failed")
        return p

    def free(self, ptr: int):
        self._lib.arena_free(self._a, ptr)

    def buffer(self, nbytes):
        """numpy view over an arena block; call free(view.ctypes.data)."""
        ptr = self.alloc(nbytes)
        return np.ctypeslib.as_array(
            ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint8)), (nbytes,)), ptr

    @property
    def reserved_bytes(self):
        return self._lib.arena_reserved_bytes(self._a)

    def __del__(self):
        try:
            self._lib.arena_destroy(self._a)
        except Exception:
            pass
