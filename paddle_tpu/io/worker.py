"""Multiprocess DataLoader workers.

Reference: python/paddle/fluid/dataloader/dataloader_iter.py (796 LoC:
worker processes, shared-memory tensor transport, timeout + error
propagation, get_worker_info). TPU-first rework: workers run
`__getitem__` + collate in their own processes (true parallelism for the
GIL-bound input pipeline), serialize batches to ONE contiguous buffer in
POSIX shared memory, and a parent feeder thread copies each buffer into the
C++ bounded byte-queue (csrc/native_runtime.cpp) with the GIL released —
so batch production, staging and consumption all overlap. Order is restored
by batch index in the feeder; worker exceptions travel as tracebacks and
re-raise at the consumer with the original stack text.
"""
from __future__ import annotations

import itertools
import os
import pickle
import queue as pyqueue
import threading
import traceback
from dataclasses import dataclass

import numpy as np

_TAG_BATCH = b"B"
_TAG_ERR = b"E"
_TAG_END = b"X"


@dataclass
class WorkerInfo:
    id: int
    num_workers: int
    seed: int
    dataset: object = None


_worker_info = None


def get_worker_info():
    """Inside a worker process: this worker's (id, num_workers, seed,
    dataset). In the main process: None. (ref: dataloader_iter.py)"""
    return _worker_info


def _seed_worker(worker_id, base_seed):
    import random
    random.seed(base_seed + worker_id)
    np.random.seed((base_seed + worker_id) % (2 ** 31))


def _worker_loop(dataset, collate_fn, index_queue, result_queue, worker_id,
                 num_workers, base_seed, worker_init_fn, use_shared_memory,
                 iterable_batch_size, iterable_drop_last, persistent=False):
    """Target of each worker process. Map-style: pops (batch_idx, indices)
    tasks. Iterable-style: iterates its own dataset copy (the dataset uses
    get_worker_info() to shard itself) and emits (-1, batch) results.

    persistent: map-style needs no change (the parent simply withholds the
    None sentinel until loader shutdown); iterable-style waits for an
    epoch token per epoch instead of exiting after one pass."""
    global _worker_info
    _worker_info = WorkerInfo(id=worker_id, num_workers=num_workers,
                              seed=base_seed + worker_id, dataset=dataset)
    _seed_worker(worker_id, base_seed)
    if worker_init_fn is not None:
        try:
            worker_init_fn(worker_id)
        except Exception:
            result_queue.put(("err", -1, traceback.format_exc()))
            return

    def emit(batch_idx, batch):
        from .native_loader import _serialize_batch
        data = _serialize_batch(batch)
        if use_shared_memory:
            from multiprocessing import shared_memory
            shm = shared_memory.SharedMemory(create=True, size=len(data))
            shm.buf[:len(data)] = data
            result_queue.put(("shm", batch_idx, shm.name, len(data)))
            shm.close()  # parent attaches + unlinks
        else:
            result_queue.put(("data", batch_idx, data))

    try:
        if iterable_batch_size is not None:  # iterable mode
            while True:
                if persistent:
                    tok = index_queue.get()
                    if tok is None:  # shutdown
                        return
                it = iter(dataset)
                while True:
                    batch = list(itertools.islice(it, iterable_batch_size))
                    if not batch or (len(batch) < iterable_batch_size
                                     and iterable_drop_last):
                        break
                    emit(-1, collate_fn(batch))
                result_queue.put(("done", worker_id, None))
                if not persistent:
                    return
        while True:
            task = index_queue.get()
            if task is None:
                break
            batch_idx, indices = task
            try:
                emit(batch_idx, collate_fn([dataset[i] for i in indices]))
            except Exception:
                result_queue.put(("err", batch_idx, traceback.format_exc()))
    except (KeyboardInterrupt, EOFError):
        pass


class _ByteChannel:
    """Parent-side staging channel: the C++ bounded byte-queue when the
    native lib builds, else a plain python queue. Frames are tag + payload."""

    def __init__(self, depth, capacity_mb=1024):
        import ctypes
        self._ctypes = ctypes
        try:
            from .native_loader import get_lib
            self._lib = get_lib()
            self._q = self._lib.ptq_create(depth, capacity_mb << 20)
            self._py = None
        except Exception as e:
            # same warn-once policy as the DataLoader fallbacks: a silent
            # native->python downgrade is a hidden perf cliff
            import warnings
            if not getattr(_ByteChannel, "_warned", False):
                _ByteChannel._warned = True
                warnings.warn(
                    "native C++ byte-queue unavailable, using a Python "
                    f"queue: {type(e).__name__}: {str(e)[:120]}",
                    RuntimeWarning, stacklevel=2)
            self._lib = None
            self._py = pyqueue.Queue(maxsize=depth)

    _closed = False

    def push(self, tag, payload):
        if self._lib is None:
            if not self._closed:  # closed: drop, like the native queue's -1
                self._py.put(tag + payload)
            return
        buf = (self._ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
        self._lib.ptq_push_tagged(self._q, tag[0], buf, len(payload))

    def push_shm_frame(self, tag, shm_buf, nbytes):
        """Copy straight out of shared memory into the C++ queue — the
        memcpy runs inside ptq_push_tagged with the GIL released."""
        if self._lib is None:
            if not self._closed:
                self._py.put(tag + bytes(shm_buf[:nbytes]))
            return
        buf = (self._ctypes.c_uint8 * nbytes).from_buffer(shm_buf)
        self._lib.ptq_push_tagged(self._q, tag[0], buf, nbytes)

    def pop(self, timeout=None):
        """Returns (tag, payload_memoryview) or None on timeout."""
        if self._lib is None:
            try:
                data = self._py.get(timeout=timeout)
            except pyqueue.Empty:
                return None
            return data[:1], memoryview(data)[1:]
        ms = int((timeout or 3600) * 1000)
        out_cap = 1 << 16
        while True:
            out = (self._ctypes.c_uint8 * out_cap)()
            r = self._lib.ptq_pop_timed(self._q, out, out_cap, ms)
            if r == -3:
                return None
            if r == -1:
                return _TAG_END, memoryview(b"")
            if r == -2:
                n = self._lib.ptq_peek_size(self._q)
                if n < 0:
                    return _TAG_END, memoryview(b"")
                out_cap = int(n)
                continue
            data = memoryview(out)[:int(r)]
            return bytes(data[:1]), data[1:]

    def close(self):
        if self._lib is not None:
            self._lib.ptq_close(self._q)
            return
        # python fallback: new pushes drop from now on (a put() already
        # blocked on the full queue still needs a consumer pop to finish —
        # _shutdown_workers pops while joining the feeder for that)
        self._closed = True

    def destroy(self):
        if self._lib is not None:
            self._lib.ptq_destroy(self._q)


def _mp_context():
    import multiprocessing as mp
    method = os.environ.get("PADDLE_TPU_MP_START")
    if method:
        return mp.get_context(method)
    # fork is fast and fine for numpy datasets; spawn-safe code paths are
    # kept (everything pickled is module-level)
    try:
        return mp.get_context("fork")
    except ValueError:  # pragma: no cover - non-posix
        return mp.get_context("spawn")


class MultiprocessLoaderIter:
    """One epoch's iterator over worker processes (map or iterable style)."""

    def __init__(self, dataset, collate_fn, batches, num_workers,
                 prefetch_factor=2, timeout=0, worker_init_fn=None,
                 use_shared_memory=True, iterable_batch_size=None,
                 iterable_drop_last=False, base_seed=None, persistent=False):
        ctx = _mp_context()
        self.timeout = timeout or None
        self.num_workers = num_workers
        self._iterable = iterable_batch_size is not None
        self._batches = list(batches) if batches is not None else None
        self._persistent = persistent
        self._result_queue = ctx.Queue()
        self._index_queue = ctx.Queue() if not self._iterable else None
        # iterable+persistent: epoch tokens must be PER-WORKER queues — in
        # a shared queue a fast worker pops both tokens, runs its shard
        # twice and the feeder's done-count closes the epoch while the
        # starved worker's shard never arrives (flaky dup/drop)
        self._index_queues = [ctx.Queue() for _ in range(num_workers)] \
            if (self._iterable and persistent) else None
        depth = max(2, num_workers * prefetch_factor)
        self._chan = _ByteChannel(depth)
        self._shutdown = False
        base_seed = np.random.randint(1 << 30) if base_seed is None \
            else base_seed

        self._workers = []
        for wid in range(num_workers):
            w = ctx.Process(
                target=_worker_loop,
                args=(dataset, collate_fn,
                      self._index_queues[wid] if self._index_queues
                      else self._index_queue,
                      self._result_queue, wid, num_workers, base_seed,
                      worker_init_fn, use_shared_memory,
                      iterable_batch_size, iterable_drop_last, persistent),
                daemon=True)
            w.start()
            self._workers.append(w)

        if persistent:
            return  # epochs armed explicitly via reset()/epoch()
        if not self._iterable:
            self._n_batches = len(self._batches)
            for task in enumerate(self._batches):
                self._index_queue.put(task)
            for _ in range(num_workers):
                self._index_queue.put(None)
        self._feeder = threading.Thread(target=self._feed, daemon=True)
        self._feeder.start()

    # -- persistent-workers protocol (ref: persistent_workers=True) -------
    def reset(self, batches=None):
        """Arm one epoch on the live worker pool: push the epoch's tasks
        (map) or one epoch token per worker (iterable) and start a fresh
        feeder. Workers stay alive across epochs; worker_init_fn ran once
        at spawn (reference persistent_workers semantics)."""
        assert self._persistent and not self._shutdown
        # a previous epoch abandoned mid-iteration (consumer broke out of
        # epoch()) leaves its feeder running and frames in the channel —
        # let the workers drain the already-queued tasks, then discard the
        # stale frames, or they would leak into this epoch's stream
        feeder = getattr(self, "_feeder", None)
        if feeder is not None and feeder.is_alive():
            # the feeder may be BLOCKED pushing into the full bounded
            # channel — joining first would deadlock. Drain concurrently
            # until it exits (every pop frees a slot for its next push; the
            # workers finish the old epoch's queued tasks, so the feeder's
            # receive loop terminates), then discard whatever is left.
            # Drain until the feeder exits. The stall guard is PROGRESS
            # based, not iteration based: as long as frames keep arriving
            # the workers are healthy (however slow), matching the
            # loader's own timeout semantics (self.timeout, None = wait
            # forever → a generous stall default applies only here).
            import time as _time
            stall_limit = self.timeout or 300.0
            last_progress = _time.time()
            while feeder.is_alive():
                # tight drain: pop until the channel is momentarily empty.
                # Stop on an END frame too: a CLOSED channel's pop returns
                # END forever, never None.
                got = self._chan.pop(timeout=0.02)
                while got is not None and got[0] != _TAG_END:
                    last_progress = _time.time()
                    got = self._chan.pop(timeout=0.02)
                feeder.join(timeout=0.05)
                if _time.time() - last_progress > stall_limit:
                    break
            if feeder.is_alive():
                self._shutdown_workers()
                raise RuntimeError(
                    "persistent DataLoader could not finish the abandoned "
                    "previous epoch (worker dead or stalled)")
        if getattr(self, "_epoch_open", False):
            while True:
                got = self._chan.pop(timeout=0.05)
                if got is None or got[0] == _TAG_END:
                    break
        self._epoch_open = True
        if self._iterable:
            for q in self._index_queues:
                q.put(True)  # exactly one epoch token per worker
        else:
            self._batches = list(batches)
            self._n_batches = len(self._batches)
            for task in enumerate(self._batches):
                self._index_queue.put(task)
        self._feeder = threading.Thread(target=self._feed, daemon=True)
        self._feeder.start()

    def epoch(self, batches=None):
        """One epoch's batch stream off the persistent pool; the pool
        survives the END marker (shutdown only on error or close())."""
        from .native_loader import _deserialize_batch
        self.reset(batches)
        while True:
            got = self._chan.pop(timeout=self.timeout)
            if got is None:
                self._shutdown_workers()
                raise RuntimeError(
                    f"DataLoader timed out after {self.timeout}s")
            tag, payload = got
            if tag == _TAG_END:
                self._epoch_open = False
                return
            if tag == _TAG_ERR:
                self._shutdown_workers()
                raise RuntimeError("DataLoader worker failed:\n"
                                   + pickle.loads(bytes(payload)))
            yield _deserialize_batch(payload)

    def close(self):
        """Persistent-pool shutdown: release the workers via sentinels."""
        if self._shutdown:
            return
        if self._index_queues is not None:
            for q in self._index_queues:
                q.put(None)
        else:
            for _ in range(self.num_workers):
                self._index_queue.put(None)
        self._shutdown_workers()

    # -- feeder thread: result_queue -> (reorder) -> byte channel ---------
    def _feed(self):
        try:
            if self._iterable:
                done = 0
                while done < self.num_workers:
                    msg = self._get_result()
                    if msg is None:
                        return  # timeout error already pushed
                    kind, idx, a, b = msg
                    if kind == "done":
                        done += 1
                        continue
                    self._push_result(kind, a, b)
                self._chan.push(_TAG_END, b"")
                return
            received = 0
            reorder = {}
            next_out = 0
            while received < self._n_batches:
                msg = self._get_result()
                if msg is None:
                    return
                kind, idx, a, b = msg
                received += 1
                reorder[idx] = (kind, a, b)
                while next_out in reorder:
                    self._push_result(*reorder.pop(next_out))
                    next_out += 1
            self._chan.push(_TAG_END, b"")
        except Exception:
            try:
                self._chan.push(_TAG_ERR, pickle.dumps(
                    traceback.format_exc()))
            except Exception:
                pass
        finally:
            if not self._persistent:
                self._chan.close()

    def _get_result(self):
        try:
            msg = self._result_queue.get(timeout=self.timeout)
        except pyqueue.Empty:
            self._chan.push(_TAG_ERR, pickle.dumps(
                f"DataLoader timed out after {self.timeout}s waiting for a "
                f"worker batch ({sum(w.is_alive() for w in self._workers)}"
                f"/{self.num_workers} workers alive)"))
            self._chan.close()
            return None
        if len(msg) == 3:
            msg = (*msg, None)
        return msg

    def _push_result(self, kind, a, b):
        if kind == "err":
            self._chan.push(_TAG_ERR, pickle.dumps(a))
        elif kind == "shm":
            from multiprocessing import shared_memory
            shm = shared_memory.SharedMemory(name=a)
            try:
                self._chan.push_shm_frame(_TAG_BATCH, shm.buf, b)
            finally:
                shm.close()
                shm.unlink()
        else:  # inline bytes
            self._chan.push(_TAG_BATCH, a)

    # -- consumer ----------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        from .native_loader import _deserialize_batch
        if self._shutdown:
            raise StopIteration
        got = self._chan.pop(timeout=self.timeout)
        if got is None:
            self._shutdown_workers()
            raise RuntimeError(
                f"DataLoader timed out after {self.timeout}s")
        tag, payload = got
        if tag == _TAG_END:
            self._shutdown_workers()
            raise StopIteration
        if tag == _TAG_ERR:
            self._shutdown_workers()
            raise RuntimeError(
                "DataLoader worker failed:\n" + pickle.loads(bytes(payload)))
        return _deserialize_batch(payload)

    def _shutdown_workers(self):
        if self._shutdown:
            return
        self._shutdown = True
        # close first: a feeder blocked in the native queue's push wakes
        # with -1 (closed) instead of being destroyed under mid-wait, and
        # consumer pops see END. Then join the feeder, join/terminate the
        # workers, and unlink any shm segments still parked in the result
        # queue — TWICE, because a worker mid-emit can enqueue after the
        # first drain (abandoned-epoch shutdown would leak them).
        self._chan.close()
        feeder = getattr(self, "_feeder", None)
        if feeder is not None and feeder.is_alive():
            # native queue: push now returns "closed" and the feeder exits
            # on its own. Python fallback: a put() already blocked on the
            # full queue needs pops to complete — drain while joining.
            deadline = 200
            while feeder.is_alive() and deadline > 0:
                self._chan.pop(timeout=0.02)
                feeder.join(timeout=0.05)
                deadline -= 1

        def _drain_shm():
            while True:
                try:
                    msg = self._result_queue.get_nowait()
                except pyqueue.Empty:
                    break
                except Exception:  # pragma: no cover - closed queue
                    break
                if msg and msg[0] == "shm":
                    from multiprocessing import shared_memory
                    try:
                        shm = shared_memory.SharedMemory(name=msg[2])
                        shm.close()
                        shm.unlink()
                    except Exception:
                        pass

        _drain_shm()
        for w in self._workers:
            w.join(timeout=5)
        for w in self._workers:
            if w.is_alive():  # pragma: no cover - stuck worker
                w.terminate()
        _drain_shm()
        if feeder is None or not feeder.is_alive():
            self._chan.destroy()
        # else: deliberately LEAK the (closed, near-empty) queue — freeing
        # it under a wedged daemon feeder would be a use-after-free; the
        # allocation is a few KB and the thread dies with the process

    def __del__(self):  # pragma: no cover - gc path
        try:
            self._shutdown_workers()
        except Exception:
            pass
