"""paddle.text (ref: python/paddle/text/) — dataset APIs; synthetic fallbacks
in the zero-egress environment."""
from __future__ import annotations

import numpy as np

from ..io import Dataset


class Imdb(Dataset):
    def __init__(self, data_file=None, mode="train", cutoff=150):
        n = 2000 if mode == "train" else 400
        rng = np.random.RandomState(7)
        self.docs = [rng.randint(1, 5000, rng.randint(20, 200)).astype(np.int64)
                     for _ in range(n)]
        self.labels = rng.randint(0, 2, n).astype(np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class WMT14(Dataset):
    def __init__(self, data_file=None, mode="train", dict_size=30000):
        n = 1000 if mode == "train" else 200
        rng = np.random.RandomState(8)
        self.src = [rng.randint(1, dict_size, rng.randint(5, 50)).astype(np.int64)
                    for _ in range(n)]
        self.tgt = [rng.randint(1, dict_size, rng.randint(5, 50)).astype(np.int64)
                    for _ in range(n)]

    def __getitem__(self, idx):
        return self.src[idx], self.tgt[idx][:-1], self.tgt[idx][1:]

    def __len__(self):
        return len(self.src)


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True):
    """CRF Viterbi decode via lax.scan (ref: viterbi_decode_op)."""
    import jax
    import jax.numpy as jnp

    from ..core.tensor import Tensor
    pot = potentials._value if isinstance(potentials, Tensor) \
        else jnp.asarray(potentials)
    trans = transition_params._value if isinstance(transition_params, Tensor) \
        else jnp.asarray(transition_params)

    def step(alpha, logit_t):
        scores = alpha[:, :, None] + trans[None]
        best = jnp.max(scores, axis=1) + logit_t
        idx = jnp.argmax(scores, axis=1)
        return best, idx

    alpha0 = pot[:, 0]
    _, idxs = jax.lax.scan(step, alpha0, jnp.moveaxis(pot[:, 1:], 1, 0))
    alpha_final, _ = jax.lax.scan(step, alpha0, jnp.moveaxis(pot[:, 1:], 1, 0))
    scores = jnp.max(alpha_final, axis=-1)
    last = jnp.argmax(alpha_final, axis=-1)

    def backtrack(carry, idx_t):
        tag = carry
        prev = jnp.take_along_axis(idx_t, tag[:, None], axis=1)[:, 0]
        return prev, prev

    _, path_rev = jax.lax.scan(backtrack, last, jnp.flip(idxs, 0))
    path = jnp.concatenate([jnp.flip(path_rev, 0),
                            last[None]], axis=0)
    return Tensor(scores), Tensor(jnp.moveaxis(path, 0, 1))
