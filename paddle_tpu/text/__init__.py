"""paddle.text (ref: python/paddle/text/) — dataset APIs; synthetic fallbacks
in the zero-egress environment."""
from __future__ import annotations

import numpy as np

from ..io import Dataset


class Imdb(Dataset):
    """IMDB sentiment (ref: python/paddle/text/datasets/imdb.py). With
    `data_file` it parses the PUBLISHED aclImdb_v1.tar.gz layout —
    aclImdb/<mode>/{pos,neg}/*.txt members, frequency-sorted word dict
    with `cutoff`, <unk> last — else deterministic synthetic docs."""

    def __init__(self, data_file=None, mode="train", cutoff=150):
        import os
        if data_file and os.path.exists(data_file):
            self._load_archive(data_file, mode, cutoff)
            return
        n = 2000 if mode == "train" else 400
        rng = np.random.RandomState(7)
        self.word_idx = {i: i for i in range(5000)}
        self.docs = [rng.randint(1, 5000, rng.randint(20, 200)).astype(np.int64)
                     for _ in range(n)]
        self.labels = rng.randint(0, 2, n).astype(np.int64)

    @staticmethod
    def _tokenize(text):
        import re
        import string
        return re.sub(f"[{re.escape(string.punctuation)}]", "",
                      text.lower()).split()

    def _load_archive(self, data_file, mode, cutoff):
        import re
        import tarfile
        # vocabulary spans BOTH splits (ref imdb.py:95 builds the dict over
        # aclImdb/((train)|(test))), so train/test token ids agree; docs
        # come from the requested mode only. One getmembers() pass —
        # per-name extractfile is a reverse linear scan of the archive.
        dict_pat = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        mode_pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        texts, labels = [], []
        freq = {}
        with tarfile.open(data_file, "r:*") as tf:
            for member in sorted(tf.getmembers(), key=lambda m: m.name):
                if not dict_pat.match(member.name):
                    continue
                toks = self._tokenize(tf.extractfile(member).read()
                                      .decode("utf-8", "replace"))
                for w in toks:
                    freq[w] = freq.get(w, 0) + 1
                m = mode_pat.match(member.name)
                if m:
                    texts.append(toks)
                    labels.append(0 if m.group(1) == "pos" else 1)  # pos=0
        kept = {w: c for w, c in freq.items() if c > cutoff} or freq
        ordered = sorted(kept.items(), key=lambda kv: (-kv[1], kv[0]))
        self.word_idx = {w: i for i, (w, _) in enumerate(ordered)}
        unk = self.word_idx["<unk>"] = len(self.word_idx)
        self.docs = [np.asarray([self.word_idx.get(w, unk) for w in toks],
                                np.int64) for toks in texts]
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class WMT14(Dataset):
    def __init__(self, data_file=None, mode="train", dict_size=30000):
        n = 1000 if mode == "train" else 200
        rng = np.random.RandomState(8)
        self.src = [rng.randint(1, dict_size, rng.randint(5, 50)).astype(np.int64)
                    for _ in range(n)]
        self.tgt = [rng.randint(1, dict_size, rng.randint(5, 50)).astype(np.int64)
                    for _ in range(n)]

    def __getitem__(self, idx):
        return self.src[idx], self.tgt[idx][:-1], self.tgt[idx][1:]

    def __len__(self):
        return len(self.src)


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True):
    """CRF Viterbi decode via lax.scan (ref: viterbi_decode_op)."""
    import jax
    import jax.numpy as jnp

    from ..core.tensor import Tensor
    pot = potentials._value if isinstance(potentials, Tensor) \
        else jnp.asarray(potentials)
    trans = transition_params._value if isinstance(transition_params, Tensor) \
        else jnp.asarray(transition_params)

    def step(alpha, logit_t):
        scores = alpha[:, :, None] + trans[None]
        best = jnp.max(scores, axis=1) + logit_t
        idx = jnp.argmax(scores, axis=1)
        return best, idx

    alpha0 = pot[:, 0]
    _, idxs = jax.lax.scan(step, alpha0, jnp.moveaxis(pot[:, 1:], 1, 0))
    alpha_final, _ = jax.lax.scan(step, alpha0, jnp.moveaxis(pot[:, 1:], 1, 0))
    scores = jnp.max(alpha_final, axis=-1)
    last = jnp.argmax(alpha_final, axis=-1)

    def backtrack(carry, idx_t):
        tag = carry
        prev = jnp.take_along_axis(idx_t, tag[:, None], axis=1)[:, 0]
        return prev, prev

    _, path_rev = jax.lax.scan(backtrack, last, jnp.flip(idxs, 0))
    path = jnp.concatenate([jnp.flip(path_rev, 0),
                            last[None]], axis=0)
    return Tensor(scores), Tensor(jnp.moveaxis(path, 0, 1))


class Imikolov(Dataset):
    """PTB-style n-gram dataset (ref: python/paddle/text/datasets/imikolov.py);
    synthetic corpus in the zero-egress environment."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50):
        n = 5000 if mode == "train" else 500
        rng = np.random.RandomState(11)
        vocab = 2000
        self.window_size = window_size
        corpus = rng.zipf(1.5, n + window_size) % vocab
        self.samples = [corpus[i:i + window_size].astype(np.int64)
                        for i in range(n)]

    def __getitem__(self, idx):
        s = self.samples[idx]
        return tuple(s[:-1]) + (s[-1:],)

    def __len__(self):
        return len(self.samples)


class Movielens(Dataset):
    """MovieLens ratings (ref: python/paddle/text/datasets/movielens.py);
    synthetic (user, gender, age, job, movie, category, title, rating)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0):
        rng = np.random.RandomState(rand_seed)
        n = 4000 if mode == "train" else 400
        self.rows = [(
            rng.randint(1, 6041), rng.randint(0, 2), rng.randint(0, 7),
            rng.randint(0, 21), rng.randint(1, 3953),
            rng.randint(0, 19, 3).astype(np.int64),
            rng.randint(1, 5000, 4).astype(np.int64),
            np.float32(rng.randint(1, 6)),
        ) for _ in range(n)]

    def __getitem__(self, idx):
        return self.rows[idx]

    def __len__(self):
        return len(self.rows)


class UCIHousing(Dataset):
    """Boston housing regression (ref: python/paddle/text/datasets/
    uci_housing.py). With `data_file` it parses the published
    housing.data layout (whitespace rows, 14 columns, feature-range
    normalization, 80/20 train/test split like the reference); else
    synthetic 13-feature rows."""

    def __init__(self, data_file=None, mode="train"):
        import os
        if data_file and os.path.exists(data_file):
            data = np.loadtxt(data_file).astype(np.float32)
            assert data.shape[1] == 14, data.shape
            feats = data[:, :-1]
            mn, mx = feats.min(0), feats.max(0)
            feats = (feats - feats.mean(0)) / np.maximum(mx - mn, 1e-12)
            split = int(data.shape[0] * 0.8)
            sl = slice(0, split) if mode == "train" else slice(split, None)
            self.x = feats[sl]
            self.y = data[sl, -1]
            return
        rng = np.random.RandomState(3)
        n = 404 if mode == "train" else 102
        self.x = rng.randn(n, 13).astype(np.float32)
        w = rng.randn(13).astype(np.float32)
        self.y = (self.x @ w + rng.randn(n) * 0.1).astype(np.float32)

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx:idx + 1]

    def __len__(self):
        return len(self.x)


class Conll05st(Dataset):
    """CoNLL-2005 SRL dataset (ref: python/paddle/text/datasets/conll05.py);
    synthetic (word, predicate, ctx windows, mark, label) id rows."""

    WORD_DICT_LEN = 44068
    LABEL_DICT_LEN = 59
    PRED_DICT_LEN = 3162

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, mode="train"):
        rng = np.random.RandomState(5)
        n = 1000 if mode == "train" else 100
        self.rows = []
        for _ in range(n):
            ln = rng.randint(5, 30)
            words = rng.randint(0, self.WORD_DICT_LEN, ln).astype(np.int64)
            pred = np.full(ln, rng.randint(0, self.PRED_DICT_LEN),
                           np.int64)
            mark = (rng.rand(ln) < 0.1).astype(np.int64)
            label = rng.randint(0, self.LABEL_DICT_LEN, ln).astype(np.int64)
            ctx = [np.roll(words, s) for s in (-2, -1, 0, 1, 2)]
            self.rows.append(tuple([words] + ctx + [pred, mark, label]))

    def __getitem__(self, idx):
        return self.rows[idx]

    def __len__(self):
        return len(self.rows)


class WMT16(WMT14):
    """WMT16 en-de (ref: python/paddle/text/datasets/wmt16.py); same synthetic
    contract as WMT14 with a BPE-sized vocab."""

    def __init__(self, data_file=None, mode="train", src_dict_size=10000,
                 trg_dict_size=10000, lang="en"):
        super().__init__(data_file, mode, dict_size=src_dict_size)


import sys as _sys  # noqa: E402

datasets = _sys.modules[__name__]  # paddle.text.datasets alias

# the reference's text/datasets also binds the 1.x reader modules as
# attributes (ref: text/datasets/__init__.py import list)
from ..dataset import (  # noqa: E402,F401
    conll05, imdb, imikolov, movielens, uci_housing, wmt14, wmt16)

# register the alias and its corpus leaves as IMPORTABLE module paths so
# `import paddle.text.datasets.imdb` (the reference's layout) resolves,
# not just attribute access (r4 module-path parity)
_sys.modules[__name__ + ".datasets"] = datasets
for _n, _m in (("conll05", conll05), ("imdb", imdb),
               ("imikolov", imikolov), ("movielens", movielens),
               ("uci_housing", uci_housing), ("wmt14", wmt14),
               ("wmt16", wmt16)):
    _sys.modules[f"{__name__}.datasets.{_n}"] = _m
