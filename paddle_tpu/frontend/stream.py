"""Token-by-token streaming for the serving front door (round 12).

The engine (`PagedGenerationServer._slot_token`) invokes a per-request
`on_token(token, reason)` callback from its loop thread for every
generated token. This module turns that callback into a consumer-facing
stream:

  * `DeltaAssembler` — incremental detokenization with STOP-STRING-SAFE
    release: before any delta is handed out, the tail of the
    accumulated text is re-checked against the request's stop strings
    (bounded-tail, like the engine's own stop check) and any suffix
    that could still grow into a stop string is HELD BACK. Released
    text therefore never contains a suppressed stop-string suffix —
    not even transiently, token by token (the round-12 satellite fix).
  * `StreamHandle` — the object `FrontDoor.submit` returns: an
    iterator of `StreamEvent`s plus the classic `result()` future
    surface. Delivery is BACKPRESSURE-AWARE without ever blocking the
    engine: the event buffer is bounded, and once a slow consumer
    falls `max_buffered` events behind, new deltas COALESCE into the
    newest undelivered event (text concatenated, token ids appended)
    instead of growing the queue — memory stays bounded, no token or
    character is ever dropped, and the consumer simply sees coarser
    events until it catches up.

Detokenizer contract: deltas are computed over a bounded token tail
(`tail_tokens`, the engine's `stop_tail_tokens` by default), so the
`detokenize` callable must be prefix-stable within that window —
appending one token may only append characters. This is the same
contract the engine's host-side stop-string check already relies on.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field

from ..observability import metrics as _metrics
from ..reliability import QuarantinedRequest, RequestTimeout

_m_stream_events = _metrics.counter(
    "frontdoor_stream_events_total",
    "stream events delivered to consumers (post-coalescing)")
_m_stream_coalesced = _metrics.counter(
    "frontdoor_stream_coalesced_total",
    "token deltas merged into an undelivered event because the "
    "consumer fell max_buffered events behind (backpressure)")


@dataclass
class StreamEvent:
    """One streamed increment: `text` is the SAFE detokenized delta
    (may be empty while the assembler holds back a possible
    stop-string prefix, or when the server has no detokenizer),
    `token_ids` the raw tokens it covers. On the final event `done` is
    True and `stop_reason` is one of eos / stop_token / stop_string /
    budget (or "error" if the request failed)."""
    text: str = ""
    token_ids: tuple = ()
    done: bool = False
    stop_reason: str | None = None


class DeltaAssembler:
    """Stop-string-safe incremental detokenizer.

    push(tok) returns the text this token makes SAFE to release; the
    unreleased remainder (a suffix that is a proper prefix of some
    stop string, or everything from a completed match onward) stays
    pending. finish(reason) flushes: for reason == "stop_string" the
    earliest stop-string match and everything after it is suppressed;
    any other reason releases the pending text verbatim.

    Invariant (inductive): released text never ends with a non-empty
    proper prefix of a stop string, so every possible match lies
    entirely inside the pending buffer and can still be suppressed.
    """

    def __init__(self, detokenize, stop_strings=(), tail_tokens=16):
        if detokenize is None:
            raise ValueError("DeltaAssembler needs a detokenize callable")
        self._detok = detokenize
        self._stops = tuple(s for s in (stop_strings or ()) if s)
        self._w = max(1, int(tail_tokens))
        self._toks: list[int] = []
        self._pending = ""

    def _delta(self, tok):
        """Text `tok` appends, over the bounded tail window."""
        prev = self._toks[-(self._w - 1):] if self._w > 1 else []
        self._toks.append(tok)
        before = self._detok(prev) if prev else ""
        after = self._detok(prev + [tok])
        return after[len(before):]

    def _earliest_match(self, text):
        cut = None
        for s in self._stops:
            j = text.find(s)
            if j >= 0:
                cut = j if cut is None else min(cut, j)
        return cut

    def _holdback(self):
        """Longest suffix of pending that is a PROPER prefix of some
        stop string — the characters that could still grow into a
        match and must not be released yet."""
        h = 0
        for s in self._stops:
            for ln in range(min(len(s) - 1, len(self._pending)), h, -1):
                if self._pending.endswith(s[:ln]):
                    h = ln
                    break
        return h

    def push(self, tok):
        """Feed one generated token; returns the safe-to-release text
        (possibly empty)."""
        self._pending += self._delta(int(tok))
        if not self._stops:
            out, self._pending = self._pending, ""
            return out
        cut = self._earliest_match(self._pending)
        if cut is not None:
            # a full stop string is present: release only the text
            # before it; the match (and anything after) can only ever
            # be suppressed or re-examined at finish()
            out = self._pending[:cut]
            self._pending = self._pending[cut:]
            return out
        h = self._holdback()
        out = self._pending[:len(self._pending) - h]
        self._pending = self._pending[len(self._pending) - h:]
        return out

    def finish(self, reason):
        """Flush at end of generation. Returns the final releasable
        text (the suppressed stop string never appears in it)."""
        out, self._pending = self._pending, ""
        if reason == "stop_string" and self._stops:
            cut = self._earliest_match(out)
            if cut is not None:
                out = out[:cut]
        return out

    @property
    def pending(self):
        return self._pending


class StreamHandle:
    """Consumer handle for one streamed request.

    Iterate for `StreamEvent`s (blocks until events arrive; ends after
    the final event), or call `result(timeout)` for the classic full
    [prompt + generated] array. `text()` returns the released text so
    far; `stop_reason`/`done` report final state. The producer side
    (`_on_token`, engine thread) never blocks: past `max_buffered`
    undelivered events, deltas coalesce into the newest one.

    timeout_s (r17): per-GAP iterator timeout — iterating raises
    `TimeoutError` when no event arrives for this many seconds, so a
    dead or wedged engine can never hang a consumer thread forever
    (the iterator-side twin of `result(timeout=)`). Streams whose
    request was quarantined or timed out by the engine terminate with
    `stop_reason` "quarantined" / "timeout" instead of "error".
    """

    def __init__(self, detokenize=None, stop_strings=(),
                 tail_tokens=16, max_buffered=256, timeout_s=None):
        self._asm = (DeltaAssembler(detokenize, stop_strings,
                                    tail_tokens)
                     if detokenize is not None else None)
        self._cv = threading.Condition()
        self._events: deque[StreamEvent] = deque()
        self._tokens: list[int] = []
        self._chunks: list[str] = []
        self._done = False
        self._stop_reason: str | None = None
        self._max = max(1, int(max_buffered))
        if timeout_s is not None and float(timeout_s) <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self._timeout = None if timeout_s is None else float(timeout_s)
        self.coalesced = 0
        self._future = None
        self._bind_gen = 0  # rebind epoch: stale futures are ignored

    # ---- producer side (engine thread) --------------------------------
    def _on_token(self, tok, reason):
        tok = int(tok)
        delta = ""
        if self._asm is not None:
            delta = self._asm.push(tok)
            if reason is not None:
                delta += self._asm.finish(reason)
        with self._cv:
            self._tokens.append(tok)
            if delta:
                self._chunks.append(delta)
            if len(self._events) >= self._max:
                last = self._events[-1]  # coalesce: bounded memory,
                last.text += delta       # engine never blocks
                last.token_ids += (tok,)
                self.coalesced += 1
                _m_stream_coalesced.inc()
            else:
                self._events.append(StreamEvent(text=delta,
                                                token_ids=(tok,)))
            if reason is not None:
                self._events[-1].done = True
                self._events[-1].stop_reason = reason
                self._done = True
                self._stop_reason = reason
            self._cv.notify_all()

    def _bind(self, future):
        """Attach the engine future; a request that dies without a
        final token (dispatch failure, server stop) still terminates
        the stream via the future's done callback."""
        with self._cv:
            self._future = future
            self._bind_gen += 1
            gen = self._bind_gen
        future.add_done_callback(
            lambda f: self._on_future_done(f, gen))
        return self

    def rebind(self, future):
        """RE-ATTACH the stream to a new engine future (fleet round:
        failover/migration moved the session to another replica).
        Token delivery simply continues — the new replica resumes at
        the next undelivered token, so the consumer sees one
        uninterrupted stream — and any terminal outcome of the OLD
        future after this point is ignored (its generation is stale).
        `result()` now reports the new future's outcome. No-op safe
        on a stream that already finished."""
        return self._bind(future)

    def _on_future_done(self, fut, gen=None):
        with self._cv:
            if gen is not None and gen != self._bind_gen:
                return  # stale binding: the stream was rebound
            if not self._done:
                self._done = True
                exc = fut.exception()
                if exc is not None:
                    # r17: quarantine / timeout terminations are their
                    # own stop reasons, not a generic "error"
                    if isinstance(exc, QuarantinedRequest):
                        reason = "quarantined"
                    elif isinstance(exc, RequestTimeout):
                        reason = "timeout"
                    else:
                        reason = "error"
                    self._stop_reason = reason
                    self._events.append(StreamEvent(
                        done=True, stop_reason=reason))
            self._cv.notify_all()

    # ---- consumer side -------------------------------------------------
    def __iter__(self):
        while True:
            deadline = (None if self._timeout is None
                        else time.monotonic() + self._timeout)
            with self._cv:
                while not self._events and not self._done:
                    if deadline is not None \
                            and time.monotonic() >= deadline:
                        raise TimeoutError(
                            f"stream produced no event for "
                            f"{self._timeout:g}s (engine dead or "
                            f"wedged?)")
                    self._cv.wait(timeout=0.1)
                if self._events:
                    ev = self._events.popleft()
                else:
                    return  # done and drained
            _m_stream_events.inc()
            yield ev
            if ev.done:
                return

    def result(self, timeout=None):
        """The classic submit/drain surface: the full
        [prompt + generated] int32 array (raises what the engine
        raised)."""
        return self._future.result(timeout=timeout)

    def text(self):
        """Released text so far (never includes a suppressed stop
        string suffix)."""
        with self._cv:
            return "".join(self._chunks)

    @property
    def tokens(self):
        with self._cv:
            return list(self._tokens)

    @property
    def done(self):
        with self._cv:
            return self._done

    @property
    def stop_reason(self):
        with self._cv:
            return self._stop_reason
