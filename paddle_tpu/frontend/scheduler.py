"""SLO-lane / multi-tenant scheduler for `PagedGenerationServer`
(round 12).

The engine stays mechanism-only (reservation admission, packed chunk
prefill, preemption swap-out); this module is the POLICY it consults
when a scheduler is installed (`server.set_scheduler(...)`):

  * Two SLO lanes — "interactive" (TTFT-sensitive; ordered earliest-
    deadline-first) and "batch" (throughput; ordered by per-tenant
    stride fair share). Lane service is weighted (default 4:1
    interactive:batch) via served/weight counters, so neither lane
    starves; a lane whose head candidate is blocked on resources is
    set aside for the pass instead of head-of-line-blocking the other
    lane.
  * Multi-tenancy — per-tenant FIFO queues inside each lane, stride
    scheduling across tenants by `TenantConfig.weight`, token-bucket
    rate limits (throttled tenants stay queued but ineligible — delay,
    not rejection), and bounded queues with EXPLICIT rejection
    (`QueueFull` raised at submit, counted).
  * Preemption policy — when an interactive candidate is blocked on a
    slot or blocks, `victims()` names batch-lane slots newest-first;
    interactive never preempts interactive, batch never preempts
    anyone, and a candidate WAITS instead of preempting while some
    resident is within `preempt_wait_tokens` of finishing (drain-wait
    hysteresis — unless the candidate's deadline already passed). The
    engine performs the swap-out and calls `requeue`, which puts the
    victim at the FRONT of its tenant queue.
  * Prefill chunk sharing — `prefill_plan` orders feeding slots
    interactive-(EDF)-first and, when both lanes are feeding, caps the
    interactive lane at `interactive_chunk_share` of the chunk budget
    so batch prompts keep a guaranteed share and interactive keeps its
    latency priority.

All methods that read time take `now` explicitly (the engine passes
one `time.perf_counter()` per pass), so the whole policy is
deterministic under test. Engine calls arrive under the server lock.
"""
from __future__ import annotations

from collections import deque

from ..inference.serving import RequestMeta
from ..observability import metrics as _metrics
from .tenancy import QueueFull, TenantConfig, TokenBucket

LANES = ("interactive", "batch")

_m_lane_queue = _metrics.gauge(
    "serving_lane_queue_depth",
    "queued requests per SLO lane (front-door scheduler)",
    labelnames=("lane",))
_m_tenant_queue = _metrics.gauge(
    "serving_tenant_queue_depth",
    "queued requests per tenant (front-door scheduler)",
    labelnames=("tenant",))
_m_rejected = _metrics.counter(
    "frontdoor_rejected_total",
    "submits rejected by a bounded queue (tenant or global)",
    labelnames=("why",))
_m_throttled = _metrics.counter(
    "frontdoor_throttled_skips_total",
    "admission passes that skipped a tenant because its token bucket "
    "could not afford its head request (delay, not rejection)",
    labelnames=("tenant",))


class _TenantState:
    __slots__ = ("cfg", "bucket", "vtime", "queued")

    def __init__(self, cfg):
        self.cfg = cfg
        self.bucket = None
        if cfg.rate_tokens_per_s is not None:
            burst = (cfg.burst_tokens if cfg.burst_tokens is not None
                     else 4.0 * cfg.rate_tokens_per_s)
            self.bucket = TokenBucket(cfg.rate_tokens_per_s, burst)
        self.vtime = 0.0   # stride-scheduling virtual time
        self.queued = 0    # across both lanes


class LaneScheduler:
    """The policy object `PagedGenerationServer` consults (see module
    docstring). Construct directly for tests, or let `FrontDoor` build
    and install it."""

    def __init__(self, tenants=None, *, lane_weights=None,
                 interactive_chunk_share=0.7, preemption=True,
                 preempt_wait_tokens=8, max_queue=None,
                 auto_tenants=None):
        self._weights = dict(lane_weights or {"interactive": 4.0,
                                              "batch": 1.0})
        for lane in LANES:
            if self._weights.get(lane, 0) <= 0:
                raise ValueError(f"lane_weights[{lane!r}] must be > 0")
        if not 0.0 < float(interactive_chunk_share) <= 1.0:
            raise ValueError("interactive_chunk_share must be in "
                             f"(0, 1], got {interactive_chunk_share}")
        self.interactive_chunk_share = float(interactive_chunk_share)
        self.preemption = bool(preemption)
        if int(preempt_wait_tokens) < 0:
            raise ValueError("preempt_wait_tokens must be >= 0, got "
                             f"{preempt_wait_tokens}")
        self.preempt_wait_tokens = int(preempt_wait_tokens)
        self.max_queue = None if max_queue is None else int(max_queue)
        self._tenants: dict[str, _TenantState] = {}
        explicit = tenants is not None
        for cfg in (tenants or ()):
            if isinstance(tenants, dict):
                cfg = tenants[cfg]
            if not isinstance(cfg, TenantConfig):
                raise TypeError(f"tenants entries must be TenantConfig,"
                                f" got {type(cfg).__name__}")
            self._tenants[cfg.name] = _TenantState(cfg)
        # explicit tenant roster = closed world (unknown tenants are a
        # config error); no roster = tenants appear on first use
        self.auto_tenants = (not explicit if auto_tenants is None
                             else bool(auto_tenants))
        self._q: dict[str, dict[str, deque]] = {ln: {} for ln in LANES}
        self._depth = 0
        self._served = dict.fromkeys(LANES, 0.0)
        self._rejected = 0
        self._throttled = 0

    # ---- tenant registry ------------------------------------------------
    def tenant(self, name):
        ts = self._tenants.get(name)
        if ts is None:
            if not self.auto_tenants:
                raise ValueError(
                    f"unknown tenant {name!r} (known: "
                    f"{sorted(self._tenants)}); pass a TenantConfig "
                    f"for it or enable auto_tenants")
            ts = _TenantState(TenantConfig(name=name))
            self._tenants[name] = ts
        return ts

    # ---- submission ------------------------------------------------------
    def on_submit(self, req, now):
        """Route one request into its lane/tenant queue. Raises
        `QueueFull` (nothing enqueued) when a bounded queue is full —
        the explicit-rejection satellite of the bounded-queue design."""
        if req.meta is None:
            # bare server.submit on a fronted server: default lane /
            # tenant, cost = prompt + budget
            req.meta = RequestMeta(cost=int(req.ids.size + req.budget))
        meta = req.meta
        if meta.lane not in LANES:
            raise ValueError(f"unknown lane {meta.lane!r} "
                             f"(lanes: {LANES})")
        ts = self.tenant(meta.tenant)
        if not meta.cost:
            meta.cost = int(req.ids.size + req.budget)
        if self.max_queue is not None and self._depth >= self.max_queue:
            self._rejected += 1
            _m_rejected.labels(why="global").inc()
            raise QueueFull(
                f"front-door queue full ({self._depth}/"
                f"{self.max_queue} queued)")
        if ts.cfg.max_queued is not None \
                and ts.queued >= ts.cfg.max_queued:
            self._rejected += 1
            _m_rejected.labels(why="tenant").inc()
            raise QueueFull(
                f"tenant {meta.tenant!r} queue full ({ts.queued}/"
                f"{ts.cfg.max_queued} queued)")
        self._q[meta.lane].setdefault(meta.tenant,
                                      deque()).append(req)
        ts.queued += 1
        self._depth += 1
        self._push_gauges(meta.lane, meta.tenant)

    def requeue(self, req, now):
        """A preempted request returns to the FRONT of its tenant
        queue (it resumes before tenant-mates that never ran); its
        rate cost was charged at first admission and is not charged
        again."""
        meta = req.meta
        ts = self.tenant(meta.tenant)
        self._q[meta.lane].setdefault(meta.tenant,
                                      deque()).appendleft(req)
        ts.queued += 1
        self._depth += 1
        self._push_gauges(meta.lane, meta.tenant)

    # ---- candidate selection --------------------------------------------
    def _lane_head(self, lane, now):
        """Best eligible request in `lane`: interactive = earliest
        deadline first (undated requests after dated ones, FIFO among
        themselves); batch = head of the min-vtime eligible tenant.
        Rate-throttled tenants are skipped (and counted) — delay, not
        rejection."""
        best = None
        best_key = None
        for tname, dq in self._q[lane].items():
            if not dq:
                continue
            head = dq[0]
            ts = self._tenants[tname]
            if ts.bucket is not None and not getattr(
                    head, "_fd_charged", False) \
                    and not ts.bucket.affords(head.meta.cost, now):
                self._throttled += 1
                _m_throttled.labels(tenant=tname).inc()
                continue
            if lane == "interactive":
                dl = head.meta.deadline_s
                key = (0, req_deadline(head), head.t_submit) \
                    if dl is not None else (1, 0.0, head.t_submit)
            else:
                key = (ts.vtime, head.t_submit)
            if best is None or key < best_key:
                best, best_key = head, key
        return best

    def next_request(self, now, blocked=()):
        """The engine's admission probe: the best candidate across
        non-blocked lanes, weighted by lane service counters
        (served/weight — the lane that is furthest behind its weight
        goes first). Returns the request WITHOUT removing it; the
        engine calls `pop` once the reservation holds."""
        lanes = [ln for ln in LANES if ln not in blocked]
        lanes.sort(key=lambda ln: (self._served[ln]
                                   / self._weights[ln],
                                   LANES.index(ln)))
        for lane in lanes:
            head = self._lane_head(lane, now)
            if head is not None:
                return head
        return None

    def peek(self, now, n):
        """Up to `n` queued requests in approximate admission order
        WITHOUT popping, charging rate buckets, or counting throttle
        skips — the tier-prefetch tick's lane-aware look-ahead
        (ROADMAP 5d). Order is advisory: lanes rank by their current
        served/weight counters, interactive requests EDF across
        tenants, batch tenants by stride vtime then FIFO — the same
        keys `next_request` uses, minus the per-admission counter
        advances, so the set of likely-next requests is right even
        when the exact interleave shifts by the time they admit."""
        n = int(n)
        if n <= 0 or self._depth == 0:
            return []
        lanes = sorted(LANES, key=lambda ln: (self._served[ln]
                                              / self._weights[ln],
                                              LANES.index(ln)))
        out = []
        for lane in lanes:
            if len(out) >= n:
                break
            if lane == "interactive":
                entries = []
                for tname, dq in self._q[lane].items():
                    if not dq or self._peek_throttled(tname, dq[0],
                                                      now):
                        continue
                    for r in dq:
                        dl = r.meta.deadline_s
                        key = ((0, req_deadline(r), r.t_submit)
                               if dl is not None
                               else (1, 0.0, r.t_submit))
                        entries.append((key, r))
                entries.sort(key=lambda kr: kr[0])
                out.extend(r for _, r in entries)
            else:
                tnames = sorted(
                    (t for t, dq in self._q[lane].items() if dq),
                    key=lambda t: self._tenants[t].vtime)
                for tname in tnames:
                    dq = self._q[lane][tname]
                    if self._peek_throttled(tname, dq[0], now):
                        continue
                    out.extend(dq)
        return out[:n]

    def _peek_throttled(self, tname, head, now):
        """`_lane_head`'s eligibility test, side-effect-free (no
        throttle counters; the bucket refill is idempotent)."""
        ts = self._tenants[tname]
        return (ts.bucket is not None
                and not getattr(head, "_fd_charged", False)
                and not ts.bucket.affords(head.meta.cost, now))

    def pop(self, req, now):
        """Remove an admitted request from its queue; charge its
        tenant's rate bucket (once per request lifetime) and advance
        the tenant's stride clock and the lane service counter."""
        meta = req.meta
        ts = self.tenant(meta.tenant)
        self._q[meta.lane][meta.tenant].remove(req)
        ts.queued -= 1
        self._depth -= 1
        if ts.bucket is not None and not getattr(req, "_fd_charged",
                                                 False):
            ts.bucket.charge(meta.cost, now)
        req._fd_charged = True
        ts.vtime += meta.cost / ts.cfg.weight
        self._served[meta.lane] += 1.0
        self._push_gauges(meta.lane, meta.tenant)

    # ---- preemption policy ----------------------------------------------
    def victims(self, req, occupied, now):
        """Slots the engine may evict to admit `req`: only an
        interactive candidate preempts, and only batch-lane residents
        are victims — newest first (least sunk work; with the prefix
        cache on, even that work is preserved through the swap-out
        publish). `occupied`: list of (slot_idx, resident_request,
        remaining_tokens).

        Drain-wait hysteresis: when ANY resident is within
        `preempt_wait_tokens` of its budget, its slot frees in a few
        rounds anyway — preempting a victim would buy almost nothing
        and cost a swap-out/resume cycle, so the candidate waits (a
        few tokens' worth of TTFT, traded against batch-lane churn).
        A candidate whose deadline has already PASSED preempts
        regardless — lateness beats churn."""
        if not self.preemption or req.meta.lane != "interactive":
            return []
        if self.preempt_wait_tokens > 0 \
                and any(rem <= self.preempt_wait_tokens
                        for _, _, rem in occupied):
            dl = req.meta.deadline_s
            if dl is None or now < req.t_submit + dl:
                return []
        cands = [(j, r) for j, r, _ in occupied
                 if r.meta is not None and r.meta.lane == "batch"]
        # spread the damage: fewest-preempted first (re-hitting the
        # same victim concentrates ALL the eviction delay on one
        # request and stretches the batch lane's completion tail),
        # newest-first among ties (least sunk work)
        cands.sort(key=lambda jr: (getattr(jr[1], "preempts", 0),
                                   -jr[1].t_submit))
        return [j for j, _ in cands]

    # ---- prefill chunk sharing ------------------------------------------
    def prefill_plan(self, entries, budget):
        """Order the feeding slots for one packed prefill chunk and
        cap the interactive lane's total draw at
        `interactive_chunk_share` of the budget when batch prompts are
        feeding too. `entries`: list of (slot_idx, slot_dict).
        Returns [(slot_idx, token_cap_or_None), ...] in feed order."""
        inter, batch = [], []
        for i, s in entries:
            meta = s["req"].meta
            lane = meta.lane if meta is not None else "interactive"
            (inter if lane == "interactive" else batch).append((i, s))

        def edf(item):
            meta = item[1]["req"].meta
            dl = meta.deadline_s if meta is not None else None
            return ((0, dl) if dl is not None else (1, 0.0),
                    item[1]["req"].t_submit)

        inter.sort(key=edf)
        batch.sort(key=lambda item: item[1]["req"].t_submit)
        if not inter or not batch:
            return [(i, None) for i, _ in inter + batch]
        out = []
        rem = int(-(-budget * self.interactive_chunk_share // 1))
        for i, s in inter:
            need = int(s["prompt"].size - s["fed"])
            take = min(need, rem)
            out.append((i, take))
            rem -= take
        out.extend((i, None) for i, _ in batch)
        return out

    # ---- introspection ---------------------------------------------------
    def depth(self):
        return self._depth

    def lane_depths(self):
        return {ln: sum(len(dq) for dq in self._q[ln].values())
                for ln in LANES}

    def tenant_depths(self):
        return {name: ts.queued for name, ts in
                sorted(self._tenants.items())}

    def window_stats(self):
        """Window counters merged into stats()["frontdoor"]; reset via
        reset_window() (the engine's reset_stats calls it)."""
        return {"rejected": self._rejected,
                "rate_throttled_skips": self._throttled}

    def reset_window(self):
        self._rejected = 0
        self._throttled = 0

    def expire(self, now, pred):
        """Remove and return every queued request for which
        `pred(req)` is true — the engine's per-request timeout scan
        (r17). Rate charges are not refunded (the request consumed its
        admission slot); stride clocks are untouched (it never ran)."""
        out = []
        for lane in LANES:
            for tname, dq in self._q[lane].items():
                hits = [r for r in dq if pred(r)]
                for r in hits:
                    dq.remove(r)
                    self._tenants[tname].queued -= 1
                    self._depth -= 1
                    out.append(r)
                if hits:
                    self._push_gauges(lane, tname)
        return out

    def drain(self):
        """Remove and return every queued request (server stop)."""
        out = []
        for lane in LANES:
            for tname, dq in self._q[lane].items():
                out.extend(dq)
                dq.clear()
                self._push_gauges(lane, tname)
        for ts in self._tenants.values():
            ts.queued = 0
        self._depth = 0
        return out

    def _push_gauges(self, lane, tenant):
        if not _metrics.enabled():
            return
        _m_lane_queue.labels(lane=lane).set(
            sum(len(dq) for dq in self._q[lane].values()))
        _m_tenant_queue.labels(tenant=tenant).set(
            self._tenants[tenant].queued)


def req_deadline(req):
    """Absolute deadline of a request (submit time + relative TTFT
    deadline); requests without one sort last via the caller's key."""
    return req.t_submit + req.meta.deadline_s
