"""Serving front door (round 12): token streaming, SLO-aware
scheduling (interactive/batch lanes, TTFT deadlines), preemption with
prefix-cache swap-out, and multi-tenant fairness (weighted fair share,
token-rate limits, bounded queues with explicit rejection) — the
scheduling-and-delivery layer over `inference.PagedGenerationServer`.
See docs/FRONTDOOR.md.
"""
from ..inference.serving import RequestMeta
from .frontdoor import FrontDoor
from .scheduler import LANES, LaneScheduler
from .stream import DeltaAssembler, StreamEvent, StreamHandle
from .tenancy import QueueFull, TenantConfig, TokenBucket

__all__ = [
    "FrontDoor", "LaneScheduler", "LANES", "RequestMeta",
    "DeltaAssembler", "StreamEvent", "StreamHandle",
    "QueueFull", "TenantConfig", "TokenBucket",
]
