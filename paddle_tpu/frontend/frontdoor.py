"""The production front door (round 12): streaming, SLO lanes,
preemption, and multi-tenant fairness over `PagedGenerationServer`.

`FrontDoor` is the delivery-and-policy facade a fleet talks to. It
wraps one paged serving engine, installs a `LaneScheduler` into it,
and exposes a submit surface with per-request lane / tenant / deadline
/ streaming:

    from paddle_tpu.frontend import FrontDoor, TenantConfig

    fd = FrontDoor(model, max_slots=8, max_new_tokens=64,
                   detokenize=tok.decode,
                   tenants=[TenantConfig("free", weight=1,
                                         rate_tokens_per_s=500,
                                         max_queued=32),
                            TenantConfig("pro", weight=4)])
    fd.start()
    h = fd.submit(ids, lane="interactive", tenant="pro",
                  deadline_ms=250)
    for ev in h:                 # token-by-token streaming
        print(ev.text, end="")
    full = h.result()            # classic submit/drain surface
    fd.stats(); fd.stop()

Semantics in one paragraph: interactive-lane requests are ordered
earliest-deadline-first and may PREEMPT batch-lane residents under
resource pressure (the victim's live K/V is published through the
prefix-cache index, so its resume re-prefills from cache with
near-zero recompute; output is token-identical to an uninterrupted
run either way, because positions, penalties, and the counter-based
PRNG are all residency-invariant). Batch-lane requests share capacity
by per-tenant weighted fair share; token-rate limits DELAY a tenant
(its requests stay queued), bounded queues REJECT (`QueueFull` at
submit). Deadlines are observed, never enforced: a first token landing
past its deadline increments the lane's miss counter and the overage
histogram. The engine's legacy `submit()/result()` path still works on
a fronted server (default lane/tenant), and a server WITHOUT a front
door runs the exact pre-round-12 code path bit for bit.
"""
from __future__ import annotations

from ..inference.serving import PagedGenerationServer, RequestMeta
from ..sampling import SamplingParams
from .scheduler import LANES, LaneScheduler
from .stream import StreamHandle
from .tenancy import QueueFull, TenantConfig  # noqa: F401 (re-export)


class FrontDoor:
    """Front-door facade over one `PagedGenerationServer`.

    Either pass a model (plus any `PagedGenerationServer` kwargs — the
    front door then builds the engine, with prefix caching ON by
    default so preemption swap-outs keep their work) or pass an
    existing not-yet-started server via `server=`.

    tenants: iterable of `TenantConfig` (closed roster: unknown
        tenants are rejected) or None (tenants auto-register with
        default config on first use).
    lane_weights: admission service weights, default 4:1
        interactive:batch.
    interactive_chunk_share: the interactive lane's guaranteed maximum
        share of each packed prefill chunk while batch prompts are
        feeding (the SLO-lane split of the PR 3 chunk budget).
    preemption: allow interactive candidates to evict batch residents.
    preempt_wait_tokens: drain-wait hysteresis — while any resident is
        within this many tokens of its budget, a blocked interactive
        candidate waits for that slot instead of preempting (unless
        its deadline has already passed). 0 = always preempt.
    max_queue: global bounded queue across lanes/tenants (None =
        unbounded); overflow raises `QueueFull` at submit.
    stream_buffer: per-request cap on undelivered stream events before
        deltas coalesce (backpressure without blocking the engine).

    Ops plane (ISSUE 10): `expose_port=` (and `stall_timeout_s=`,
    `flight_recorder=`) forward to the engine like every other server
    kwarg — a fronted fleet node typically runs
    `FrontDoor(model, expose_port=9100, ...)` and is scraped at
    `/metrics`, watched at `/statusz` (which then carries the lane /
    tenant queue blocks), and health-checked at `/healthz`.
    `ops_url` / `health()` / `statusz()` / `dump_flight_recorder()`
    surface the engine's ops plane on the facade.
    """

    def __init__(self, model=None, *, server=None, tenants=None,
                 lane_weights=None, interactive_chunk_share=0.7,
                 preemption=True, preempt_wait_tokens=8,
                 max_queue=None, stream_buffer=256,
                 **server_kwargs):
        if (model is None) == (server is None):
            raise ValueError("pass exactly one of model= or server=")
        if server is None:
            # prefix caching on by default: it is the swap-out medium
            # that makes preemption cheap (publish instead of discard)
            server_kwargs.setdefault("enable_prefix_cache", True)
            server = PagedGenerationServer(model, **server_kwargs)
        elif server_kwargs:
            raise ValueError(
                f"server= given; engine kwargs "
                f"{sorted(server_kwargs)} must go to its constructor")
        self.server = server
        self.scheduler = LaneScheduler(
            tenants, lane_weights=lane_weights,
            interactive_chunk_share=interactive_chunk_share,
            preemption=preemption,
            preempt_wait_tokens=preempt_wait_tokens,
            max_queue=max_queue)
        server.set_scheduler(self.scheduler)
        self._stream_buffer = int(stream_buffer)

    # ---- lifecycle -------------------------------------------------------
    def warm(self, modes=((False, False),)):
        """Pre-compile the engine's packed-prefill shape buckets before
        taking traffic (`PagedGenerationServer.warm_buckets`).
        Preemption and cache-hit resume make bucket usage
        timing-dependent, so a front door that must meet TTFT
        deadlines from the first request should warm explicitly —
        compiles mid-window land on whichever requests are in flight.
        Call before start(). Returns the variant count compiled."""
        return self.server.warm_buckets(modes=modes)

    def start(self):
        self.server.start()
        return self

    def stop(self):
        self.server.stop()

    # ---- client API ------------------------------------------------------
    def submit(self, ids, *, lane="interactive", tenant="default",
               deadline_ms=None, sampling=None, max_new_tokens=None,
               stream=True, on_token=None, timeout_s=None,
               stream_timeout_s=None):
        """Submit one request; returns a `StreamHandle` (iterate for
        token/text deltas, or call `.result()` for the classic full
        array — both always work; `stream=False` skips per-token event
        delivery but keeps the handle surface).

        lane: "interactive" (TTFT-sensitive, EDF, may preempt batch)
            or "batch" (throughput, tenant fair share, preemptible).
        tenant: accounting bucket for fairness / rate limits / bounded
            queues. Raises `QueueFull` when a bounded queue is full.
        deadline_ms: relative TTFT deadline; misses are counted (lane
            histograms + `stats()["frontdoor"]`), never enforced.
        sampling / max_new_tokens: forwarded to the engine unchanged.
        on_token: optional extra `(token, reason)` callback invoked
            from the engine thread alongside (after) the stream's own
            delivery — for latency probes and bridges that want raw
            tokens without consuming the stream.
        timeout_s: per-request engine deadline (r17) — queued or
            resident past this, the request is cancelled slot-
            freeingly and the stream terminates with
            reason="timeout".
        stream_timeout_s: iterator-side gap timeout — iterating the
            returned handle raises `TimeoutError` after this many
            seconds without an event, so a dead engine can never hang
            the consumer thread.

        When the engine was built with `shed_queue_depth=`, an
        overloaded submit raises `reliability.AdmissionShed` (nothing
        enqueued); its `retry_after_s` is the hint to surface as an
        HTTP Retry-After.
        """
        if lane not in LANES:
            raise ValueError(f"unknown lane {lane!r} (lanes: {LANES})")
        if deadline_ms is not None and deadline_ms <= 0:
            raise ValueError(f"deadline_ms must be > 0, "
                             f"got {deadline_ms}")
        srv = self.server
        budget = max_new_tokens
        if budget is None and isinstance(sampling, SamplingParams):
            budget = sampling.max_new_tokens
        if budget is None:
            budget = srv.max_new
        meta = RequestMeta(
            lane=lane, tenant=tenant,
            deadline_s=(None if deadline_ms is None
                        else deadline_ms * 1e-3),
            cost=int(len(ids) + budget))
        stops = (sampling.stop_strings
                 if isinstance(sampling, SamplingParams) else ())
        handle = StreamHandle(
            detokenize=srv._detok, stop_strings=stops,
            tail_tokens=srv.stop_tail_tokens,
            max_buffered=self._stream_buffer,
            timeout_s=stream_timeout_s)
        cb = handle._on_token if stream else None
        if on_token is not None:
            if cb is None:
                cb = on_token
            else:
                def cb(tok, reason, _h=handle._on_token, _u=on_token):
                    _h(tok, reason)
                    _u(tok, reason)
        fut = srv.submit(ids, max_new_tokens=max_new_tokens,
                         sampling=sampling, meta=meta, on_token=cb,
                         timeout_s=timeout_s)
        return handle._bind(fut)

    # ---- introspection ---------------------------------------------------
    def stats(self):
        """The engine's stats() — which, with the scheduler installed,
        already carries per-lane/per-tenant queue depths, preemption /
        resume / deadline-miss counters, per-lane TTFT/ITL
        percentiles, and the scheduler's rejection/throttle window."""
        return self.server.stats()

    def reset_stats(self):
        self.server.reset_stats()

    # ---- ops plane (ISSUE 10) --------------------------------------------
    @property
    def ops_url(self):
        """Base URL of the engine's /metrics /statusz /healthz
        endpoint, or None when the server was built without one."""
        exp = self.server.exporter
        return exp.url if exp is not None else None

    def health(self):
        return self.server.health()

    def statusz(self):
        return self.server.statusz()

    def dump_flight_recorder(self):
        return self.server.dump_flight_recorder()

    def slo_report(self):
        """The engine's SLO burn-rate report (ISSUE 14) — pass
        `slos=[SLO(...), ...]` (an engine kwarg) to attach objectives;
        the report is also served at the ops endpoint's /slo."""
        return self.server.slo_report()

    def export_timeline(self, path):
        """Write the engine's Chrome/Perfetto timeline (ISSUE 14)."""
        return self.server.export_timeline(path)

    def capacity(self):
        """The engine's versioned pressure snapshot (ISSUE 17) — pool
        headroom + exhaustion forecast, tier occupancy, lane/tenant
        queue depths, shed pressure and SLO burns; also served at the
        ops endpoint's /capacity."""
        return self.server.capacity_snapshot()

    def cost_report(self):
        """The engine's per-tenant `CostReport` billing export
        (ISSUE 17); None when the engine runs without attribution."""
        return self.server.cost_report()
