"""Multi-tenant admission primitives for the front door (round 12).

A TENANT is a fair-share and rate-limit accounting bucket: every
front-door request names one, and the scheduler (`LaneScheduler`)
queues, throttles, and weighs requests per tenant. This module holds
the pure-policy pieces so they are unit-testable with a fake clock:

  * `TenantConfig` — declarative per-tenant policy (weight for the
    fair-share scheduler, token rate limit, bounded queue depth).
  * `TokenBucket` — deterministic token-bucket rate limiter. Time is
    always passed IN (`now`), never read from a wall clock, so the
    scheduler's single `time.perf_counter()` per admission pass drives
    every bucket and tests can replay exact schedules.
  * `QueueFull` — the EXPLICIT rejection: raised at submit time when a
    bounded tenant/global queue is full. Rate limits never reject —
    they delay (the request stays queued but ineligible until the
    bucket refills); only bounded queues reject.
"""
from __future__ import annotations

from dataclasses import dataclass


class QueueFull(RuntimeError):
    """Submit rejected: the tenant's (or the global) bounded queue is
    full. Nothing was enqueued; the caller may retry later."""


@dataclass
class TenantConfig:
    """Per-tenant front-door policy.

    weight: fair-share weight inside the batch lane (stride
        scheduling: a tenant with weight 2 is served twice as often as
        a weight-1 tenant under contention).
    rate_tokens_per_s: token-rate limit charged at ADMISSION with the
        request's cost (prompt tokens + token budget). None = no limit.
    burst_tokens: bucket capacity (how far ahead of the steady rate a
        quiet tenant may burst). Defaults to 4x the rate — and a
        request costing more than the burst is still admittable at a
        full bucket (the bucket goes into debt and repays at the
        steady rate), so no request is unschedulable by construction.
    max_queued: bounded queue depth; a submit past it raises
        `QueueFull`. None = unbounded.
    """
    name: str = "default"
    weight: float = 1.0
    rate_tokens_per_s: float | None = None
    burst_tokens: float | None = None
    max_queued: int | None = None

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"tenant {self.name!r}: weight must be "
                             f"> 0, got {self.weight}")
        if self.rate_tokens_per_s is not None \
                and self.rate_tokens_per_s <= 0:
            raise ValueError(
                f"tenant {self.name!r}: rate_tokens_per_s must be > 0 "
                f"or None, got {self.rate_tokens_per_s}")
        if self.max_queued is not None and self.max_queued < 1:
            raise ValueError(f"tenant {self.name!r}: max_queued must "
                             f"be >= 1 or None, got {self.max_queued}")


class TokenBucket:
    """Deterministic token bucket. All methods take `now` explicitly
    (any monotonic float clock); the bucket starts full at the first
    call's timestamp. `charge` may drive the level negative (debt) —
    `affords` then stays False until the refill repays it, which is
    what lets a single request larger than the burst through without
    permanently starving it."""

    def __init__(self, rate, burst):
        self.rate = float(rate)
        self.burst = float(burst)
        if self.rate <= 0 or self.burst <= 0:
            raise ValueError("rate and burst must be > 0")
        self._level = self.burst
        self._t = None

    def _refill(self, now):
        if self._t is None:
            self._t = float(now)
        dt = max(0.0, float(now) - self._t)
        self._level = min(self.burst, self._level + dt * self.rate)
        self._t = float(now)

    @property
    def level(self):
        return self._level

    def affords(self, cost, now):
        """Whether a request costing `cost` tokens may be admitted
        now: the level covers the cost, OR the bucket is full (so an
        over-burst-sized request runs on debt instead of starving)."""
        self._refill(now)
        return (self._level >= float(cost)
                or self._level >= self.burst)

    def charge(self, cost, now):
        self._refill(now)
        self._level -= float(cost)
