"""paddle.linalg namespace (ref: python/paddle/tensor/linalg.py exports)."""
from __future__ import annotations

from .ops.linalg import (  # noqa: F401
    cholesky, cholesky_solve, corrcoef, cov, cross, det, eigh, eigvalsh,
    inverse, lstsq, matrix_power, matrix_rank, multi_dot, mv, norm, pinv, qr,
    slogdet, solve, svd, triangular_solve,
)
from .ops.linalg import inverse as inv  # noqa: F401
