"""NLP model zoo (BERT/ERNIE/GPT-2/Transformer) — the BASELINE.json configs.

Reference models: ERNIE/BERT-large pretraining + GPT-2 with fused attention
(BASELINE.json configs; fluid transformer ops). These are the flagship models
for bench.py and __graft_entry__.py.
"""
from __future__ import annotations

from .bert import Bert, BertConfig, Ernie, ErnieConfig  # noqa: F401
from .gpt2 import GPT2, GPT2Config  # noqa: F401
from .transformer import TransformerConfig, TransformerModel  # noqa: F401
