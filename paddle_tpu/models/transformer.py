"""Seq2seq Transformer for machine translation.

Reference config: the WMT-style transformer built from fluid transformer ops
(python/paddle/fluid/layers + nn.Transformer). Encoder-decoder with shared
source/target embeddings optional, sinusoidal positions, greedy decode.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

from .. import nn, ops
from ..core.tensor import Tensor


@dataclass
class TransformerConfig:
    src_vocab_size: int = 30000
    tgt_vocab_size: int = 30000
    d_model: int = 512
    nhead: int = 8
    num_encoder_layers: int = 6
    num_decoder_layers: int = 6
    dim_feedforward: int = 2048
    dropout: float = 0.1
    max_length: int = 256
    bos_id: int = 0
    eos_id: int = 1

    @classmethod
    def tiny(cls):
        return cls(src_vocab_size=512, tgt_vocab_size=512, d_model=64,
                   nhead=4, num_encoder_layers=2, num_decoder_layers=2,
                   dim_feedforward=128, max_length=64)


def sinusoid_position_encoding(max_len, d_model):
    pos = np.arange(max_len)[:, None]
    i = np.arange(d_model)[None, :]
    angle = pos / np.power(10000, 2 * (i // 2) / d_model)
    enc = np.zeros((max_len, d_model), np.float32)
    enc[:, 0::2] = np.sin(angle[:, 0::2])
    enc[:, 1::2] = np.cos(angle[:, 1::2])
    return enc


class TransformerModel(nn.Layer):
    def __init__(self, cfg: TransformerConfig = None, **kw):
        super().__init__()
        cfg = cfg or TransformerConfig(**kw)
        self.cfg = cfg
        self.src_embed = nn.Embedding(cfg.src_vocab_size, cfg.d_model)
        self.tgt_embed = nn.Embedding(cfg.tgt_vocab_size, cfg.d_model)
        self.register_buffer(
            "pos_enc", Tensor(sinusoid_position_encoding(cfg.max_length,
                                                         cfg.d_model)),
            persistable=False)
        self.transformer = nn.Transformer(
            d_model=cfg.d_model, nhead=cfg.nhead,
            num_encoder_layers=cfg.num_encoder_layers,
            num_decoder_layers=cfg.num_decoder_layers,
            dim_feedforward=cfg.dim_feedforward, dropout=cfg.dropout,
            activation="gelu")
        self.generator = nn.Linear(cfg.d_model, cfg.tgt_vocab_size)
        self.dropout = nn.Dropout(cfg.dropout)
        self.scale = math.sqrt(cfg.d_model)

    def _embed(self, table, ids):
        s = ids.shape[1]
        return self.dropout(table(ids) * self.scale + self.pos_enc[:s])

    def forward(self, src_ids, tgt_ids, src_pad_mask=None):
        src = self._embed(self.src_embed, src_ids)
        tgt = self._embed(self.tgt_embed, tgt_ids)
        tgt_mask = self.transformer.generate_square_subsequent_mask(
            tgt_ids.shape[1])
        src_mask = None
        if src_pad_mask is not None:
            m = ops.unsqueeze(src_pad_mask.astype("float32"), [1, 2])
            src_mask = (1.0 - m) * -1e30
        out = self.transformer(src, tgt, src_mask=src_mask, tgt_mask=tgt_mask)
        # generator matmul on [B*S, E]: a 3-D head dot picks a sequence-minor
        # output layout on TPU and the loss's flatten then costs a [B,S,V]
        # relayout copy (same fix as GPT2.forward); both reshapes are
        # layout-free bitcasts
        b, s = out.shape[0], out.shape[1]
        out2 = ops.reshape(out, [-1, self.cfg.d_model])
        return ops.reshape(self.generator(out2),
                           [b, s, self.cfg.tgt_vocab_size])

    def loss(self, src_ids, tgt_in, tgt_out, label_smoothing=0.1):
        logits = self(src_ids, tgt_in)
        return ops.cross_entropy(
            ops.reshape(logits, [-1, self.cfg.tgt_vocab_size]),
            ops.reshape(tgt_out, [-1]),
            label_smoothing=label_smoothing)

    def greedy_decode(self, src_ids, max_len=32, use_cache=True):
        """Greedy generation. use_cache=True (default) encodes the source
        ONCE and runs the decoder incrementally against the layer-level
        KV caches (MultiHeadAttention.Cache for self-attention,
        StaticCache for the cross-attention K/V) — O(S) decoder work per
        token instead of re-running the full decoder stack
        (ref capability: the fluid decode loop's cache tensors).
        use_cache=False keeps the full re-forward path; both produce
        identical tokens (parity-tested)."""
        b = src_ids.shape[0]
        bos = self.cfg.bos_id
        if not use_cache:
            tgt = Tensor(np.full((b, 1), bos, np.int32))
            for _ in range(max_len - 1):
                logits = self(src_ids, tgt)
                nxt = ops.argmax(logits[:, -1], axis=-1).astype("int32")
                tgt = ops.concat([tgt, ops.unsqueeze(nxt, 1)], axis=1)
            return tgt
        src = self._embed(self.src_embed, src_ids)
        memory = self.transformer.encoder(src, None)
        caches = self.transformer.decoder.gen_cache(memory)
        tok = Tensor(np.full((b, 1), bos, np.int32))
        toks = [tok]
        for step in range(max_len - 1):
            # one-token embed at absolute position `step` (the host loop
            # owns the position; _embed's pos_enc slice starts at 0)
            t = self.dropout(self.tgt_embed(tok) * self.scale
                             + self.pos_enc[step:step + 1])
            out, caches = self.transformer.decoder(
                t, memory, None, None, caches)
            logits = self.generator(out[:, -1])
            nxt = ops.unsqueeze(
                ops.argmax(logits, axis=-1).astype("int32"), 1)
            toks.append(nxt)
            tok = nxt
        return ops.concat(toks, axis=1)

    def beam_search_decode(self, src_ids, beam_size=4, max_len=32,
                           length_penalty=0.6):
        """Beam search with the GNMT length penalty lp = ((5+len)/6)^alpha
        (ref capability: fluid.layers.beam_search / beam_search_decode).
        Finished beams are frozen (only an eos continuation at unchanged
        score); returns the best hypothesis per batch row, [B, <=max_len].
        beam_size=1 reproduces greedy_decode exactly."""
        import jax
        import jax.numpy as jnp

        src = src_ids._value if isinstance(src_ids, Tensor) \
            else jnp.asarray(np.asarray(src_ids))
        B, K, V = src.shape[0], int(beam_size), self.cfg.tgt_vocab_size
        eos, bos = self.cfg.eos_id, self.cfg.bos_id
        srcK = jnp.repeat(src, K, axis=0)                    # [B*K, S]
        tgt = jnp.full((B * K, 1), bos, jnp.int32)
        # only beam 0 is live at step 0 — otherwise K identical beams
        scores = jnp.tile(
            jnp.asarray([0.0] + [-1e9] * (K - 1), jnp.float32)[None],
            (B, 1))                                          # [B, K]
        finished = jnp.zeros((B, K), bool)
        row = jnp.arange(B)[:, None]
        # encode ONCE; decode incrementally against layer caches, which
        # are reordered by the winning beam index each step (the fluid
        # decode loop's cache-gather, done with a pytree gather here)
        src_e = self._embed(self.src_embed, Tensor(srcK))
        memory = self.transformer.encoder(src_e, None)
        caches = self.transformer.decoder.gen_cache(memory)
        step_tok = Tensor(tgt[:, -1:])
        pos = 0
        for _ in range(max_len - 1):
            t = self.dropout(self.tgt_embed(step_tok) * self.scale
                             + self.pos_enc[pos:pos + 1])
            out, caches = self.transformer.decoder(t, memory, None, None,
                                                   caches)
            pos += 1
            logits = self.generator(out[:, -1])._value
            logp = jax.nn.log_softmax(
                logits.astype(jnp.float32), -1).reshape(B, K, V)
            eos_only = jnp.where(jnp.arange(V)[None, None, :] == eos,
                                 0.0, -jnp.inf)
            cont = scores[:, :, None] + jnp.where(
                finished[:, :, None], eos_only, logp)        # [B, K, V]
            top_s, top_i = jax.lax.top_k(cont.reshape(B, K * V), K)
            beam_idx = top_i // V                            # [B, K]
            tok = (top_i % V).astype(jnp.int32)
            gather = (row * K + beam_idx).reshape(-1)
            tgt = jnp.concatenate([tgt[gather], tok.reshape(-1, 1)], 1)
            # reorder every cache row to follow its winning beam
            caches = jax.tree_util.tree_map(
                lambda c: Tensor(c._value[gather])
                if isinstance(c, Tensor) else c[gather], caches)
            step_tok = Tensor(tok.reshape(-1, 1))
            finished = finished[row, beam_idx] | (tok == eos)
            scores = top_s
            if bool(finished.all()):
                break
        # hypothesis length = tokens up to and including the first eos
        seq = tgt.reshape(B, K, -1)
        T = seq.shape[-1]
        is_eos = seq == eos
        first_eos = jnp.where(is_eos.any(-1), is_eos.argmax(-1),
                              T - 1)                         # [B, K]
        lengths = (first_eos + 1).astype(jnp.float32)
        lp = ((5.0 + lengths) / 6.0) ** length_penalty
        best = jnp.argmax(scores / lp, axis=-1)              # [B]
        out = seq[jnp.arange(B), best]
        # pad everything after the first eos with eos
        pos = jnp.arange(T)[None, :]
        cut = jnp.where(is_eos[jnp.arange(B), best].any(-1),
                        first_eos[jnp.arange(B), best], T - 1)[:, None]
        out = jnp.where(pos <= cut, out, eos)
        return Tensor(out, stop_gradient=True)
