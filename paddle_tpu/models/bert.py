"""BERT / ERNIE — encoder-only transformer for pretraining.

Reference configs: "BERT-base pretraining (fluid transformer ops → XLA)" and
"ERNIE-large under paddle.distributed.fleet collective" (BASELINE.json).
ERNIE shares BERT's architecture (it differs in masking strategy/data, which
lives in the input pipeline), so ErnieConfig aliases BertConfig sizes.
"""
from __future__ import annotations

from dataclasses import dataclass

from .. import nn, ops
from ..core.tensor import Tensor
from ..nn import initializer as I


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    dropout: float = 0.1
    layer_norm_epsilon: float = 1e-12

    @classmethod
    def base(cls):
        return cls()

    @classmethod
    def large(cls):
        return cls(hidden_size=1024, num_layers=24, num_heads=16,
                   intermediate_size=4096)

    @classmethod
    def tiny(cls):
        return cls(vocab_size=1024, hidden_size=128, num_layers=2,
                   num_heads=4, intermediate_size=512, max_position=128)


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        attr = I.Normal(0.0, 0.02)
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                            weight_attr=attr)
        self.position_embeddings = nn.Embedding(cfg.max_position,
                                                cfg.hidden_size,
                                                weight_attr=attr)
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size,
                                                  cfg.hidden_size,
                                                  weight_attr=attr)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size,
                                       epsilon=cfg.layer_norm_epsilon)
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        s = input_ids.shape[1]
        if position_ids is None:
            position_ids = ops.arange(0, s, dtype="int32")
        if token_type_ids is None:
            token_type_ids = ops.zeros(input_ids.shape, "int32")
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


class Bert(nn.Layer):
    def __init__(self, cfg: BertConfig = None, **kw):
        super().__init__()
        cfg = cfg or BertConfig(**kw)
        self.cfg = cfg
        self.embeddings = BertEmbeddings(cfg)
        enc_layer = nn.TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_heads, cfg.intermediate_size,
            dropout=cfg.dropout, activation="gelu")
        self.encoder = nn.TransformerEncoder(enc_layer, cfg.num_layers)
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        # MLM head
        self.mlm_transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.mlm_norm = nn.LayerNorm(cfg.hidden_size,
                                     epsilon=cfg.layer_norm_epsilon)
        self.mlm_bias = self.create_parameter(
            (cfg.vocab_size,), is_bias=True,
            default_initializer=I.Constant(0.0))
        # NSP head
        self.nsp = nn.Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                position_ids=None):
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        mask = None
        if attention_mask is not None:
            # [B, S] 1/0 -> additive [B, 1, 1, S]
            m = ops.unsqueeze(attention_mask.astype("float32"), [1, 2])
            mask = (1.0 - m) * -1e30
        seq = self.encoder(x, mask)
        pooled = ops.tanh(self.pooler(seq[:, 0]))
        return seq, pooled

    def mlm_logits(self, seq):
        # vocab matmul on [B*S, E]: a 3-D head dot picks a sequence-minor
        # output layout on TPU and the loss's flatten then costs a full
        # [B,S,V] relayout copy (same fix as GPT2.forward); the flatten and
        # unflatten around the 2-D dot are layout-free bitcasts
        lead = seq.shape[:-1]
        h2 = ops.reshape(seq, [-1, seq.shape[-1]])
        h2 = ops.gelu(self.mlm_transform(h2))
        h2 = self.mlm_norm(h2)
        logits2 = ops.matmul(h2, self.embeddings.word_embeddings.weight,
                             transpose_y=True) + self.mlm_bias
        return ops.reshape(logits2, list(lead) + [self.cfg.vocab_size])

    def pretraining_loss(self, input_ids, labels, next_sentence_label=None,
                         token_type_ids=None, attention_mask=None):
        """MLM (+ optional NSP) loss; labels use -100 for unmasked tokens."""
        seq, pooled = self(input_ids, token_type_ids, attention_mask)
        logits = self.mlm_logits(seq)
        mlm = ops.cross_entropy(
            ops.reshape(logits, [-1, self.cfg.vocab_size]),
            ops.reshape(labels, [-1]), ignore_index=-100)
        if next_sentence_label is not None:
            nsp = ops.cross_entropy(self.nsp(pooled),
                                    ops.reshape(next_sentence_label, [-1]))
            return mlm + nsp
        return mlm


@dataclass
class ErnieConfig(BertConfig):
    @classmethod
    def large(cls):
        return cls(vocab_size=18000, hidden_size=1024, num_layers=24,
                   num_heads=16, intermediate_size=4096)


class Ernie(Bert):
    """ERNIE-large: BERT architecture + entity-level masking (data-side)."""

    def __init__(self, cfg: ErnieConfig = None, **kw):
        super().__init__(cfg or ErnieConfig(**kw))


def create_mlm_batch(ids, vocab_size, mask_token, mask_prob=0.15,
                     mode="token", span_max=3, seed=None, pad_id=0):
    """Host-side MLM masking (ref: BERT data pipeline; ERNIE's phrase/entity
    masking — `mode='span'` masks contiguous spans the way ERNIE masks
    entities). Returns (masked_ids, labels) with labels==-100 where unmasked.
    """
    import numpy as np
    rng = np.random.RandomState(seed)
    ids = np.asarray(ids)
    masked = ids.copy()
    labels = np.full_like(ids, -100)
    b, s = ids.shape
    for i in range(b):
        valid = np.flatnonzero(ids[i] != pad_id)
        n_mask = max(1, int(len(valid) * mask_prob))
        if mode == "span":
            chosen = []
            while len(chosen) < n_mask and len(valid):
                start = rng.choice(valid)
                span = rng.randint(1, span_max + 1)
                chosen.extend(range(start, min(start + span, s)))
            chosen = np.unique(np.asarray(chosen[:n_mask], dtype=np.int64))
        else:
            chosen = rng.choice(valid, size=min(n_mask, len(valid)),
                                replace=False)
        labels[i, chosen] = ids[i, chosen]
        roll = rng.rand(len(chosen))
        for j, pos in enumerate(chosen):
            if roll[j] < 0.8:
                masked[i, pos] = mask_token
            elif roll[j] < 0.9:
                masked[i, pos] = rng.randint(0, vocab_size)
    return masked, labels


def build_train_step(cfg: BertConfig, remat=False):
    """Pure (params, batch, key) -> loss for pjit/fleet (same pattern as
    gpt2.build_train_step)."""
    import jax

    from ..core import rng as rng_mod

    model = Bert(cfg)
    model.train()

    def init_params():
        p, _ = model.functional_state()
        return p

    def loss_fn(params, batch, key):
        saved_p, saved_b = model.functional_state()
        rng_saved = (rng_mod._default_generator._key,
                     rng_mod._default_generator._count)
        rng_mod._default_generator._key = key
        rng_mod._default_generator._count = 0
        model.load_functional_state(params, None)
        try:
            from ..core.autograd import functional_trace
            with functional_trace():
                loss = model.pretraining_loss(
                    Tensor(batch["input_ids"]), Tensor(batch["labels"]),
                    next_sentence_label=None)
            return loss._value
        finally:
            model.load_functional_state(saved_p, saved_b)
            (rng_mod._default_generator._key,
             rng_mod._default_generator._count) = rng_saved

    if remat:
        loss_fn = jax.checkpoint(loss_fn)
    return loss_fn, init_params, model
