"""GPT-2 — decoder-only transformer LM.

Reference config: "GPT-2 medium with fused_attention_op → Pallas flash-attn,
pipeline-parallel Fleet" (BASELINE.json). TPU-first construction:
  * attention → ops.scaled_dot_product_attention (Pallas flash-attn on TPU)
  * pre-LN blocks, tied embeddings, bf16-friendly
  * `build_train_step` returns a pure (params, batch, key) -> loss function
    for pjit/fleet hybrid-parallel execution; `jax.checkpoint` per block when
    remat=True (recompute strategy).
"""
from __future__ import annotations

from dataclasses import dataclass

from .. import nn, ops
from ..core.tensor import Tensor
from ..nn import initializer as I


@dataclass
class GPT2Config:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_position: int = 1024
    intermediate_size: int = None  # defaults to 4*hidden
    dropout: float = 0.1
    layer_norm_epsilon: float = 1e-5
    tie_embeddings: bool = True

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    @classmethod
    def small(cls):
        return cls()

    @classmethod
    def medium(cls):
        return cls(hidden_size=1024, num_layers=24, num_heads=16)

    @classmethod
    def large(cls):
        return cls(hidden_size=1280, num_layers=36, num_heads=20)

    @classmethod
    def tiny(cls):
        return cls(vocab_size=1024, hidden_size=128, num_layers=2,
                   num_heads=4, max_position=256)


class GPT2Block(nn.Layer):
    """Pre-LN decoder block. Fused QKV: one [E, 3E] GEMM (vs 3 separate) —
    bigger MXU tiles, fewer HBM round-trips; the `qkv` name matches the
    column-parallel TP sharding rule."""

    def __init__(self, cfg: GPT2Config):
        super().__init__()
        h = cfg.hidden_size
        self.num_heads = cfg.num_heads
        self.head_dim = h // cfg.num_heads
        self.attn_dropout = cfg.dropout
        self.ln_1 = nn.LayerNorm(h, epsilon=cfg.layer_norm_epsilon)
        self.qkv_proj = nn.Linear(h, 3 * h)
        self.out_proj = nn.Linear(h, h)
        self.ln_2 = nn.LayerNorm(h, epsilon=cfg.layer_norm_epsilon)
        self.fc1 = nn.Linear(h, cfg.intermediate_size)
        self.fc2 = nn.Linear(cfg.intermediate_size, h)
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, x, attn_mask=None):
        a = self.ln_1(x)
        b, s = a.shape[0], a.shape[1]
        nh, hd = self.num_heads, self.head_dim
        qkv = ops.reshape(self.qkv_proj(a), [b, s, 3, nh, hd])
        qkv = ops.transpose(qkv, [2, 0, 3, 1, 4])  # [3, B, H, S, D]
        q, k, v = qkv[0], qkv[1], qkv[2]
        o, _ = ops.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=True,
            dropout_p=self.attn_dropout if self.training else 0.0)
        o = ops.reshape(ops.transpose(o, [0, 2, 1, 3]), [b, s, nh * hd])
        x = x + self.dropout(self.out_proj(o))
        m = self.ln_2(x)
        m = self.fc2(ops.gelu(self.fc1(m), approximate=True))
        return x + self.dropout(m)


class GPT2(nn.Layer):
    def __init__(self, cfg: GPT2Config = None, **kw):
        super().__init__()
        cfg = cfg or GPT2Config(**kw)
        self.cfg = cfg
        init_std = 0.02
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                weight_attr=I.Normal(0.0, init_std))
        self.wpe = nn.Embedding(cfg.max_position, cfg.hidden_size,
                                weight_attr=I.Normal(0.0, init_std))
        self.drop = nn.Dropout(cfg.dropout)
        self.h = nn.LayerList([GPT2Block(cfg) for _ in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size,
                                 epsilon=cfg.layer_norm_epsilon)
        if not cfg.tie_embeddings:
            self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                     bias_attr=False)

    def hidden_states(self, input_ids, position_ids=None, attn_mask=None):
        """Transformer body up to (and including) the final LayerNorm —
        the pre-head activations the chunked CE consumes."""
        s = input_ids.shape[1]
        if position_ids is None:
            position_ids = ops.arange(0, s, dtype="int32")
        x = self.wte(input_ids) + self.wpe(position_ids)
        x = self.drop(x)
        for block in self.h:
            x = block(x, attn_mask)
        return self.ln_f(x)

    def forward(self, input_ids, position_ids=None, attn_mask=None):
        x = self.hidden_states(input_ids, position_ids, attn_mask)
        # head matmul on [B*S, E]: a 3-D head dot picks a sequence-minor
        # output layout on TPU and the loss's flatten then costs a full
        # [B,S,V] relayout copy (4.9ms/step at batch 16, r4 per-op profile
        # %copy.578); the 2-D dot emits logits vocab-minor, and both the
        # flatten here and the unflatten below are layout-free bitcasts
        b, s = input_ids.shape[0], input_ids.shape[1]
        x2 = ops.reshape(x, [-1, self.cfg.hidden_size])
        if self.cfg.tie_embeddings:
            logits2 = ops.matmul(x2, self.wte.weight, transpose_y=True)
        else:
            logits2 = self.lm_head(x2)
        return ops.reshape(logits2, [b, s, self.cfg.vocab_size])

    def loss(self, input_ids, labels):
        import os
        n_chunks = int(os.environ.get("PADDLE_TPU_CHUNKED_CE", "0"))
        if n_chunks > 1 and self.cfg.tie_embeddings:
            # vocab-chunked CE: never materializes [B*S, V] logits —
            # flag-gated perf lever, parity-tested (ops/chunked_xent.py)
            from ..ops._registry import apply_op
            from ..ops.chunked_xent import chunked_softmax_xent
            h = self.hidden_states(input_ids)
            e = h.shape[-1]
            return apply_op(
                lambda hv, wv, lv: chunked_softmax_xent(
                    hv.reshape(-1, e), wv, lv.reshape(-1), n_chunks),
                "chunked_softmax_xent",
                (h, self.wte.weight, labels), {})
        logits = self(input_ids)
        return ops.cross_entropy(
            ops.reshape(logits, [-1, self.cfg.vocab_size]),
            ops.reshape(labels, [-1]))

    def quantize_weights(self, params=None):
        """Weight-only int8 (W8A16) packing of the decode path's big 2-D
        weights: returns a NEW flat params dict where each quantized
        entry is replaced by `name::w8c` (int8 codes) + `name::w8s`
        (per-channel scales in the weight dtype); every other entry is
        passed through. This is the ONE shared implementation behind
        `generate(weight_quant="int8")`, the W8A16 deployment artifact
        (`export_generator`), and the serving engines — a
        `PagedGenerationServer(quantization="w8a16")` calls it ONCE at
        construction and reuses the packed params across every
        prefill/step/packed_prefill/packed_verify dispatch, which is
        why the old lazy per-generate weakref cache (`_w8_cache`) is
        gone: serving no longer re-quantizes per call, and offline
        callers hold the snapshot themselves if they loop.

        params: optional pre-snapshotted functional params; defaults to
        the model's current `functional_state()`."""
        if params is None:
            params, _ = self.functional_state()
        return _quantize_decode_weights_int8(params, self.cfg)

    def generate(self, input_ids, max_new_tokens, temperature=0.0,
                 eos_token_id=None, seed=0, top_k=0, top_p=1.0,
                 pad_token_id=None, weight_quant=None, kv_quant=None,
                 kv_cache="dense", prompt_lens=None, block_size=16,
                 sampling=None):
        """Autoregressive decoding with a KV cache (serving path; ref
        capability: fluid beam_search/sampling decode ops). TPU-first:
        static shapes throughout — prefill compiles once per prompt shape,
        then a `lax.scan` emits one token per step against a fixed-size
        cache, so the whole generate is two XLA computations regardless of
        token count. temperature=0 is greedy; >0 samples.

        kv_cache="dense" (default) is the contiguous-cache fast path
        above. kv_cache="paged" decodes against the block-pool
        PagedKVCache (inference/kv_cache.py): prompts are RIGHT-padded
        with per-row `prompt_lens` (no pad-value matching), block_size
        sets the pool granularity, and the step loop runs host-side —
        it is the engine the continuous-batching server drives, exposed
        here for parity testing and offline use. kv_quant="int8" on
        the paged path stores the pool as int8 codes + per-vector
        scales (PagedKVCache(kv_dtype="int8")) with dequant inside the
        attention kernels — the served int8-KV configuration, parity-
        tested here offline.

        sampling: optional `paddle_tpu.sampling.SamplingParams` applied
        to EVERY batch row; overrides the temperature/top_k/top_p/seed
        args. The paged path runs the full vectorized pipeline
        (including min_p and penalties; stop_token_ids stop a row like
        EOS); row r samples from stream seed+r, so each row draws an
        independent counter-based PRNG stream. The dense path maps the
        program-level subset (temperature/top_p/seed, one stop id) and
        rejects the rest eagerly."""
        import jax.numpy as jnp
        import numpy as np

        from ..core.tensor import Tensor
        from ..sampling import SamplingParams

        if sampling is not None and not isinstance(sampling,
                                                   SamplingParams):
            raise TypeError(f"sampling must be a SamplingParams, "
                            f"got {type(sampling).__name__}")
        if sampling is not None and sampling.stop_strings:
            raise ValueError("stop_strings need a detokenizer — serve "
                             "via PagedGenerationServer(detokenize=...)")
        if sampling is not None and sampling.max_new_tokens is not None:
            max_new_tokens = sampling.max_new_tokens
        ids = input_ids._value if isinstance(input_ids, Tensor) \
            else jnp.asarray(np.asarray(input_ids))
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 0:
            raise ValueError("max_new_tokens must be >= 0")
        if max_new_tokens == 0:
            return Tensor(ids, stop_gradient=True)
        if kv_cache not in ("dense", "paged"):
            raise ValueError(f"unknown kv_cache {kv_cache!r} "
                             "(supported: 'dense', 'paged')")
        if kv_cache == "paged":
            if kv_quant not in (None, "int8"):
                raise ValueError(f"unknown kv_quant {kv_quant!r} "
                                 "(supported: 'int8')")
            if sampling is None:
                sampling = SamplingParams(
                    temperature=float(temperature), top_k=int(top_k),
                    top_p=float(top_p), seed=int(seed))
            return self._generate_paged(
                ids, max_new_tokens, eos_token_id, seed, pad_token_id,
                prompt_lens, block_size, weight_quant, sampling,
                kv_quant)
        if sampling is not None:
            # dense program-level subset: per-slot fields are a paged-
            # path feature (the dense decode is one fused program)
            for f in ("min_p", "repetition_penalty", "presence_penalty",
                      "frequency_penalty"):
                default = 1.0 if f == "repetition_penalty" else 0.0
                if getattr(sampling, f) != default:
                    raise ValueError(
                        f"kv_cache='dense' does not support "
                        f"SamplingParams.{f}={getattr(sampling, f)!r}; "
                        f"use kv_cache='paged'")
            if len(sampling.stop_token_ids) > 1:
                raise ValueError(
                    "kv_cache='dense' supports at most one stop token "
                    f"id (the eos), got {sampling.stop_token_ids!r}")
            temperature = sampling.temperature
            top_k = sampling.top_k
            top_p = sampling.top_p
            if sampling.seed is not None:
                seed = sampling.seed
            if sampling.stop_token_ids:
                eos_token_id = sampling.stop_token_ids[0]
        if prompt_lens is not None:
            raise ValueError("prompt_lens is only meaningful with "
                             "kv_cache='paged' (the dense path derives "
                             "lengths from LEFT padding)")
        if ids.shape[1] + max_new_tokens > self.cfg.max_position:
            raise ValueError(
                f"prompt ({ids.shape[1]}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_position "
                f"({self.cfg.max_position})")
        if pad_token_id is not None:
            # batched variable-length prompts must be LEFT-padded: the
            # decode reads the prompt's last token at position -1
            valid = np.asarray(ids) != pad_token_id
            if not valid.any(axis=1).all():
                raise ValueError("a prompt row is entirely padding")
            if (np.diff(valid.astype(np.int8), axis=1) < 0).any():
                raise ValueError(
                    "prompts must be LEFT-padded (pad tokens only at the "
                    "start of each row)")
        params, _ = self.functional_state()
        if weight_quant == "int8":
            # weight-only int8 (W8A16): decode is weight-STREAM bound, and
            # the int8->bf16 dequant fuses into the dot's operand pipeline
            # (measured ~1.9x on the streaming path, PERF.md) — halve the
            # per-token parameter stream, keep activations bf16. The
            # quantization itself is ~250 device ops over 124M params;
            # loops should snapshot quantize_weights() once — the
            # serving engines do exactly that at construction.
            params = self.quantize_weights(params)
        elif weight_quant is not None:
            raise ValueError(f"unknown weight_quant {weight_quant!r} "
                             "(supported: 'int8')")
        if kv_quant not in (None, "int8"):
            raise ValueError(f"unknown kv_quant {kv_quant!r} "
                             "(supported: 'int8')")
        out = _generate_jit(self.cfg, params, ids, max_new_tokens,
                            temperature,
                            -1 if eos_token_id is None else int(eos_token_id),
                            int(seed),
                            min(int(top_k), self.cfg.vocab_size), top_p,
                            -1 if pad_token_id is None else int(pad_token_id),
                            kv_quant == "int8")
        return Tensor(out, stop_gradient=True)

    def _generate_paged(self, ids, max_new, eos_token_id, seed,
                        pad_token_id, prompt_lens, block_size,
                        weight_quant, sampling, kv_quant=None):
        """Paged-cache decode: RIGHT-padded prompts + per-row lengths,
        host-side step loop over the jitted PagedDecoder (the same
        engine the continuous-batching server drives), with the full
        per-slot sampling pipeline (`sampling` applied to every row;
        row r uses PRNG stream seed+r). Output rows are [prompt,
        generated, fill]: generated tokens start at each row's true
        length; eos/stop padding continues after a hit like the dense
        path; the tail past len+max_new is filled with pad_token_id
        (else eos, else 0)."""
        import jax.numpy as jnp
        import numpy as np

        from ..core.tensor import Tensor
        from ..inference.kv_cache import PagedKVCache, blocks_for
        from ..nn.decode import PagedDecoder
        from ..sampling import SlotParamStore

        ids = np.asarray(ids).astype(np.int32)
        B, S0 = ids.shape
        if prompt_lens is None:
            lens = np.full((B,), S0, np.int32)
        else:
            lens = np.asarray(prompt_lens).astype(np.int32).reshape(-1)
            if lens.shape[0] != B:
                raise ValueError("prompt_lens must have one entry per row")
            if (lens < 1).any() or (lens > S0).any():
                raise ValueError(f"prompt_lens must be in [1, {S0}]")
        if S0 > self.cfg.max_position or \
                int(lens.max()) + max_new > self.cfg.max_position:
            raise ValueError(
                f"prompt ({int(lens.max())}) + max_new_tokens ({max_new}) "
                f"exceeds max_position ({self.cfg.max_position})")
        eos = -1 if eos_token_id is None else int(eos_token_id)
        params, _ = self.functional_state()
        if weight_quant == "int8":
            params = self.quantize_weights(params)
        elif weight_quant is not None:
            raise ValueError(f"unknown weight_quant {weight_quant!r} "
                             "(supported: 'int8')")
        dt = params["ln_f.weight"].dtype
        bs = int(block_size)
        m_width = blocks_for(max(S0, int(lens.max()) + max_new), bs)
        total_blocks = sum(blocks_for(int(n) + max_new, bs) for n in lens)
        # fixed pool label: offline generate() builds a transient cache
        # per call — an auto-assigned name would mint a new metric
        # series every call under telemetry
        cache = PagedKVCache(self.cfg.num_layers, self.cfg.num_heads,
                             self.cfg.hidden_size // self.cfg.num_heads,
                             block_size=bs, num_blocks=total_blocks + 1,
                             dtype=dt, kv_dtype=kv_quant,
                             name="gpt2-generate")
        for b in range(B):  # offline batch: reserve the full horizon
            cache.allocate(b, int(lens[b]) + max_new)
        tables = jnp.asarray(cache.table_array(range(B), m_width))
        dec = PagedDecoder.for_config(self.cfg, bs, kv_dtype=kv_quant)
        # per-row sampling buffers: the same params every row, stream
        # seed+r per row (independent counter-based PRNG streams)
        store = SlotParamStore(B, self.cfg.vocab_size)
        base_seed = sampling.seed if sampling.seed is not None \
            else int(seed)
        for b in range(B):
            store.set_slot(b, sampling, base_seed + b, eos=eos,
                           prompt_ids=ids[b, :int(lens[b])])
        fill = pad_token_id if pad_token_id is not None \
            else (eos if eos >= 0 else 0)
        stop_fill = eos if eos >= 0 else fill
        lens_j = jnp.asarray(lens)
        active = jnp.ones((B,), bool)
        sp, mode = store.step_args(np.zeros((B,), np.int32))
        tok, stopped, kc, vc, counts = dec.prefill(
            params, jnp.asarray(ids), lens_j, tables, cache.k_blocks,
            cache.v_blocks, sp, mode)
        cache.swap_arrays(kc, vc)
        store.swap_counts(counts)
        tok = np.asarray(tok)
        done = np.asarray(stopped)
        out_toks = [tok]
        pos = lens.copy()
        for step in range(1, max_new):
            sp, mode = store.step_args(np.full((B,), step, np.int32))
            nxt, stopped, kc, vc, counts = dec.step(
                params, jnp.asarray(out_toks[-1]), jnp.asarray(pos),
                active, tables, kc, vc, sp, mode)
            cache.swap_arrays(kc, vc)
            store.swap_counts(counts)
            nxt = np.asarray(nxt)
            # dense-path semantics: rows that hit eos (or a stop token)
            # keep emitting the stop-fill value
            nxt = np.where(done, stop_fill, nxt)
            done = done | np.asarray(stopped)
            out_toks.append(nxt)
            pos = pos + 1
        gen = np.stack(out_toks, axis=1)             # [B, max_new]
        out = np.full((B, S0 + max_new), fill, np.int32)
        for b in range(B):
            n = int(lens[b])
            out[b, :n] = ids[b, :n]
            out[b, n:n + max_new] = gen[b]
        return Tensor(jnp.asarray(out), stop_gradient=True)


def _quantize_decode_weights_int8(params, cfg):
    """Per-channel symmetric int8 for the decode path's big 2-D weights.
    Each quantized entry replaces `name` with `name + "::w8"` holding
    (codes int8, scale bf16); the decode fn detects the key at trace time
    and applies the scale AFTER the contraction (epilogue-fused). wte is
    quantized per-ROW so both the embedding gather and the tied head
    share one scale vector."""
    import jax.numpy as jnp

    out = dict(params)

    def quant(name, axis):
        w = out.pop(name)
        amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis,
                       keepdims=True)
        scale = (jnp.maximum(amax, 1e-12) / 127.0)
        codes = jnp.clip(jnp.round(w.astype(jnp.float32) / scale),
                         -127, 127).astype(jnp.int8)
        # FLAT keys (not tuples) so the dict serializes through the
        # standard .pdiparams npz artifact unchanged; scales stay in the
        # weight dtype (bf16 for serving) — an f32 scale vector measured
        # 0.41 vs 0.30 ms/token (promotion breaks the epilogue fusion)
        out[name + "::w8c"] = codes
        out[name + "::w8s"] = scale.squeeze(axis).astype(w.dtype)

    quant("wte.weight", 1)  # per-row: shared by gather and tied head
    if not cfg.tie_embeddings:
        quant("lm_head.weight", 0)
    for i in range(cfg.num_layers):
        for part in ("qkv_proj", "out_proj", "fc1", "fc2"):
            quant(f"h.{i}.{part}.weight", 0)  # per-output-column
    return out


def _generate_jit(cfg: GPT2Config, params, ids, max_new, temp, eos, seed,
                  top_k=0, top_p=1.0, pad=-1, kv_quant=False):
    import jax
    import jax.numpy as jnp

    spec = (cfg.num_layers, cfg.num_heads,
            cfg.hidden_size // cfg.num_heads, cfg.hidden_size,
            cfg.layer_norm_epsilon, cfg.tie_embeddings)
    fn = _generate_impl(spec, max_new, top_k, top_p < 1.0, bool(kv_quant))
    # key/temperature/eos/top_p/pad are traced arguments: new values reuse
    # the compiled program (static: max_new — the scan length — top_k,
    # which fixes the lax.top_k output shape, and WHETHER nucleus
    # filtering is on, so the default top_p=1.0 path never pays the
    # per-token sort)
    return fn(params, ids, jax.random.key(seed),
              jnp.float32(temp), jnp.int32(eos), jnp.float32(top_p),
              jnp.int32(pad))


import functools as _functools  # noqa: E402


@_functools.lru_cache(maxsize=16)
def _generate_impl(spec, max_new, top_k=0, nucleus=False, kv_quant=False):
    import jax
    return jax.jit(_build_decode_fn(spec, max_new, top_k, nucleus,
                                    kv_quant))


def _build_decode_fn(spec, max_new, top_k=0, nucleus=False,
                     kv_quant=False):
    """Build the raw (params, ids, key, temp, eos, top_p) -> tokens decode
    function for one static configuration. Two XLA computations total: a
    prefill over the prompt and a lax.scan of single-token steps against a
    fixed-size KV cache [L, B, H, S0+max_new, D]."""
    import jax
    import jax.numpy as jnp

    L, H, Dh, E, eps, tied = spec
    scale = Dh ** -0.5

    def ln(x, w, b):
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + eps) * w + b

    def matw(p, name, x, dt):
        # weight-only int8 (W8A16): `name::w8` holds (codes, per-out-col
        # scale); the int8->dt convert fuses into the dot's operand
        # pipeline (halves the weight stream — decode is stream-bound)
        # and the scale multiplies the [.., N] OUTPUT (epilogue-fused)
        codes = p.get(name + "::w8c")
        if codes is None:
            return x @ p[name]
        return (x @ codes.astype(dt)) * p[name + "::w8s"].astype(dt)

    def mlp(p, i, x):
        dt = x.dtype
        hdn = jax.nn.gelu(
            matw(p, f"h.{i}.fc1.weight", x, dt) + p[f"h.{i}.fc1.bias"],
            approximate=True)
        return matw(p, f"h.{i}.fc2.weight", hdn, dt) + p[f"h.{i}.fc2.bias"]

    def qkv_split(p, i, a):
        # a: [..., E] -> q, k, v each [..., H, Dh]
        qkv = matw(p, f"h.{i}.qkv_proj.weight", a, a.dtype) \
            + p[f"h.{i}.qkv_proj.bias"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        new = q.shape[:-1] + (H, Dh)
        return q.reshape(new), k.reshape(new), v.reshape(new)

    def step_fn(params, ids, key0, temp, eos, top_p, pad):
        B, S0 = ids.shape
        S = S0 + max_new
        wpe = params["wpe.weight"]
        dt = params["ln_f.weight"].dtype
        wte_codes = params.get("wte.weight::w8c")
        if wte_codes is None:
            wte_full = params["wte.weight"]

            def embed(t):
                return wte_full[t]
        else:
            wte_rs = params["wte.weight::w8s"]  # [V] per-row scale

            def embed(t):
                return wte_codes[t].astype(dt) * wte_rs[t][..., None] \
                    .astype(dt)

        def head(xf):
            if tied:
                if wte_codes is None:
                    return (xf @ wte_full.T).astype(jnp.float32)
                return ((xf @ wte_codes.T.astype(dt))
                        * wte_rs[None, :].astype(dt)).astype(jnp.float32)
            return matw(params, "lm_head.weight", xf,
                        dt).astype(jnp.float32)

        # LEFT-padding support: pad is a traced token id (-1 = no padding,
        # valid everywhere). Pad keys are masked out of attention, pad
        # positions don't consume wpe slots, and the rightmost position is
        # always a real token, so x[:, -1] stays the correct read-out.
        valid = ids != pad                           # [B, S0] bool
        pos = jnp.maximum(jnp.cumsum(valid, axis=1) - 1, 0)
        n_valid = valid.sum(axis=1)                  # [B]

        # ---- prefill over the prompt (causal full attention) ----
        x = embed(ids) + wpe[pos]
        if kv_quant:
            # int8 KV cache, per-(position) vector scales: at large batch
            # the decode becomes cache-READ bound and halving the KV
            # stream is the remaining lever (weights: see ::w8c)
            ck = jnp.zeros((L, B, H, S, Dh), jnp.int8)
            cv = jnp.zeros((L, B, H, S, Dh), jnp.int8)
            ksc = jnp.zeros((L, B, H, S), dt)
            vsc = jnp.zeros((L, B, H, S), dt)
        else:
            ck = jnp.zeros((L, B, H, S, Dh), dt)
            cv = jnp.zeros((L, B, H, S, Dh), dt)
            ksc = vsc = jnp.zeros((0,), dt)

        def kv_enc(t):
            # [..., Dh] -> (int8 codes, per-vector scale [...])
            amax = jnp.max(jnp.abs(t.astype(jnp.float32)), axis=-1)
            sc = jnp.maximum(amax, 1e-12) / 127.0
            codes = jnp.clip(jnp.round(t.astype(jnp.float32)
                                       / sc[..., None]),
                             -127, 127).astype(jnp.int8)
            return codes, sc.astype(dt)

        causal = jnp.tril(jnp.ones((S0, S0), bool))
        kmask = causal[None, None] & valid[:, None, None, :]
        for i in range(L):
            a = ln(x, params[f"h.{i}.ln_1.weight"],
                   params[f"h.{i}.ln_1.bias"])
            q, k, v = qkv_split(params, i, a)       # [B, S0, H, Dh]
            q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
            if kv_quant:
                kc, ks = kv_enc(k)
                vc, vs = kv_enc(v)
                ck = ck.at[i, :, :, :S0].set(kc)
                cv = cv.at[i, :, :, :S0].set(vc)
                ksc = ksc.at[i, :, :, :S0].set(ks)
                vsc = vsc.at[i, :, :, :S0].set(vs)
            else:
                ck = ck.at[i, :, :, :S0].set(k)
                cv = cv.at[i, :, :, :S0].set(v)
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(
                jnp.float32) * scale
            s = jnp.where(kmask, s, -1e30)
            w = jax.nn.softmax(s, axis=-1).astype(dt)
            o = jnp.einsum("bhqk,bhkd->bhqd", w, v)
            o = o.transpose(0, 2, 1, 3).reshape(B, S0, E)
            x = x + matw(params, f"h.{i}.out_proj.weight", o, dt) \
                + params[f"h.{i}.out_proj.bias"]
            m = ln(x, params[f"h.{i}.ln_2.weight"],
                   params[f"h.{i}.ln_2.bias"])
            x = x + mlp(params, i, m)
        xf = ln(x[:, -1], params["ln_f.weight"], params["ln_f.bias"])
        logits0 = head(xf)

        def pick(logits, key):
            # temp/top_p are traced: branch with lax.cond so every
            # sampling mode lives in one compiled program
            def sample():
                l = logits / jnp.maximum(temp, 1e-6)
                if top_k > 0:  # static: fixes the lax.top_k shape
                    kth = jax.lax.top_k(l, top_k)[0][..., -1:]
                    l = jnp.where(l < kth, -jnp.inf, l)
                if nucleus:  # static: the top_p=1 default skips the sort
                    # keep the smallest prefix of desc-sorted tokens whose
                    # exclusive cumulative prob stays under top_p (the
                    # top-1 token always survives)
                    sl = jnp.sort(l, axis=-1)[..., ::-1]
                    probs = jax.nn.softmax(sl, axis=-1)
                    cum = jnp.cumsum(probs, axis=-1) - probs
                    n_keep = jnp.maximum(
                        jnp.sum(cum < top_p, axis=-1, keepdims=True), 1)
                    kth_val = jnp.take_along_axis(sl, n_keep - 1, axis=-1)
                    l = jnp.where(l < kth_val, -jnp.inf, l)
                return jax.random.categorical(
                    key, l, axis=-1).astype(jnp.int32)

            return jax.lax.cond(
                temp > 0.0, sample,
                lambda: jnp.argmax(logits, axis=-1).astype(jnp.int32))

        key0, sub0 = jax.random.split(key0)
        tok0 = pick(logits0, sub0)
        done0 = (tok0 == eos) & (eos >= 0)

        # ---- decode: one token per scan step against the cache ----
        vfull = jnp.concatenate(
            [valid, jnp.ones((B, max_new), bool)], axis=1)  # [B, S]

        def body(carry, step):
            tok, done, ck, cv, ksc, vsc, key = carry
            t = S0 + step  # absolute cache slot of `tok`
            x = embed(tok) + wpe[n_valid + step]    # per-row position
            for i in range(L):
                a = ln(x, params[f"h.{i}.ln_1.weight"],
                       params[f"h.{i}.ln_1.bias"])
                q, k, v = qkv_split(params, i, a)   # [B, H, Dh]
                if kv_quant:
                    kc, ks = kv_enc(k)
                    vc, vs = kv_enc(v)
                    ck = ck.at[i, :, :, t].set(kc)
                    cv = cv.at[i, :, :, t].set(vc)
                    ksc = ksc.at[i, :, :, t].set(ks)
                    vsc = vsc.at[i, :, :, t].set(vs)
                    # fold the per-vector scales into the SMALL tensors so
                    # the big cache is consumed as raw int8 codes (the
                    # convert fuses into the einsum operand like the
                    # weight dot): scores scale per position AFTER the
                    # contraction; v's scale rides the [B,H,S] probs
                    s = jnp.einsum("bhd,bhsd->bhs", q,
                                   ck[i].astype(dt)).astype(jnp.float32) \
                        * ksc[i].astype(jnp.float32) * scale
                else:
                    ck = ck.at[i, :, :, t].set(k)
                    cv = cv.at[i, :, :, t].set(v)
                    s = jnp.einsum("bhd,bhsd->bhs", q, ck[i]).astype(
                        jnp.float32) * scale
                s = jnp.where((jnp.arange(s.shape[-1]) <= t)[None, None]
                              & vfull[:, None, :], s, -1e30)
                w = jax.nn.softmax(s, axis=-1).astype(dt)
                if kv_quant:
                    o = jnp.einsum("bhs,bhsd->bhd", w * vsc[i],
                                   cv[i].astype(dt)).reshape(B, E)
                else:
                    o = jnp.einsum("bhs,bhsd->bhd", w, cv[i]).reshape(B, E)
                x = x + matw(params, f"h.{i}.out_proj.weight", o, dt) \
                    + params[f"h.{i}.out_proj.bias"]
                m = ln(x, params[f"h.{i}.ln_2.weight"],
                       params[f"h.{i}.ln_2.bias"])
                x = x + mlp(params, i, m)
            xf = ln(x, params["ln_f.weight"], params["ln_f.bias"])
            logits = head(xf)
            key, sub = jax.random.split(key)
            nxt = pick(logits, sub)
            # eos is traced (-1 disables): once done, keep emitting eos
            nxt = jnp.where(done, eos, nxt)
            done = done | ((nxt == eos) & (eos >= 0))
            return (nxt, done, ck, cv, ksc, vsc, key), tok

        (last, *_), toks = jax.lax.scan(
            body, (tok0, done0, ck, cv, ksc, vsc, key0),
            jnp.arange(max_new - 1)) if max_new > 1 else \
            ((tok0,), jnp.zeros((0, B), jnp.int32))
        seq = jnp.concatenate([ids, toks.T.astype(jnp.int32),
                               last[:, None]], axis=1)
        return seq

    return step_fn


def export_generator(model: "GPT2", path_prefix, prompt_len,
                     max_new_tokens, top_k=0, top_p_enabled=False,
                     batch_size=None, weight_quant=None, kv_quant=None):
    """Serialize the KV-cache decode program as the standard deployment
    artifact (.pdmodel StableHLO + .pdiparams npz) so text generation runs
    in a serving process with NO Python model class:

        served = paddle.jit.load(path_prefix)
        tokens = served(ids, seed, temperature, eos, top_p, pad)

    ids: [B, prompt_len] int32 (B symbolic when batch_size is None);
    seed uint32; temperature/top_p float32 (top_p only filters when
    exported with top_p_enabled); eos int32 (-1 disables); pad int32
    (-1 = no padding, otherwise prompts must be LEFT-padded with this
    token id and pads are masked from attention)."""
    import jax
    import jax.numpy as jnp

    from .. import jit as jit_mod

    cfg = model.cfg
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1 for an exported "
                         "generator (a 0-token artifact has no decode)")
    if prompt_len + max_new_tokens > cfg.max_position:
        raise ValueError("prompt_len + max_new_tokens exceeds max_position")
    spec = (cfg.num_layers, cfg.num_heads,
            cfg.hidden_size // cfg.num_heads, cfg.hidden_size,
            cfg.layer_norm_epsilon, cfg.tie_embeddings)
    if kv_quant not in (None, "int8"):
        raise ValueError(f"unknown kv_quant {kv_quant!r} "
                         "(supported: 'int8')")
    decode = _build_decode_fn(spec, int(max_new_tokens),
                              min(int(top_k), cfg.vocab_size),
                              bool(top_p_enabled), kv_quant == "int8")

    def serving_fn(params, bufs, ids, seed, temp, eos, top_p, pad):
        del bufs  # GPT-2 has no buffers; kept for the artifact convention
        return decode(params, ids, jax.random.key(seed), temp, eos, top_p,
                      pad)

    params, _ = model.functional_state()
    if weight_quant == "int8":
        # W8A16 artifact: the served program streams int8 weights
        # (1.8-2.7x decode tokens/s at small batch, PERF.md); codes and
        # bf16 scales ride the standard npz as flat keys (the artifact
        # stores extension dtypes as bit-preserving views + dtype
        # sidecars, so the served program keeps the bf16-scale fast path)
        params = _quantize_decode_weights_int8(params, cfg)
    elif weight_quant is not None:
        raise ValueError(f"unknown weight_quant {weight_quant!r} "
                         "(supported: 'int8')")
    if batch_size is None:
        (bdim,) = jit_mod._symbolic_dims(1)
    else:
        bdim = int(batch_size)
    from jax import export as jexport
    args = (jax.ShapeDtypeStruct((bdim, int(prompt_len)), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.uint32),
            jax.ShapeDtypeStruct((), jnp.float32),
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.float32),
            jax.ShapeDtypeStruct((), jnp.int32))
    p_specs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
               for k, v in params.items()}
    jf = jax.jit(serving_fn)
    try:
        # multi-platform like jit.save: a dev-box export must serve on TPU
        exported = jexport.export(jf, platforms=("cpu", "tpu"))(
            p_specs, {}, *args)
    except Exception:
        exported = jexport.export(jf)(p_specs, {}, *args)
    meta = {"kind": "gpt2_generator", "weight_quant": weight_quant,
            "kv_quant": kv_quant, "prompt_len": int(prompt_len),
            "max_new_tokens": int(max_new_tokens), "top_k": int(top_k),
            "top_p_enabled": bool(top_p_enabled),
            # None = batch-polymorphic (serving layers pick their own B)
            "batch_size": None if batch_size is None else int(batch_size),
            "inputs": ["ids[int32]", "seed[uint32]",
                       "temperature[f32]", "eos[int32]", "top_p[f32]",
                       "pad[int32] (-1 disables left-pad masking)"]}
    return jit_mod.write_artifact(path_prefix, exported, params, {}, meta)


def build_train_step(cfg: GPT2Config, remat=False, dtype="float32"):
    """Pure functional GPT-2 loss for pjit/fleet: returns
    (loss_fn(params, batch, key), init_params()). The module tree above is
    used once to materialize params; the pure fn re-binds them per call.
    """
    import jax
    import jax.numpy as jnp

    from ..core import rng as rng_mod

    model = GPT2(cfg)
    model.train()
    if dtype != "float32":
        model.to(dtype=dtype)

    def init_params():
        p, _ = model.functional_state()
        return p

    def loss_fn(params, batch, key):
        saved_p, saved_b = model.functional_state()
        rng_saved = (rng_mod._default_generator._key,
                     rng_mod._default_generator._count)
        rng_mod._default_generator._key = key
        rng_mod._default_generator._count = 0
        model.load_functional_state(params, None)
        try:
            from ..core.autograd import functional_trace
            input_ids, labels = batch["input_ids"], batch["labels"]
            with functional_trace():
                loss = model.loss(Tensor(input_ids), Tensor(labels))
            return loss._value
        finally:
            model.load_functional_state(saved_p, saved_b)
            (rng_mod._default_generator._key,
             rng_mod._default_generator._count) = rng_saved

    if remat:
        import jax
        loss_fn = jax.checkpoint(loss_fn)
    return loss_fn, init_params, model
