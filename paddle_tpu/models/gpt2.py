"""GPT-2 — decoder-only transformer LM.

Reference config: "GPT-2 medium with fused_attention_op → Pallas flash-attn,
pipeline-parallel Fleet" (BASELINE.json). TPU-first construction:
  * attention → ops.scaled_dot_product_attention (Pallas flash-attn on TPU)
  * pre-LN blocks, tied embeddings, bf16-friendly
  * `build_train_step` returns a pure (params, batch, key) -> loss function
    for pjit/fleet hybrid-parallel execution; `jax.checkpoint` per block when
    remat=True (recompute strategy).
"""
from __future__ import annotations

from dataclasses import dataclass

from .. import nn, ops
from ..core.tensor import Tensor
from ..nn import initializer as I


@dataclass
class GPT2Config:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_position: int = 1024
    intermediate_size: int = None  # defaults to 4*hidden
    dropout: float = 0.1
    layer_norm_epsilon: float = 1e-5
    tie_embeddings: bool = True

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    @classmethod
    def small(cls):
        return cls()

    @classmethod
    def medium(cls):
        return cls(hidden_size=1024, num_layers=24, num_heads=16)

    @classmethod
    def large(cls):
        return cls(hidden_size=1280, num_layers=36, num_heads=20)

    @classmethod
    def tiny(cls):
        return cls(vocab_size=1024, hidden_size=128, num_layers=2,
                   num_heads=4, max_position=256)


class GPT2Block(nn.Layer):
    """Pre-LN decoder block. Fused QKV: one [E, 3E] GEMM (vs 3 separate) —
    bigger MXU tiles, fewer HBM round-trips; the `qkv` name matches the
    column-parallel TP sharding rule."""

    def __init__(self, cfg: GPT2Config):
        super().__init__()
        h = cfg.hidden_size
        self.num_heads = cfg.num_heads
        self.head_dim = h // cfg.num_heads
        self.attn_dropout = cfg.dropout
        self.ln_1 = nn.LayerNorm(h, epsilon=cfg.layer_norm_epsilon)
        self.qkv_proj = nn.Linear(h, 3 * h)
        self.out_proj = nn.Linear(h, h)
        self.ln_2 = nn.LayerNorm(h, epsilon=cfg.layer_norm_epsilon)
        self.fc1 = nn.Linear(h, cfg.intermediate_size)
        self.fc2 = nn.Linear(cfg.intermediate_size, h)
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, x, attn_mask=None):
        a = self.ln_1(x)
        b, s = a.shape[0], a.shape[1]
        nh, hd = self.num_heads, self.head_dim
        qkv = ops.reshape(self.qkv_proj(a), [b, s, 3, nh, hd])
        qkv = ops.transpose(qkv, [2, 0, 3, 1, 4])  # [3, B, H, S, D]
        q, k, v = qkv[0], qkv[1], qkv[2]
        o, _ = ops.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=True,
            dropout_p=self.attn_dropout if self.training else 0.0)
        o = ops.reshape(ops.transpose(o, [0, 2, 1, 3]), [b, s, nh * hd])
        x = x + self.dropout(self.out_proj(o))
        m = self.ln_2(x)
        m = self.fc2(ops.gelu(self.fc1(m), approximate=True))
        return x + self.dropout(m)


class GPT2(nn.Layer):
    def __init__(self, cfg: GPT2Config = None, **kw):
        super().__init__()
        cfg = cfg or GPT2Config(**kw)
        self.cfg = cfg
        init_std = 0.02
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                weight_attr=I.Normal(0.0, init_std))
        self.wpe = nn.Embedding(cfg.max_position, cfg.hidden_size,
                                weight_attr=I.Normal(0.0, init_std))
        self.drop = nn.Dropout(cfg.dropout)
        self.h = nn.LayerList([GPT2Block(cfg) for _ in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size,
                                 epsilon=cfg.layer_norm_epsilon)
        if not cfg.tie_embeddings:
            self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                     bias_attr=False)

    def forward(self, input_ids, position_ids=None, attn_mask=None):
        s = input_ids.shape[1]
        if position_ids is None:
            position_ids = ops.arange(0, s, dtype="int32")
        x = self.wte(input_ids) + self.wpe(position_ids)
        x = self.drop(x)
        for block in self.h:
            x = block(x, attn_mask)
        x = self.ln_f(x)
        if self.cfg.tie_embeddings:
            logits = ops.matmul(x, self.wte.weight, transpose_y=True)
        else:
            logits = self.lm_head(x)
        return logits

    def loss(self, input_ids, labels):
        logits = self(input_ids)
        return ops.cross_entropy(
            ops.reshape(logits, [-1, self.cfg.vocab_size]),
            ops.reshape(labels, [-1]))


def build_train_step(cfg: GPT2Config, remat=False, dtype="float32"):
    """Pure functional GPT-2 loss for pjit/fleet: returns
    (loss_fn(params, batch, key), init_params()). The module tree above is
    used once to materialize params; the pure fn re-binds them per call.
    """
    import jax
    import jax.numpy as jnp

    from ..core import rng as rng_mod

    model = GPT2(cfg)
    model.train()
    if dtype != "float32":
        model.to(dtype=dtype)

    def init_params():
        p, _ = model.functional_state()
        return p

    def loss_fn(params, batch, key):
        saved_p, saved_b = model.functional_state()
        rng_saved = (rng_mod._default_generator._key,
                     rng_mod._default_generator._count)
        rng_mod._default_generator._key = key
        rng_mod._default_generator._count = 0
        model.load_functional_state(params, None)
        try:
            input_ids, labels = batch["input_ids"], batch["labels"]
            loss = model.loss(Tensor(input_ids), Tensor(labels))
            return loss._value
        finally:
            model.load_functional_state(saved_p, saved_b)
            (rng_mod._default_generator._key,
             rng_mod._default_generator._count) = rng_saved

    if remat:
        import jax
        loss_fn = jax.checkpoint(loss_fn)
    return loss_fn, init_params, model
