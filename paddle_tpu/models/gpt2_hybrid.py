"""GPT-2 with full 4-D hybrid parallelism: dp × pp × mp × sp on ONE mesh.

The north-star configuration (BASELINE.json: "ERNIE/BERT-large pretraining
under Fleet collective mode on v5e-256") needs data, pipeline, tensor and
sequence parallelism composed in a single train step. Reference lineage:
fleet meta_optimizers (sharding/pipeline/hybrid_parallel_optimizer) rewrite
the program graph with NCCL send/recv + allreduce; here the whole step is one
shard_map over the (dp, pp, mp, sp) mesh and XLA emits the ICI collectives:

  dp — batch split; gradient reduction comes out of shard_map's transpose
       (replicated params -> psum cotangent), no hand-written allreduce.
  pp — GPipe microbatch rotation via ppermute (parallel/pipeline.py).
  mp — Megatron tensor parallel: column-split QKV/fc1, row-split out/fc2
       with one psum per half-block. QKV is stored [E, H, 3, d] so the mp
       split on H keeps each rank's q/k/v for its own heads contiguous.
  sp — ring attention over the sequence shards (parallel/ring_attention.py,
       Pallas flash kernels inside each ring step when shapes allow);
       ring_impl="zigzag" selects the load-balanced causal ring (the
       caller feeds the batch in zigzag_order layout; position embeddings
       follow the permutation inside inner()), "ulysses" the all-to-all
       mode.

Params are a flat dict of jnp arrays; per-stage leaves are stacked
[pp, L/pp, ...] so the pp axis shards stages and a lax.scan walks the
layers inside a stage. `reference_loss` computes the identical math without
any mesh for the single-device parity assertion.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P


def pad_vocab(vocab_size, mp):
    """Megatron vocab padding: round V up so the mp axis divides it; the
    padded logit columns are masked to -inf in the loss."""
    return -(-vocab_size // mp) * mp


def init_hybrid_gpt2_params(key, vocab_size, hidden, num_heads, num_layers,
                            pp, max_position, intermediate=None,
                            dtype=jnp.float32, mp=1):
    """Flat param dict; stage leaves stacked [pp, L/pp, ...]. The embedding
    is vocab-padded to a multiple of `mp` (vocab-parallel sharding)."""
    assert num_layers % pp == 0, (num_layers, pp)
    lps = num_layers // pp
    e = hidden
    h = num_heads
    d = e // h
    f = intermediate or 4 * e
    ks = jax.random.split(key, 8)

    def nrm(k, shape, std=0.02):
        return (jax.random.normal(k, shape) * std).astype(dtype)

    v_pad = pad_vocab(vocab_size, mp)
    wte = nrm(ks[0], (vocab_size, e))
    if v_pad > vocab_size:  # padded rows zero: they receive no gradient mass
        wte = jnp.concatenate(
            [wte, jnp.zeros((v_pad - vocab_size, e), dtype)], axis=0)

    return {
        "wte": wte,
        "wpe": nrm(ks[1], (max_position, e)),
        "ln_f.w": jnp.ones((e,), dtype),
        "ln_f.b": jnp.zeros((e,), dtype),
        "blk.ln1.w": jnp.ones((pp, lps, e), dtype),
        "blk.ln1.b": jnp.zeros((pp, lps, e), dtype),
        # [E, H, 3, d]: mp splits H, so each rank holds q/k/v of its heads
        "blk.wqkv": nrm(ks[2], (pp, lps, e, h, 3, d)),
        "blk.bqkv": jnp.zeros((pp, lps, h, 3, d), dtype),
        "blk.wo": nrm(ks[3], (pp, lps, h, d, e)),
        "blk.bo": jnp.zeros((pp, lps, e), dtype),
        "blk.ln2.w": jnp.ones((pp, lps, e), dtype),
        "blk.ln2.b": jnp.zeros((pp, lps, e), dtype),
        "blk.w1": nrm(ks[4], (pp, lps, e, f)),
        "blk.b1": jnp.zeros((pp, lps, f), dtype),
        "blk.w2": nrm(ks[5], (pp, lps, f, e)),
        "blk.b2": jnp.zeros((pp, lps, e), dtype),
    }


def hybrid_param_specs(params):
    """PartitionSpec per leaf: stage dim -> pp, TP dim -> mp, rest replicated.
    (Used both as shard_map in_specs and jit in_shardings.)"""
    specs = {
        # vocab-parallel (Megatron): each mp rank owns V/mp embedding rows;
        # the embed is a masked local gather + psum, the logits stay
        # [B,S,V/mp] per rank and the loss uses psum'd softmax statistics —
        # [B,S,V] never materializes on any rank (VERDICT r2 weak #7)
        "wte": P("mp", None),
        "wpe": P(),
        "ln_f.w": P(),
        "ln_f.b": P(),
        "blk.ln1.w": P("pp"),
        "blk.ln1.b": P("pp"),
        "blk.wqkv": P("pp", None, None, "mp"),
        "blk.bqkv": P("pp", None, "mp"),
        "blk.wo": P("pp", None, "mp"),
        "blk.bo": P("pp"),
        "blk.ln2.w": P("pp"),
        "blk.ln2.b": P("pp"),
        "blk.w1": P("pp", None, None, "mp"),
        "blk.b1": P("pp", None, "mp"),
        "blk.w2": P("pp", None, "mp"),
        "blk.b2": P("pp"),
    }
    assert set(specs) == set(params)
    return specs


def _ln(x, w, b, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * w + b


def _stage_fn(stage, x, *, sp_axis, mp_axis, ring_impl):
    """One pipeline stage: scan over its L/pp layers. `stage` leaves are this
    rank's slice: [L/pp, ...] (TP dims already local)."""
    from ..parallel.ring_attention import ring_attention

    def layer(h, wl):
        a = _ln(h, wl["blk.ln1.w"], wl["blk.ln1.b"])
        qkv = jnp.einsum("bse,ehtd->bshtd", a, wl["blk.wqkv"]) \
            + wl["blk.bqkv"]
        q = jnp.moveaxis(qkv[:, :, :, 0], 1, 2)  # [mb, H_loc, S_l, d]
        k = jnp.moveaxis(qkv[:, :, :, 1], 1, 2)
        v = jnp.moveaxis(qkv[:, :, :, 2], 1, 2)
        if sp_axis is not None:
            if ring_impl == "ulysses":  # all-to-all sequence parallelism
                from ..parallel.ulysses import ulysses_attention
                o = ulysses_attention(q, k, v, axis_name=sp_axis,
                                      causal=True)
            elif ring_impl == "zigzag":
                # load-balanced causal ring: the batch (and positions —
                # see inner()) are in zigzag layout, every rank does
                # equal work per ring step
                from ..parallel.ring_attention import zigzag_ring_attention
                o = zigzag_ring_attention(q, k, v, axis_name=sp_axis)
            else:
                o = ring_attention(q, k, v, axis_name=sp_axis, causal=True,
                                   impl=ring_impl)
        else:  # no sp axis: plain causal attention
            s = q.shape[2]
            logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (q.shape[-1] ** -0.5)
            mask = jnp.tril(jnp.ones((s, s), bool))
            logits = jnp.where(mask, logits, -1e30)
            o = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(logits, -1), v)
        att = jnp.einsum("bhsd,hde->bse", o, wl["blk.wo"])
        if mp_axis is not None:
            att = jax.lax.psum(att, mp_axis)
        h = h + att + wl["blk.bo"]
        m = _ln(h, wl["blk.ln2.w"], wl["blk.ln2.b"])
        m = jax.nn.gelu(jnp.einsum("bse,ef->bsf", m, wl["blk.w1"])
                        + wl["blk.b1"], approximate=True)
        m = jnp.einsum("bsf,fe->bse", m, wl["blk.w2"])
        if mp_axis is not None:
            m = jax.lax.psum(m, mp_axis)
        return h + m + wl["blk.b2"], None

    blk = {k: v for k, v in stage.items() if k.startswith("blk.")}
    out, _ = jax.lax.scan(layer, x, blk)
    return out


def build_hybrid_gpt2_loss(mesh, num_microbatches=2, ring_impl=None,
                           vocab_size=None, pp_schedule="gpipe",
                           num_virtual=1):
    """Pure loss_fn(params, batch) running dp×pp×mp×sp on `mesh`.

    batch: {"input_ids": [B, S] int32, "labels": [B, S] int32} — B sharded
    over dp, S over sp. Differentiable end-to-end: grads of replicated
    leaves psum automatically via the shard_map transpose.

    `vocab_size`: the TRUE vocab size when the embedding is padded for the
    mp split (pad_vocab); padded logit columns are masked out of the
    softmax statistics.
    `pp_schedule`: "gpipe" or "interleaved" (circular; each pp rank holds
    `num_virtual` non-adjacent layer chunks — parallel/pipeline.py).
    """
    from jax.experimental.shard_map import shard_map

    from ..parallel.pipeline import (pipeline_apply,
                                     pipeline_apply_interleaved)

    axes = dict(mesh.shape)
    use_pp = axes.get("pp", 1) > 1
    if pp_schedule not in ("gpipe", "interleaved"):
        raise ValueError(f"unknown pipeline schedule {pp_schedule!r}")
    interleaved = use_pp and pp_schedule == "interleaved" and num_virtual > 1
    sp_axis = "sp" if axes.get("sp", 1) > 1 else None
    mp_axis = "mp" if axes.get("mp", 1) > 1 else None

    def inner(params, ids, labels):
        sp_idx = jax.lax.axis_index("sp") if sp_axis else 0
        s_l = ids.shape[1]
        if ring_impl == "zigzag" and sp_axis is not None:
            # zigzag layout: this rank holds global chunks (i, 2n-1-i) of
            # 2n — position embeddings must follow the SAME permutation
            # the caller applied to the batch (zigzag_order)
            from ..parallel.mesh import axis_size
            n_sp = axis_size(sp_axis)
            half = s_l // 2
            pos = jnp.concatenate(
                [sp_idx * half + jnp.arange(half),
                 (2 * n_sp - 1 - sp_idx) * half + jnp.arange(half)])
        else:
            pos = sp_idx * s_l + jnp.arange(s_l)
        wte = params["wte"]  # mp-local shard: [V_pad/mp, E]
        v_loc = wte.shape[0]
        if mp_axis:
            # vocab-parallel embed: masked local gather + psum over mp
            v_start = jax.lax.axis_index(mp_axis) * v_loc
            lids = ids - v_start
            ok = (lids >= 0) & (lids < v_loc)
            x = jnp.where(ok[..., None],
                          wte[jnp.clip(lids, 0, v_loc - 1)], 0.0)
            x = jax.lax.psum(x, mp_axis)
        else:
            v_start = 0
            x = wte[ids]
        x = x + params["wpe"][pos][None]
        stage_fn = functools.partial(_stage_fn, sp_axis=sp_axis,
                                     mp_axis=mp_axis, ring_impl=ring_impl)
        if interleaved:
            # pass ONLY the chunk-stacked blk leaves (the schedule indexes
            # every leaf's leading V dim); blk arrive [V, 1, nblk, ...]
            # with dim 1 pp-sharded
            chunks = {k: v[:, 0] for k, v in params.items()
                      if k.startswith("blk.")}
            m = num_microbatches
            mbs = x.reshape((m, x.shape[0] // m) + x.shape[1:])
            outs = pipeline_apply_interleaved(stage_fn, chunks, mbs, "pp")
            y = outs.reshape((x.shape[0],) + outs.shape[2:])
        elif use_pp:
            stage = {k: (v[0] if k.startswith("blk.") else v)
                     for k, v in params.items()}  # local: [1, L/pp, ...]
            m = num_microbatches
            mbs = x.reshape((m, x.shape[0] // m) + x.shape[1:])
            outs = pipeline_apply(stage_fn, stage, mbs, "pp")
            y = outs.reshape((x.shape[0],) + outs.shape[2:])
        else:
            stage = {k: (v[0] if k.startswith("blk.") else v)
                     for k, v in params.items()}
            y = stage_fn(stage, x)
        y = _ln(y, params["ln_f.w"], params["ln_f.b"])
        # logits stay vocab-sharded: [B, S_l, V_pad/mp] per rank
        logits = jnp.einsum("bse,ve->bsv", y, wte).astype(jnp.float32)
        if vocab_size is not None:  # mask padded vocab columns
            col = v_start + jnp.arange(v_loc)
            logits = jnp.where(col[None, None, :] < vocab_size, logits,
                               -jnp.inf)
        if mp_axis:
            # Megatron vocab-parallel CE from psum'd softmax statistics.
            # The max is detached (pmax has no VJP; the CE gradient
            # softmax(l) - onehot is exact for any constant shift).
            lmax = jax.lax.pmax(
                jax.lax.stop_gradient(jnp.max(logits, axis=-1)),
                mp_axis)  # [B,S]
            sumexp = jax.lax.psum(
                jnp.sum(jnp.exp(logits - lmax[..., None]), axis=-1),
                mp_axis)
            lt = labels - v_start
            ok = (lt >= 0) & (lt < v_loc)
            tgt = jnp.take_along_axis(
                logits, jnp.clip(lt, 0, v_loc - 1)[..., None], axis=-1
            )[..., 0]
            tgt = jax.lax.psum(jnp.where(ok, tgt, 0.0), mp_axis)
            nll = jnp.log(sumexp) + lmax - tgt
        else:
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(logp, labels[..., None],
                                       axis=-1)[..., 0]
        loss = jnp.mean(nll)
        for ax in ("dp", "sp"):
            if axes.get(ax, 1) > 1:
                loss = jax.lax.pmean(loss, ax)
        if use_pp:
            loss = jax.lax.pmean(loss, "pp")
        return loss

    def loss_fn(params, batch):
        specs = hybrid_param_specs(params)
        data_spec = P("dp", "sp")
        params_in = params
        if interleaved:
            # blk [pp, lps, ...] is layer order p*lps + i; flatten to [L]
            # and regroup [V, S, nblk, ...] — sharding dim 1 on pp gives
            # rank r chunks {l*S + r}, the circular placement
            s_pp = axes["pp"]

            def regroup(k, v):
                if not k.startswith("blk."):
                    return v
                L = v.shape[0] * v.shape[1]
                if L % (num_virtual * s_pp):
                    raise ValueError(
                        f"interleaved schedule needs num_layers ({L}) "
                        f"divisible by num_virtual*pp "
                        f"({num_virtual}*{s_pp})")
                nblk = L // (num_virtual * s_pp)
                return v.reshape((L,) + v.shape[2:]).reshape(
                    (num_virtual, s_pp, nblk) + v.shape[2:])

            params_in = {k: regroup(k, v) for k, v in params.items()}

            def respec(k):
                if not k.startswith("blk."):
                    return specs[k]
                # (pp, lps_spec, rest...) -> (None_V, pp_S, None_nblk,
                # rest...): TP dims keep their mp sharding
                rest = tuple(specs[k])[2:]
                return P(*((None, "pp", None) + rest))

            specs = {k: respec(k) for k in specs}
        return shard_map(
            inner, mesh=mesh,
            in_specs=(specs, data_spec, data_spec),
            out_specs=P(),
            check_rep=False)(params_in, batch["input_ids"],
                             batch["labels"])

    return loss_fn


def reference_loss(params, batch, vocab_size=None):
    """Same math, no mesh — the parity oracle for dryrun_multichip."""
    ids, labels = batch["input_ids"], batch["labels"]
    s = ids.shape[1]
    x = params["wte"][ids] + params["wpe"][jnp.arange(s)][None]
    pp, lps = params["blk.w1"].shape[:2]
    for pi in range(pp):
        for li in range(lps):
            wl = {k: v[pi, li] for k, v in params.items()
                  if k.startswith("blk.")}
            a = _ln(x, wl["blk.ln1.w"], wl["blk.ln1.b"])
            qkv = jnp.einsum("bse,ehtd->bshtd", a, wl["blk.wqkv"]) \
                + wl["blk.bqkv"]
            q = jnp.moveaxis(qkv[:, :, :, 0], 1, 2)
            k = jnp.moveaxis(qkv[:, :, :, 1], 1, 2)
            v = jnp.moveaxis(qkv[:, :, :, 2], 1, 2)
            logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (q.shape[-1] ** -0.5)
            mask = jnp.tril(jnp.ones((s, s), bool))
            logits = jnp.where(mask, logits, -1e30)
            o = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(logits, -1), v)
            att = jnp.einsum("bhsd,hde->bse", o, wl["blk.wo"])
            x = x + att + wl["blk.bo"]
            m = _ln(x, wl["blk.ln2.w"], wl["blk.ln2.b"])
            m = jax.nn.gelu(jnp.einsum("bse,ef->bsf", m, wl["blk.w1"])
                            + wl["blk.b1"], approximate=True)
            x = x + jnp.einsum("bsf,fe->bse", m, wl["blk.w2"]) + wl["blk.b2"]
    x = _ln(x, params["ln_f.w"], params["ln_f.b"])
    logits = jnp.einsum("bse,ve->bsv", x, params["wte"]).astype(jnp.float32)
    if vocab_size is not None and vocab_size < logits.shape[-1]:
        col = jnp.arange(logits.shape[-1])
        logits = jnp.where(col[None, None, :] < vocab_size, logits, -jnp.inf)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)
    return jnp.mean(nll)


def hybrid_shardings(mesh, params, optimizer_state=None, zero_dp=True):
    """NamedShardings for jit: params per hybrid_param_specs; optimizer
    slots additionally ZeRO-sharded over dp on replicated leaves (stage-1
    style: the big replicated tensors' moments live dp-sharded)."""
    specs = hybrid_param_specs(params)
    p_sh = {k: NamedSharding(mesh, specs[k]) for k in params}

    def slot_spec(name, v):
        base = specs[name]
        if zero_dp and base == P():
            dp = mesh.shape["dp"]
            for i, s in enumerate(v.shape):
                if s % dp == 0 and s >= dp:
                    return NamedSharding(
                        mesh,
                        P(*([None] * i + ["dp"]
                            + [None] * (v.ndim - i - 1))))
        return NamedSharding(mesh, base)

    if optimizer_state is None:
        return p_sh, None
    slots = {name: {k: slot_spec(name, params[name])
                    for k in optimizer_state["slots"][name]}
             for name in optimizer_state["slots"]}
    os_sh = {"slots": slots, "t": NamedSharding(mesh, P())}
    return p_sh, os_sh
