"""GPT-2 pipeline-parallel training step.

BASELINE config 5: "GPT-2 medium with fused_attention_op → Pallas flash-attn,
pipeline-parallel Fleet". The L transformer blocks are stacked into per-leaf
[L, ...] arrays, the leading dim is sharded over the `pp` mesh axis, and each
rank scans its local L/S blocks inside the GPipe schedule
(parallel/pipeline.py). Embedding + final-LN/head run replicated outside the
pipelined region; their grads flow through the shard_map boundary.
"""
from __future__ import annotations

import numpy as np

from .gpt2 import GPT2, GPT2Config


def _split_block_params(params):
    """Split flat name->array params into (stacked_blocks, other).

    stacked_blocks: {subname: [L, ...]} for names 'h.{i}.{subname}'.
    """
    import jax.numpy as jnp
    blocks = {}
    other = {}
    for name, v in params.items():
        if name.startswith("h."):
            _, idx, sub = name.split(".", 2)
            blocks.setdefault(sub, {})[int(idx)] = v
        else:
            other[name] = v
    stacked = {sub: jnp.stack([d[i] for i in range(len(d))])
               for sub, d in blocks.items()}
    return stacked, other


def _merge_block_params(stacked, other):
    params = dict(other)
    for sub, arr in stacked.items():
        for i in range(arr.shape[0]):
            params[f"h.{i}.{sub}"] = arr[i]
    return params


def build_pp_train_step(cfg: GPT2Config, mesh, num_microbatches=4,
                        pp_axis="pp", schedule="gpipe", num_virtual=1):
    """Returns (loss_fn(stacked, other, batch), init()) where loss_fn runs
    the selected pipeline schedule over `pp_axis` of `mesh` ("gpipe", or
    "interleaved" with `num_virtual` chunks per rank — see
    parallel/pipeline.py for the schedules and their bubble fractions)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..core import rng as rng_mod
    from ..core.tensor import Tensor
    from ..parallel.pipeline import (pipeline_apply,
                                     pipeline_apply_interleaved)

    model = GPT2(cfg)
    model.train()
    assert cfg.dropout == 0.0, "pp step: disable dropout (rng is per-trace)"
    s_pp = mesh.shape[pp_axis]
    assert cfg.num_layers % s_pp == 0
    if schedule not in ("gpipe", "interleaved"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    interleaved = schedule == "interleaved" and num_virtual > 1
    if interleaved:
        assert cfg.num_layers % (s_pp * num_virtual) == 0
        assert num_microbatches % s_pp == 0

    block0 = model.h[0]

    def block_apply(block_tree, x):
        """Apply one transformer block with the given param tree (names are
        block-relative, e.g. 'ln_1.weight')."""
        lookup = dict(block0.named_parameters())
        saved = {n: p._value for n, p in lookup.items()}
        for n, v in block_tree.items():
            lookup[n]._value = v
        try:
            return block0(Tensor(x))._value
        finally:
            for n, p in lookup.items():
                p._value = saved[n]

    def stage_fn(stage_tree, x):
        # stage_tree leaves: [L/S, ...] — scan the local blocks
        def body(h, one_block):
            return block_apply(one_block, h), None

        out, _ = jax.lax.scan(body, x, stage_tree)
        return out

    def init():
        params, _ = model.functional_state()
        stacked, other = _split_block_params(params)
        return stacked, other

    def embed(other, input_ids):
        s = input_ids.shape[1]
        pos = jnp.arange(s)
        return (jnp.take(other["wte.weight"], input_ids, axis=0)
                + jnp.take(other["wpe.weight"], pos, axis=0))

    def head_loss(other, h, labels):
        ln_w = other["ln_f.weight"]
        ln_b = other["ln_f.bias"]
        mu = jnp.mean(h, axis=-1, keepdims=True)
        var = jnp.var(h, axis=-1, keepdims=True)
        h = (h - mu) * jax.lax.rsqrt(var + cfg.layer_norm_epsilon)
        h = h * ln_w + ln_b
        logits = jnp.einsum("bsd,vd->bsv", h, other["wte.weight"])
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(
            logp, labels[..., None].astype(jnp.int32), axis=-1)
        return jnp.mean(nll)

    def loss_fn(stacked, other, batch):
        x0 = embed(other, batch["input_ids"])

        def inner(stacked_local, x0, labels):
            m = num_microbatches
            mbs = x0.reshape((m, x0.shape[0] // m) + x0.shape[1:])
            if interleaved:
                # leaves [V, 1, L/(V*S), ...]: this rank's V chunks
                chunk_tree = jax.tree_util.tree_map(
                    lambda p: p[:, 0], stacked_local)
                outs = pipeline_apply_interleaved(stage_fn, chunk_tree,
                                                  mbs, pp_axis)
            else:
                stage_tree = stacked_local  # leaves [L/S, ...] local
                outs = pipeline_apply(stage_fn, stage_tree, mbs, pp_axis)
            h = outs.reshape((x0.shape[0],) + outs.shape[2:])
            return h

        if interleaved:
            # [L, ...] layer order -> [V, S, L/(V*S), ...]; shard dim 1 on
            # pp: rank r holds chunks {l*S + r} of consecutive layers —
            # the circular placement (layers l*S*(L/VS) + r*(L/VS) ...)
            nblk = cfg.num_layers // (s_pp * num_virtual)
            stacked_in = jax.tree_util.tree_map(
                lambda p: p.reshape((num_virtual, s_pp, nblk)
                                    + p.shape[1:]),
                stacked)
            spec_stk = jax.tree_util.tree_map(
                lambda _: P(None, pp_axis), stacked_in)
        else:
            stacked_in = stacked
            spec_stk = jax.tree_util.tree_map(lambda _: P(pp_axis), stacked)
        h = shard_map(inner, mesh=mesh,
                      in_specs=(spec_stk, P(), P()),
                      out_specs=P(), check_rep=False)(
            stacked_in, x0, batch["labels"])
        return head_loss(other, h, batch["labels"])

    return loss_fn, init
