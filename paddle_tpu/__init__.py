"""paddle_tpu — a TPU-native deep-learning framework with the capability
surface of PaddlePaddle (reference: joey12300/Paddle @ /root/reference),
rebuilt from scratch on JAX/XLA/Pallas.

Top-level namespace mirrors `import paddle` (ref: python/paddle/__init__.py):
tensors, ops, nn, optimizer, static, distributed, amp, io, jit, metric,
vision, incubate. Execution defaults to dygraph (eager) exactly like the
reference 2.0 API; `paddle_tpu.enable_static()` switches to the
Program/Executor path, and `paddle_tpu.jit.to_static` compiles eager code
into a single XLA computation.
"""
from __future__ import annotations

# core first (ops patches Tensor methods on import)
from .core import dtype as _dtype_mod
from .core.dtype import (  # noqa: F401
    bfloat16, bool_, complex64, complex128, float16, float32, float64,
    get_default_dtype, int8, int16, int32, int64, set_default_dtype, uint8,
)
from .core.place import (  # noqa: F401
    CPUPlace, CUDAPinnedPlace, CUDAPlace, TPUPlace, XPUPlace, get_device,
    is_compiled_with_cuda, is_compiled_with_tpu, is_compiled_with_xpu,
    set_device,
)
from .core.rng import get_rng_state, seed, set_rng_state  # noqa: F401
from .core.tensor import Parameter, Tensor, is_tensor, to_tensor  # noqa: F401
from .core.param_attr import ParamAttr  # noqa: F401
from .core import autograd as _autograd
from .core.autograd import enable_grad, grad  # noqa: F401
from .core.mode import disable_static, enable_static, in_dygraph_mode  # noqa: F401

no_grad = _autograd._NoGradDecorator()

from . import ops  # noqa: E402  (patches Tensor)
from .ops import *  # noqa: F401,F403,E402
from .ops import sum, max, min, abs, all, any, pow, round, slice  # noqa: F401,A004,E402

from . import nn  # noqa: E402,F401
from . import optimizer  # noqa: E402,F401
from . import regularizer  # noqa: E402,F401
from .regularizer import L1Decay, L2Decay  # noqa: E402,F401
from . import static  # noqa: E402,F401
from . import framework  # noqa: E402,F401
from .framework.io import load, save  # noqa: E402,F401
from . import io  # noqa: E402,F401
from . import jit  # noqa: E402,F401
from . import amp  # noqa: E402,F401
from . import metric  # noqa: E402,F401
from . import distributed  # noqa: E402,F401
from . import vision  # noqa: E402,F401
from . import inference  # noqa: E402,F401
from . import hapi  # noqa: E402,F401
from .hapi.model import Model  # noqa: E402,F401
from . import utils  # noqa: E402,F401
from . import observability  # noqa: E402,F401
from . import sampling  # noqa: E402,F401
from . import incubate  # noqa: E402,F401
from . import parallel  # noqa: E402,F401
from . import text  # noqa: E402,F401
from . import version  # noqa: E402,F401

# the ops star-import bound submodule names (linalg, loss, ...) onto this
# namespace; import the real top-level modules explicitly so they win
import importlib as _importlib  # noqa: E402

linalg = _importlib.import_module(".linalg", __name__)
tensor = _importlib.import_module(".tensor", __name__)
autograd = _importlib.import_module(".autograd", __name__)
from . import distribution  # noqa: E402,F401
from . import fluid  # noqa: E402,F401


def __getattr__(name):
    # lazy heavy namespaces (PEP 562): deployment processes (inference.
    # Predictor on a jit.save'd artifact) never pay for — or depend on —
    # the model classes / dataset loaders; first touch still works
    if name in ("models", "dataset"):
        mod = _importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

# legacy fluid-era top-level names kept by the reference 2.0 namespace
from .compat import *  # noqa: F401,F403,E402
from .reader import batch  # noqa: E402,F401  (ref: python/paddle/batch.py)
from .compat import (  # noqa: E402,F401
    ComplexVariable, LoDTensor, LoDTensorArray, VarBase,
    disable_dygraph, enable_dygraph, get_cuda_rng_state, get_cudnn_version,
    monkey_patch_math_varbase, monkey_patch_variable, set_cuda_rng_state,
    set_printoptions,
)
from .distributed.parallel import DataParallel  # noqa: E402,F401
from .fluid.layers import (  # noqa: E402,F401
    create_global_var, create_parameter, data, fill_constant,
)
from .hapi import callbacks  # noqa: E402,F401

__version__ = version.full_version


def ones(shape, dtype=None, name=None):
    return ops.ones(shape, dtype)


def zeros(shape, dtype=None, name=None):
    return ops.zeros(shape, dtype)


def rand(shape, dtype=None, name=None):
    return ops.rand(shape, dtype)


def randn(shape, dtype=None, name=None):
    return ops.randn(shape, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    return ops.arange(start, end, step, dtype)


def full(shape, fill_value, dtype=None, name=None):
    return ops.full(shape, fill_value, dtype)


def set_grad_enabled(flag):
    import contextlib

    from .core import autograd as ag

    @contextlib.contextmanager
    def cm():
        prev = ag._grad_enabled
        ag._grad_enabled = bool(flag)
        try:
            yield
        finally:
            ag._grad_enabled = prev
    return cm()


def is_grad_enabled():
    return _autograd.grad_enabled()


def summary(net, input_size=None, dtypes=None, input=None):  # noqa: A002
    import builtins

    import numpy as _np
    total = builtins.sum(int(_np.prod(p.shape)) for p in net.parameters())
    trainable = builtins.sum(int(_np.prod(p.shape))
                             for p in net.parameters() if p.trainable)
    info = {"total_params": total, "trainable_params": trainable}
    print(f"Total params: {total:,}\n"  # cli-print: summary() report
          f"Trainable params: {trainable:,}")
    return info


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Per-layer FLOPs profile of one forward (ref: paddle.flops /
    hapi/dynamic_flops.py) — hook-based counter in hapi/static_flops.py."""
    from .hapi.static_flops import flops as _flops
    return _flops(net, input_size, custom_ops=custom_ops,
                  print_detail=print_detail)

from .version import commit, full_version  # noqa: E402,F401
from . import sysconfig  # noqa: E402,F401


from . import onnx  # noqa: E402,F401 — raising-by-design package (SURVEY §2 #39)
