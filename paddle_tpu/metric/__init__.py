"""Metrics (ref: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


class Metric:
    def __init__(self, name=None):
        self._name = name or type(self).__name__.lower()

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self._name

    def compute(self, pred, label, *args):
        return pred, label


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__(name or "acc")
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        p = pred.numpy() if isinstance(pred, Tensor) else np.asarray(pred)
        l = label.numpy() if isinstance(label, Tensor) else np.asarray(label)
        if l.ndim == p.ndim and l.shape[-1] == 1:
            l = l[..., 0]
        topk_idx = np.argsort(-p, axis=-1)[..., :self.maxk]
        correct = topk_idx == l[..., None]
        return correct

    def update(self, correct, *args):
        c = correct.numpy() if isinstance(correct, Tensor) else np.asarray(correct)
        accs = []
        for i, k in enumerate(self.topk):
            num = c[..., :k].sum()
            n = int(np.prod(c.shape[:-1]))
            self.total[i] += float(num)
            self.count[i] += n
            accs.append(float(num) / max(n, 1))
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res


class Precision(Metric):
    def __init__(self, name=None):
        super().__init__(name or "precision")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)
             > 0.5).astype(int).reshape(-1)
        l = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels
                       ).astype(int).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        return self.tp / max(self.tp + self.fp, 1)


class Recall(Metric):
    def __init__(self, name=None):
        super().__init__(name or "recall")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)
             > 0.5).astype(int).reshape(-1)
        l = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels
                       ).astype(int).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        return self.tp / max(self.tp + self.fn, 1)


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__(name or "auc")
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        l = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels
                       ).astype(int).reshape(-1)
        idx = np.clip((p * self.num_thresholds).astype(int), 0, self.num_thresholds)
        np.add.at(self._stat_pos, idx[l == 1], 1)
        np.add.at(self._stat_neg, idx[l == 0], 1)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # iterate from high threshold to low
        pos = np.cumsum(self._stat_pos[::-1])
        neg = np.cumsum(self._stat_neg[::-1])
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapezoid(tpr, fpr)) if hasattr(np, "trapezoid") \
            else float(np.trapz(tpr, fpr))


def accuracy(input, label, k=1):  # noqa: A002
    import jax.numpy as jnp
    p = input._value if isinstance(input, Tensor) else jnp.asarray(input)
    l = label._value if isinstance(label, Tensor) else jnp.asarray(label)
    if l.ndim == p.ndim and l.shape[-1] == 1:
        l = l[..., 0]
    idx = jnp.argsort(-p, axis=-1)[..., :k]
    correct = jnp.any(idx == l[..., None], axis=-1)
    return Tensor(jnp.mean(correct.astype(jnp.float32)))


def chunk_eval(input, label, chunk_scheme, num_chunk_types,  # noqa: A002
               excluded_chunk_types=None, seq_length=None):
    """Chunk-level precision/recall/F1 for sequence labeling (ref:
    chunk_eval_op.cc). Schemes: IOB, IOE, IOBES, plain."""
    import numpy as np

    from ..core.tensor import Tensor

    def decode(tags):
        # returns set of (start, end, type) chunks
        chunks = []
        start, ctype = None, None
        for i, t in enumerate(list(tags) + [-1]):
            if chunk_scheme == "plain":
                ty = t if t >= 0 else None
                if ty is not None and (ctype is None or ty != ctype):
                    if ctype is not None:
                        chunks.append((start, i - 1, ctype))
                    start, ctype = i, ty
                elif ty is None and ctype is not None:
                    chunks.append((start, i - 1, ctype))
                    ctype = None
                continue
            n_states = {"IOB": 2, "IOE": 2, "IOBES": 4}[chunk_scheme]
            if t < 0 or t >= n_states * num_chunk_types:
                if ctype is not None:
                    chunks.append((start, i - 1, ctype))
                    ctype = None
                continue
            ty, pos = t // n_states, t % n_states
            begin = pos == 0 if chunk_scheme in ("IOB", "IOBES") else \
                ctype is None
            if chunk_scheme == "IOBES" and pos == 3:  # S: single
                chunks.append((i, i, ty))
                ctype = None
                continue
            if begin or ctype != ty:
                if ctype is not None:
                    chunks.append((start, i - 1, ctype))
                start, ctype = i, ty
            ends = (chunk_scheme == "IOE" and pos == 1) or \
                (chunk_scheme == "IOBES" and pos == 2)
            if ends and ctype is not None:
                chunks.append((start, i, ctype))
                ctype = None
        return set(chunks)

    iv = np.asarray(input.numpy() if hasattr(input, "numpy") else input)
    lv = np.asarray(label.numpy() if hasattr(label, "numpy") else label)
    if iv.ndim == 1:
        iv, lv = iv[None], lv[None]
    if seq_length is not None:
        sl = np.asarray(seq_length.numpy() if hasattr(seq_length, "numpy")
                        else seq_length).reshape(-1)
    else:
        sl = [iv.shape[1]] * iv.shape[0]
    n_infer = n_label = n_correct = 0
    for row in range(iv.shape[0]):
        pred = decode(iv[row, :sl[row]])
        gold = decode(lv[row, :sl[row]])
        if excluded_chunk_types:
            pred = {c for c in pred if c[2] not in excluded_chunk_types}
            gold = {c for c in gold if c[2] not in excluded_chunk_types}
        n_infer += len(pred)
        n_label += len(gold)
        n_correct += len(pred & gold)
    p = n_correct / n_infer if n_infer else 0.0
    r = n_correct / n_label if n_label else 0.0
    f1 = 2 * p * r / (p + r) if p + r else 0.0
    mk = lambda v: Tensor(np.asarray([v], np.float32))
    mki = lambda v: Tensor(np.asarray([v], np.int64))
    return (mk(p), mk(r), mk(f1), mki(n_infer), mki(n_label),
            mki(n_correct))


import sys as _sys  # noqa: E402

metrics = _sys.modules[__name__]


def mean_iou(input, label, num_classes):  # noqa: A002
    """Mean IoU over classes (ref: metric/__init__.py re-exporting
    fluid.layers.nn.mean_iou) — same computation as the fluid legacy op."""
    from ..fluid.layers_legacy import mean_iou as _impl
    return _impl(input, label, num_classes)
