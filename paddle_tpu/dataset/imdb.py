"""ref: python/paddle/dataset/imdb.py — sentiment classification.
word_dict() -> {word: idx}; train(word_idx)/test(word_idx) yield
(word-id list, 0/1 label)."""
from __future__ import annotations

import re

from . import _text_synth


def tokenize(pattern=None):
    """ref: imdb.py tokenize — yields token lists (synthetic corpus)."""
    for s in _text_synth.sentences(200, seed=10):
        yield s


def build_dict(pattern=None, cutoff=0):
    """ref: imdb.py:60 — frequency-sorted word dict with <unk> last."""
    freq = {}
    for ws in tokenize(pattern):
        for w in ws:
            freq[w] = freq.get(w, 0) + 1
    freq = {w: c for w, c in freq.items() if c > cutoff}
    ordered = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
    word_idx = {w: i for i, (w, _) in enumerate(ordered)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def word_dict():
    return build_dict()


def _reader(word_idx, seed):
    unk = word_idx.get("<unk>", len(word_idx) - 1)

    def reader():
        for label in (0, 1):
            for ws in _text_synth.sentences(100, seed=seed + label,
                                            sentiment=label):
                yield [word_idx.get(w, unk) for w in ws], label

    return reader


def train(word_idx):
    return _reader(word_idx, seed=20)


def test(word_idx):
    return _reader(word_idx, seed=40)
