"""ref: python/paddle/dataset/common.py — download/cache helpers.

Zero-egress: download() only serves files already in the cache dir (or
raises with guidance); md5file/split/cluster_files_reader keep their
reference behavior.
"""
from __future__ import annotations

import hashlib
import glob
import os
import pickle

DATA_HOME = os.path.expanduser("~/.cache/paddle/dataset")


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """Serve from the local cache only — this environment has no egress.
    Place the file at ~/.cache/paddle/dataset/<module>/<name> yourself."""
    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(
        dirname, save_name if save_name else url.split("/")[-1])
    if os.path.exists(filename):
        return filename
    raise RuntimeError(
        f"zero-egress environment: cannot download {url}; put the file at "
        f"{filename} (the synthetic fallbacks in paddle.dataset.* need no "
        f"files at all)")


def split(reader, line_count, suffix="%05d.pickle", dumper=pickle.dump):
    """Split a reader's samples into multiple pickle files
    (ref: common.py split)."""
    indx_f = 0
    batch = []
    out_paths = []

    def flush():
        nonlocal indx_f, batch
        if batch:
            path = suffix % indx_f
            with open(path, "wb") as f:
                dumper(batch, f)
            out_paths.append(path)
            indx_f += 1
            batch = []

    for sample in reader():
        batch.append(sample)
        if len(batch) == line_count:
            flush()
    flush()
    return out_paths


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=pickle.load):
    """Read this trainer's shard of the split files (ref: common.py)."""

    def reader():
        flist = sorted(glob.glob(files_pattern))
        my = flist[trainer_id::trainer_count]
        for fn in my:
            with open(fn, "rb") as f:
                for sample in loader(f):
                    yield sample

    return reader
