"""ref: python/paddle/dataset/voc2012.py — segmentation pairs.
train()/test()/val() yield (3xHxW float image, HxW int label mask)."""
from __future__ import annotations

import numpy as np

_N_CLASSES = 21
_HW = 32


def _reader(seed, n):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            img = rng.rand(3, _HW, _HW).astype(np.float32)
            # blocky masks: a class rectangle on background
            mask = np.zeros((_HW, _HW), np.int64)
            c = rng.randint(1, _N_CLASSES)
            y0, x0 = rng.randint(0, _HW // 2, 2)
            mask[y0:y0 + _HW // 2, x0:x0 + _HW // 2] = c
            yield img, mask

    return reader


def train():
    return _reader(16, 120)


def test():
    return _reader(17, 40)


def val():
    return _reader(18, 40)
