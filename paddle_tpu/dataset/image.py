"""ref: python/paddle/dataset/image.py — numpy image transforms used by
the 1.x readers (no cv2 dependency here; pure-numpy equivalents)."""
from __future__ import annotations

import numpy as np

__all__ = [
    "load_image", "resize_short", "to_chw", "center_crop", "random_crop",
    "left_right_flip", "simple_transform", "load_and_transform",
]


def load_image(file_path, is_color=True):
    from ..vision.datasets import _load_image
    img = _load_image(file_path)
    if not is_color and img.ndim == 3:
        img = img.mean(axis=-1, keepdims=True)
    return img


def _resize(img, h, w):
    """Nearest-neighbor resize (HWC uint8/float)."""
    ih, iw = img.shape[:2]
    ys = (np.arange(h) * ih / h).astype(np.int32)
    xs = (np.arange(w) * iw / w).astype(np.int32)
    return img[ys][:, xs]


def resize_short(im, size):
    h, w = im.shape[:2]
    if h < w:
        return _resize(im, size, int(w * size / h))
    return _resize(im, int(h * size / w), size)


def to_chw(im, order=(2, 0, 1)):
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    y0 = max(0, (h - size) // 2)
    x0 = max(0, (w - size) // 2)
    return im[y0:y0 + size, x0:x0 + size]


def random_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    y0 = np.random.randint(0, max(1, h - size + 1))
    x0 = np.random.randint(0, max(1, w - size + 1))
    return im[y0:y0 + size, x0:x0 + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color)
    if im.ndim == 2:
        im = im[:, :, None]
    im = to_chw(im).astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        im -= mean if mean.ndim >= 2 else mean[:, None, None]
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)
