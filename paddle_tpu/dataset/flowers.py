"""ref: python/paddle/dataset/flowers.py — 102-category flowers.
train()/test()/valid() yield (3*32*32 float image in [0,1], int label)."""
from __future__ import annotations

import numpy as np

_N_CLASSES = 102


def _reader(seed, n):
    def reader():
        rng = np.random.RandomState(seed)
        labels = rng.randint(0, _N_CLASSES, n)
        base = rng.rand(_N_CLASSES, 3, 32, 32).astype(np.float32)
        for i in range(n):
            img = np.clip(base[labels[i]] * 0.75
                          + rng.rand(3, 32, 32) * 0.25, 0, 1)
            yield img.reshape(-1).astype(np.float32), int(labels[i])

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _reader(13, 400)


def test(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _reader(14, 100)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader(15, 100)
