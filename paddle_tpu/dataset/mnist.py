"""ref: python/paddle/dataset/mnist.py — train()/test() yield
(784-float image scaled to [-1, 1], int label). Backed by
vision.datasets.MNIST (real IDX files when given, synthetic otherwise)."""
from __future__ import annotations

import numpy as np


def _reader(mode):
    from ..vision.datasets import MNIST
    ds = MNIST(mode=mode)

    def reader():
        for i in range(len(ds)):
            img = ds.images[i].astype(np.float32).reshape(-1)
            img = img / 127.5 - 1.0
            yield img, int(ds.labels[i])

    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")
