"""ref: python/paddle/dataset/uci_housing.py — 13-feature Boston housing
regression. train()/test() yield (features[13] float32, [price])."""
from __future__ import annotations

import numpy as np

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS", "RAD", "TAX",
    "PTRATIO", "B", "LSTAT",
]

_N_TRAIN, _N_TEST = 404, 102


def _make(n, seed):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 13).astype(np.float32)
    w = np.linspace(-2.0, 2.0, 13).astype(np.float32)
    y = (x @ w + 3.0 + rng.randn(n).astype(np.float32) * 0.1)
    return x, y[:, None]


def feature_range(maximums, minimums):
    pass  # plotting helper in the reference; intentionally a no-op


def train():
    x, y = _make(_N_TRAIN, 0)

    def reader():
        for i in range(len(x)):
            yield x[i], y[i]

    return reader


def test():
    x, y = _make(_N_TEST, 1)

    def reader():
        for i in range(len(x)):
            yield x[i], y[i]

    return reader
