"""ref: python/paddle/dataset/wmt16.py — BPE-ish translation loaders with
selectable src/trg language. train/test/validation yield
(src_ids, trg_ids, trg_next_ids); get_dict(lang, dict_size)."""
from __future__ import annotations

from . import _text_synth
from .wmt14 import END, START, UNK, UNK_IDX, _dicts


def get_dict(lang, dict_size, reverse=False):
    d, _ = _dicts(dict_size)
    if reverse:
        return {v: k for k, v in d.items()}
    return d


def fetch():
    pass  # download hook in the reference; data here is synthetic


def _reader(src_dict_size, trg_dict_size, seed, n):
    src_d, _ = _dicts(src_dict_size)
    trg_d, _ = _dicts(trg_dict_size)

    def reader():
        for ws in _text_synth.sentences(n, seed=seed):
            src = [src_d.get(w, UNK_IDX) for w in ws]
            trg = [trg_d.get(w, UNK_IDX) for w in reversed(ws)]
            yield (src, [trg_d[START]] + trg, trg + [trg_d[END]])

    return reader


def train(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader(src_dict_size, trg_dict_size, seed=52, n=300)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader(src_dict_size, trg_dict_size, seed=53, n=60)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader(src_dict_size, trg_dict_size, seed=54, n=60)
