"""Shared deterministic synthetic text corpus for the NLP dataset loaders
(imdb/imikolov/wmt). A fixed vocabulary + a seeded Zipf-ish sampler gives
stable dictionaries and sentences across processes."""
from __future__ import annotations

import numpy as np

_WORDS = [
    "the", "a", "of", "to", "and", "in", "it", "is", "this", "that",
    "movie", "film", "story", "plot", "actor", "scene", "great", "bad",
    "good", "terrible", "wonderful", "boring", "love", "hate", "time",
    "character", "music", "ending", "script", "director", "watch", "see",
    "one", "two", "best", "worst", "funny", "sad", "long", "short",
]


def vocab():
    return list(_WORDS)


def sentences(n, seed, min_len=4, max_len=12, sentiment=None):
    """n synthetic sentences; sentiment=0/1 biases negative/positive words
    so classifiers can actually learn."""
    rng = np.random.RandomState(seed)
    pos = ["great", "good", "wonderful", "love", "best", "funny"]
    neg = ["bad", "terrible", "boring", "hate", "worst", "sad"]
    out = []
    for _ in range(n):
        ln = rng.randint(min_len, max_len + 1)
        ws = [_WORDS[min(int(rng.zipf(1.5)) - 1, len(_WORDS) - 1)]
              for _ in range(ln)]
        if sentiment is not None:
            bank = pos if sentiment == 1 else neg
            for _ in range(max(1, ln // 3)):
                ws[rng.randint(ln)] = bank[rng.randint(len(bank))]
        out.append(ws)
    return out
