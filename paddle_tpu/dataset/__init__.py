"""paddle.dataset — the 1.x reader-style dataset loaders.

Reference: python/paddle/dataset/ (uci_housing, mnist, cifar, imdb,
imikolov, movielens, conll05, flowers, voc2012, wmt14, wmt16, image,
common). Each module exposes `train()`/`test()` factories returning
zero-arg reader callables (the contract paddle.reader decorators expect).

Zero-egress environment: the reference downloads from public mirrors; here
each loader first looks for a caller-provided local file (same parsing as
paddle_tpu.vision.datasets where formats overlap) and otherwise generates
deterministic class-conditional synthetic data with the right shapes and
vocabularies, so reader pipelines and models are fully exercisable.
"""
from . import common  # noqa: F401
from . import uci_housing  # noqa: F401
from . import mnist  # noqa: F401
from . import cifar  # noqa: F401
from . import imdb  # noqa: F401
from . import imikolov  # noqa: F401
from . import movielens  # noqa: F401
from . import conll05  # noqa: F401
from . import flowers  # noqa: F401
from . import voc2012  # noqa: F401
from . import wmt14  # noqa: F401
from . import wmt16  # noqa: F401
from . import image  # noqa: F401

__all__ = []  # matches the reference: no APIs shown under paddle.dataset
