"""ref: python/paddle/dataset/movielens.py — MovieLens-1M recsys loaders.
train()/test() yield [user_id, gender, age, job, movie_id, categories,
title, rating]; plus the id-space helpers models size embeddings with."""
from __future__ import annotations

import numpy as np

age_table = [1, 18, 25, 35, 45, 50, 56]

_N_MOVIES = 200
_N_USERS = 120
_N_JOBS = 21
_CATEGORIES = [
    "Action", "Adventure", "Animation", "Children's", "Comedy", "Crime",
    "Documentary", "Drama", "Fantasy", "Film-Noir", "Horror", "Musical",
    "Mystery", "Romance", "Sci-Fi", "Thriller", "War", "Western",
]


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        return [self.index, [_CATEGORIES.index(c) for c in self.categories],
                [ord(ch) % 256 for ch in self.title]]

    def __repr__(self):
        return f"<MovieInfo id({self.index}), title({self.title})>"


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age, self.job_id]

    def __repr__(self):
        return (f"<UserInfo id({self.index}), gender("
                f"{'M' if self.is_male else 'F'}), age({age_table[self.age]}"
                f"), job({self.job_id})>")


def _movies():
    rng = np.random.RandomState(5)
    out = {}
    for i in range(1, _N_MOVIES + 1):
        cats = [_CATEGORIES[j] for j in
                rng.choice(len(_CATEGORIES), rng.randint(1, 4),
                           replace=False)]
        out[i] = MovieInfo(i, cats, f"Movie {i}")
    return out


def _users():
    rng = np.random.RandomState(6)
    out = {}
    for i in range(1, _N_USERS + 1):
        out[i] = UserInfo(i, "M" if rng.rand() < 0.5 else "F",
                          age_table[rng.randint(len(age_table))],
                          rng.randint(_N_JOBS))
    return out


_MOVIE_INFO = None
_USER_INFO = None


def movie_info():
    global _MOVIE_INFO
    if _MOVIE_INFO is None:
        _MOVIE_INFO = _movies()
    return _MOVIE_INFO


def user_info():
    global _USER_INFO
    if _USER_INFO is None:
        _USER_INFO = _users()
    return _USER_INFO


def get_movie_title_dict():
    words = sorted({w for m in movie_info().values()
                    for w in m.title.split()})
    return {w: i for i, w in enumerate(words)}


def max_movie_id():
    return max(movie_info())


def max_user_id():
    return max(user_info())


def max_job_id():
    return _N_JOBS - 1


def movie_categories():
    return {c: i for i, c in enumerate(_CATEGORIES)}


def _ratings(seed, n):
    rng = np.random.RandomState(seed)
    movies, users = movie_info(), user_info()
    for _ in range(n):
        u = users[rng.randint(1, _N_USERS + 1)]
        m = movies[rng.randint(1, _N_MOVIES + 1)]
        # preference structure: users like movies whose id parity matches
        base = 4.0 if (u.index + m.index) % 2 == 0 else 2.0
        rating = float(np.clip(base + rng.randn() * 0.7, 1, 5))
        yield u.value() + m.value() + [[rating]]


def train():
    def reader():
        yield from _ratings(7, 800)
    return reader


def test():
    def reader():
        yield from _ratings(8, 200)
    return reader
