"""ref: python/paddle/dataset/wmt14.py — FR->EN translation pairs.
train(dict_size)/test(dict_size) yield (src_ids, trg_ids, trg_next_ids).
The <s>/<e>/<unk> convention matches the reference."""
from __future__ import annotations

import numpy as np

from . import _text_synth

START = "<s>"
END = "<e>"
UNK = "<unk>"
UNK_IDX = 2


def _dicts(dict_size):
    words = _text_synth.vocab()[: max(0, dict_size - 3)]
    vocab = [START, END, UNK] + words
    d = {w: i for i, w in enumerate(vocab)}
    return d, d  # synthetic corpus shares src/trg vocab


def get_dict(dict_size, reverse=False):
    src, trg = _dicts(dict_size)
    if reverse:
        return ({v: k for k, v in src.items()},
                {v: k for k, v in trg.items()})
    return src, trg


def _reader(dict_size, seed, n):
    src_d, trg_d = _dicts(dict_size)

    def ids(ws, d):
        return [d.get(w, UNK_IDX) for w in ws]

    def reader():
        for ws in _text_synth.sentences(n, seed=seed):
            src = ids(ws, src_d)
            trg = ids(list(reversed(ws)), trg_d)  # synthetic "translation"
            yield (src, [src_d[START]] + trg, trg + [src_d[END]])

    return reader


def train(dict_size):
    return _reader(dict_size, seed=50, n=300)


def test(dict_size):
    return _reader(dict_size, seed=51, n=60)
