"""ref: python/paddle/dataset/conll05.py — semantic role labeling.
get_dict() -> (word_dict, verb_dict, label_dict); test() yields the 9-slot
SRL sample (word, ctx_n2..ctx_p2, verb, mark, labels)."""
from __future__ import annotations

import numpy as np

from . import _text_synth

_LABELS = ["B-A0", "I-A0", "B-A1", "I-A1", "B-V", "O"]
_VERBS = ["watch", "love", "hate", "see"]

UNK_IDX = 0


def get_dict():
    words = ["<unk>"] + _text_synth.vocab()
    word_dict = {w: i for i, w in enumerate(words)}
    verb_dict = {v: i for i, v in enumerate(_VERBS)}
    label_dict = {l: i for i, l in enumerate(_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    """ref: conll05.py get_embedding — pretrained word vectors; here a
    seeded matrix shaped [len(word_dict), 32]."""
    word_dict, _, _ = get_dict()
    rng = np.random.RandomState(9)
    return rng.randn(len(word_dict), 32).astype(np.float32)


def test():
    word_dict, verb_dict, label_dict = get_dict()

    def reader():
        rng = np.random.RandomState(11)
        for ws in _text_synth.sentences(60, seed=12, min_len=5):
            n = len(ws)
            widx = [word_dict.get(w, UNK_IDX) for w in ws]
            vpos = int(rng.randint(n))
            verb = _VERBS[rng.randint(len(_VERBS))]
            mark = [1 if i == vpos else 0 for i in range(n)]
            labels = [label_dict["B-V"] if i == vpos else label_dict["O"]
                      for i in range(n)]
            ctx = {}
            for off, name in ((-2, "n2"), (-1, "n1"), (0, "0"),
                              (1, "p1"), (2, "p2")):
                p = min(max(vpos + off, 0), n - 1)
                ctx[name] = [widx[p]] * n
            yield (widx, ctx["n2"], ctx["n1"], ctx["0"], ctx["p1"],
                   ctx["p2"], [verb_dict[verb]] * n, mark, labels)

    return reader
