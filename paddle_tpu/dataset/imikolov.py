"""ref: python/paddle/dataset/imikolov.py — PTB-style language modeling.
build_dict() -> word dict with <s>/<e>/<unk>; train/test yield n-grams
(DataType.NGRAM) or (src, trg) sequences (DataType.SEQ)."""
from __future__ import annotations

from . import _text_synth


class DataType:
    NGRAM = 1
    SEQ = 2


def build_dict(min_word_freq=0):
    freq = {}
    for ws in _text_synth.sentences(300, seed=30):
        for w in ws:
            freq[w] = freq.get(w, 0) + 1
    freq = {w: c for w, c in freq.items() if c >= min_word_freq}
    ordered = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
    word_idx = {w: i for i, (w, _) in enumerate(ordered)}
    word_idx["<unk>"] = len(word_idx)
    word_idx.setdefault("<s>", len(word_idx))
    word_idx.setdefault("<e>", len(word_idx))
    return word_idx


def _reader(word_idx, n, data_type, seed):
    s_id = word_idx["<s>"]
    e_id = word_idx["<e>"]
    unk = word_idx["<unk>"]

    def reader():
        for ws in _text_synth.sentences(150, seed=seed):
            ids = [s_id] + [word_idx.get(w, unk) for w in ws] + [e_id]
            if data_type == DataType.NGRAM:
                if len(ids) >= n:
                    for i in range(n, len(ids) + 1):
                        yield tuple(ids[i - n:i])
            else:
                yield ids[:-1], ids[1:]

    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    return _reader(word_idx, n, data_type, seed=31)


def test(word_idx, n, data_type=DataType.NGRAM):
    return _reader(word_idx, n, data_type, seed=32)
