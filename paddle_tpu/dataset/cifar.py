"""ref: python/paddle/dataset/cifar.py — train10/test10/train100/test100
yield (3072-float image scaled to [0,1], int label). Backed by
vision.datasets.Cifar10/100 (tar.gz archives when given, synthetic
otherwise)."""
from __future__ import annotations

import numpy as np


def _reader(cls, mode):
    ds = cls(mode=mode)  # parse the archive once, not per epoch

    def reader():
        for i in range(len(ds)):
            img = ds.images[i].astype(np.float32).reshape(-1) / 255.0
            yield img, int(ds.labels[i])

    return reader


def train10():
    from ..vision.datasets import Cifar10
    return _reader(Cifar10, "train")


def test10():
    from ..vision.datasets import Cifar10
    return _reader(Cifar10, "test")


def train100():
    from ..vision.datasets import Cifar100
    return _reader(Cifar100, "train")


def test100():
    from ..vision.datasets import Cifar100
    return _reader(Cifar100, "test")
