"""User-defined data generators emitting the MultiSlot text format.

Reference: python/paddle/fluid/incubate/data_generator/__init__.py. A
generator's `generate_sample(line)` yields `[(slot_name, [values]), ...]`;
`_gen_str` serializes each sample as `<n> <v1> ... <vn>` per slot — the
exact bytes fluid.dataset_feed's datasets (and the reference's C++
MultiSlotDataFeed) parse. run_from_stdin/run_from_memory drive it as the
`pipe_command` of a Dataset.

Sibling API: distributed.fleet.data_generator carries the 2.x fleet
variant of the same user contract — in-process `run_from_memory(lines)`
returning parsed samples for `Dataset.set_data_generator` (no text round
trip) and a counted protocol line. This module is the 1.x stdout-pipe
protocol, byte-compatible with the reference's feed.
"""
from __future__ import annotations

import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    def __init__(self):
        self.batch_size_ = 32
        self._proto_info = None
        self._line_limit = None

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    def generate_sample(self, line):
        """Override: return a generator over samples, each
        [(slot_name, [values]), ...]."""
        raise NotImplementedError(
            "generate_sample() must be implemented by the subclass")

    def generate_batch(self, samples):
        """Override for batch-level processing; default passes samples
        through one by one."""
        def local_iter():
            for sample in samples:
                yield sample
        return local_iter

    def _gen_str(self, line):
        raise NotImplementedError(
            "use MultiSlotDataGenerator or MultiSlotStringDataGenerator")

    def run_from_stdin(self):
        """Act as a dataset pipe_command: raw lines in, MultiSlot out."""
        batch_samples = []
        for line in sys.stdin:
            sample_gen = self.generate_sample(line)
            if sample_gen is None:
                continue
            for sample in sample_gen():
                if sample is None:
                    continue
                batch_samples.append(sample)
                if len(batch_samples) == self.batch_size_:
                    for s in self.generate_batch(batch_samples)():
                        sys.stdout.write(self._gen_str(s))
                    batch_samples = []
        if batch_samples:
            for s in self.generate_batch(batch_samples)():
                sys.stdout.write(self._gen_str(s))

    def run_from_memory(self):
        """Generate without an input file (generate_sample(None))."""
        batch_samples = []
        sample_gen = self.generate_sample(None)
        for sample in sample_gen():
            if sample is None:
                continue
            batch_samples.append(sample)
            if len(batch_samples) == self.batch_size_:
                for s in self.generate_batch(batch_samples)():
                    sys.stdout.write(self._gen_str(s))
                batch_samples = []
        if batch_samples:
            for s in self.generate_batch(batch_samples)():
                sys.stdout.write(self._gen_str(s))


def _check_sample(line):
    if not isinstance(line, (list, tuple)):
        raise ValueError(
            "the output of generate_sample() must be a list or tuple, "
            "e.g. [('words', [1926, 8, 17]), ('label', [1])]")


class MultiSlotDataGenerator(DataGenerator):
    def _gen_str(self, line):
        _check_sample(line)
        if self._proto_info is None:
            self._proto_info = [
                (name, "float" if any(isinstance(e, float) for e in elems)
                 else "uint64") for name, elems in line]
        parts = []
        for name, elements in line:
            parts.append(str(len(elements)))
            parts.extend(str(e) for e in elements)
        return " ".join(parts) + "\n"


class MultiSlotStringDataGenerator(DataGenerator):
    def _gen_str(self, line):
        _check_sample(line)
        parts = []
        for _name, elements in line:
            parts.append(str(len(elements)))
            parts.extend(elements)
        return " ".join(parts) + "\n"
