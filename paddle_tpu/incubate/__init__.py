"""paddle.incubate — experimental APIs (ref: python/paddle/incubate/)."""
from __future__ import annotations


def softmax_mask_fuse_upper_triangle(x):
    """Fused causal-masked softmax (XLA fuses this chain into one kernel)."""
    import jax.numpy as jnp

    from ..ops._registry import defop

    @defop(name="softmax_mask_fuse_upper_triangle")
    def _impl(x):
        import jax
        s = x.shape[-1]
        mask = jnp.tril(jnp.ones((s, s), bool))
        return jax.nn.softmax(jnp.where(mask, x, -1e30), axis=-1)
    return _impl(x)


class LookAhead:
    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        self.inner = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._count = 0
        self._slow = None

    def step(self):
        self.inner.step()
        self._count += 1
        if self._count % self.k == 0:
            params = self.inner._parameter_list or []
            if self._slow is None:
                self._slow = [p._value for p in params]
            else:
                for i, p in enumerate(params):
                    self._slow[i] = self._slow[i] + self.alpha * (
                        p._value - self._slow[i])
                    p._value = self._slow[i]

    def clear_grad(self):
        self.inner.clear_grad()

    def minimize(self, loss):
        loss.backward()
        self.step()

from .. import reader  # noqa: E402,F401  (the real decorator module)
from . import complex  # noqa: E402,F401,A004  (complex tensor ops)
from . import data_generator  # noqa: E402,F401  (MultiSlot generators)
from ..distributed import fleet  # noqa: E402,F401  (ref: fluid.incubate.fleet)
