"""paddle.incubate.complex — complex-number tensor ops.

Reference: python/paddle/incubate/complex/tensor/{math,linalg,
manipulation}.py. There a ComplexVariable carries a (real, imag) pair of
Variables because the fluid core has no complex dtype; here jax.numpy has
first-class complex64/128, so a ComplexVariable is simply a complex-dtype
Tensor (compat.py) and every op is the jnp op — XLA lowers complex
arithmetic to fused real/imag pairs on TPU.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops._registry import apply_op

__all__ = [
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "kron", "trace", "sum", "matmul", "reshape",
    "transpose",
]


def _c(x):
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    if not jnp.issubdtype(v.dtype, jnp.complexfloating):
        v = v.astype(jnp.complex64)
    return v


def _binop(fn, name, x, y):
    return apply_op(lambda a, b: fn(_c(a), _c(b)), name,
                    (x if isinstance(x, Tensor) else Tensor(_c(x)),
                     y if isinstance(y, Tensor) else Tensor(_c(y))), {})


def elementwise_add(x, y, axis=-1, name=None):
    return _binop(jnp.add, "complex_add", x, y)


def elementwise_sub(x, y, axis=-1, name=None):
    return _binop(jnp.subtract, "complex_sub", x, y)


def elementwise_mul(x, y, axis=-1, name=None):
    return _binop(jnp.multiply, "complex_mul", x, y)


def elementwise_div(x, y, axis=-1, name=None):
    return _binop(jnp.divide, "complex_div", x, y)


def kron(x, y, name=None):
    return _binop(jnp.kron, "complex_kron", x, y)


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    def core(a, b):
        a, b = _c(a), _c(b)
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        return alpha * (a @ b)
    return apply_op(core, "complex_matmul",
                    (x if isinstance(x, Tensor) else Tensor(_c(x)),
                     y if isinstance(y, Tensor) else Tensor(_c(y))), {})


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(
        lambda a: jnp.trace(_c(a), offset=offset, axis1=axis1, axis2=axis2),
        "complex_trace", (x if isinstance(x, Tensor) else Tensor(_c(x)),),
        {})


def sum(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply_op(
        lambda a: jnp.sum(_c(a), axis=axis, keepdims=keepdim),
        "complex_sum", (x if isinstance(x, Tensor) else Tensor(_c(x)),), {})


def reshape(x, shape, inplace=False, name=None):
    return apply_op(lambda a: jnp.reshape(_c(a), shape), "complex_reshape",
                    (x if isinstance(x, Tensor) else Tensor(_c(x)),), {})


def transpose(x, perm, name=None):
    return apply_op(lambda a: jnp.transpose(_c(a), perm),
                    "complex_transpose",
                    (x if isinstance(x, Tensor) else Tensor(_c(x)),), {})
