"""Pretrained-weights loading for the vision zoo.

Reference behavior (vision/models/resnet.py:261-264): `pretrained=True`
downloads from `model_urls` and `set_dict`s. This environment is
zero-egress, so downloading is impossible — `pretrained=True` therefore
loads from a local weights directory, and FAILS LOUDLY when no weights
exist instead of silently returning random initialization (r3 weak #2).
"""
from __future__ import annotations

import os

PRETRAINED_DIR_ENV = "PADDLE_TPU_PRETRAINED_DIR"
_DEFAULT_DIR = os.path.expanduser("~/.cache/paddle_tpu/hub")


def load_pretrained(model, arch):
    """Load `<dir>/<arch>.pdparams` into `model` (dir from
    $PADDLE_TPU_PRETRAINED_DIR, falling back to ~/.cache/paddle_tpu/hub);
    raise with actionable guidance when absent."""
    d = os.environ.get(PRETRAINED_DIR_ENV, _DEFAULT_DIR)
    path = os.path.join(d, f"{arch}.pdparams")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"pretrained=True for '{arch}' but no weights at {path}. "
            "This environment cannot download weights; place a state_dict "
            f"saved with paddle.save at that path (or set "
            f"${PRETRAINED_DIR_ENV} to your weights directory), or pass "
            "pretrained=False for random initialization.")
    from ...framework.io import load
    state = load(path)
    model.set_state_dict(state)
    return model
