"""ResNet family (ref: python/paddle/vision/models/resnet.py)."""
from __future__ import annotations

from ... import nn


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        import functools
        conv = functools.partial(nn.Conv2D, data_format=data_format)
        # data_format is injected only into the DEFAULT norm; a
        # user-supplied factory keeps its own signature (it may not
        # accept the kwarg) and handles layout itself
        if norm_layer is None:
            norm_layer = functools.partial(nn.BatchNorm2D,
                                           data_format=data_format)
        self.conv1 = conv(inplanes, planes, 3, stride=stride, padding=1,
                          bias_attr=False)
        self.bn1 = norm_layer(planes)
        self.relu = nn.ReLU()
        self.conv2 = conv(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = norm_layer(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        import functools
        conv = functools.partial(nn.Conv2D, data_format=data_format)
        # data_format is injected only into the DEFAULT norm; a
        # user-supplied factory keeps its own signature (it may not
        # accept the kwarg) and handles layout itself
        if norm_layer is None:
            norm_layer = functools.partial(nn.BatchNorm2D,
                                           data_format=data_format)
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = conv(inplanes, width, 1, bias_attr=False)
        self.bn1 = norm_layer(width)
        self.conv2 = conv(width, width, 3, padding=dilation,
                          stride=stride, groups=groups,
                          dilation=dilation, bias_attr=False)
        self.bn2 = norm_layer(width)
        self.conv3 = conv(width, planes * self.expansion, 1,
                          bias_attr=False)
        self.bn3 = norm_layer(planes * self.expansion)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    def __init__(self, block, depth=50, width=64, num_classes=1000,
                 with_pool=True, groups=1, data_format="NCHW"):
        super().__init__()
        import functools
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        # NHWC puts channels on the TPU lane dim: BN stat reduces become
        # lane-preserving and the layout matches XLA's internal conv
        # preference (r5 ResNet lever; weights stay OIHW either way)
        self.data_format = data_format
        self._norm_layer = functools.partial(nn.BatchNorm2D,
                                             data_format=data_format)
        self.inplanes = 64
        self.dilation = 1
        self.conv1 = nn.Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                               bias_attr=False, data_format=data_format)
        self.bn1 = self._norm_layer(self.inplanes)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1,
                                    data_format=data_format)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1),
                                                data_format=data_format)
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1, dilate=False):
        norm_layer = self._norm_layer
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False,
                          data_format=self.data_format),
                norm_layer(planes * block.expansion))
        layers = [block(self.inplanes, planes, stride, downsample,
                        self.groups, self.base_width, self.dilation,
                        norm_layer, data_format=self.data_format)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width,
                                norm_layer=norm_layer,
                                data_format=self.data_format))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def _resnet(block, depth, pretrained=False, **kwargs):
    model = ResNet(block, depth, **kwargs)
    if pretrained:
        from ._weights import load_pretrained
        load_pretrained(model, f"resnet{depth}")
    return model


def resnet18(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 18, pretrained, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 34, pretrained, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 50, pretrained, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 101, pretrained, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 152, pretrained, **kwargs)
