"""Vision model zoo (ref: python/paddle/vision/models/)."""
from __future__ import annotations

from .lenet import LeNet  # noqa: F401
from .mobilenet import MobileNetV1, MobileNetV2, mobilenet_v1, mobilenet_v2  # noqa: F401
from .resnet import (  # noqa: F401
    ResNet, resnet18, resnet34, resnet50, resnet101, resnet152,
)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401

from . import mobilenet as mobilenetv1  # noqa: E402,F401
from . import mobilenet as mobilenetv2  # noqa: E402,F401
