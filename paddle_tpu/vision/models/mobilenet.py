"""MobileNet V1/V2 (ref: python/paddle/vision/models/mobilenetv1.py, v2.py)."""
from __future__ import annotations

from ... import nn


class ConvBNLayer(nn.Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, groups=1, act="relu"):
        super().__init__()
        self.conv = nn.Conv2D(in_channels, out_channels, kernel_size,
                              stride=stride, padding=padding, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_channels)
        self.act = nn.ReLU6() if act == "relu6" else (
            nn.ReLU() if act == "relu" else None)

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act else x


class DepthwiseSeparable(nn.Layer):
    def __init__(self, in_channels, out_channels1, out_channels2, num_groups,
                 stride, scale):
        super().__init__()
        self.dw = ConvBNLayer(in_channels, int(out_channels1 * scale), 3,
                              stride=stride, padding=1,
                              groups=int(num_groups * scale))
        self.pw = ConvBNLayer(int(out_channels1 * scale),
                              int(out_channels2 * scale), 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = ConvBNLayer(3, int(32 * scale), 3, stride=2, padding=1)
        cfg = [(32, 32, 64, 32, 1), (64, 64, 128, 64, 2),
               (128, 128, 128, 128, 1), (128, 128, 256, 128, 2),
               (256, 256, 256, 256, 1), (256, 256, 512, 256, 2),
               (512, 512, 512, 512, 1), (512, 512, 512, 512, 1),
               (512, 512, 512, 512, 1), (512, 512, 512, 512, 1),
               (512, 512, 512, 512, 1), (512, 512, 1024, 512, 2),
               (1024, 1024, 1024, 1024, 1)]
        blocks = []
        for in_c, c1, c2, g, s in cfg:
            blocks.append(DepthwiseSeparable(int(in_c * scale), c1, c2, g, s,
                                             scale))
        self.blocks = nn.Sequential(*blocks)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.conv1(x)
        x = self.blocks(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden_dim = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNLayer(inp, hidden_dim, 1, act="relu6"))
        layers += [
            ConvBNLayer(hidden_dim, hidden_dim, 3, stride=stride, padding=1,
                        groups=hidden_dim, act="relu6"),
            ConvBNLayer(hidden_dim, oup, 1, act=None),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        input_channel = int(32 * scale)
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        features = [ConvBNLayer(3, input_channel, 3, stride=2, padding=1,
                                act="relu6")]
        for t, c, n, s in cfg:
            out_c = int(c * scale)
            for i in range(n):
                features.append(InvertedResidual(
                    input_channel, out_c, s if i == 0 else 1, t))
                input_channel = out_c
        self.last_channel = int(1280 * max(1.0, scale))
        features.append(ConvBNLayer(input_channel, self.last_channel, 1,
                                    act="relu6"))
        self.features = nn.Sequential(*features)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(self.last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    model = MobileNetV1(scale=scale, **kwargs)
    if pretrained:
        from ._weights import load_pretrained
        load_pretrained(model, f"mobilenetv1_{scale}")
    return model


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    model = MobileNetV2(scale=scale, **kwargs)
    if pretrained:
        from ._weights import load_pretrained
        load_pretrained(model, f"mobilenetv2_{scale}")
    return model
