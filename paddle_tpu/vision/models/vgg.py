"""VGG family (ref: python/paddle/vision/models/vgg.py)."""
from __future__ import annotations

from ... import nn

cfgs = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
          512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
          512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


def make_layers(cfg, batch_norm=False):
    layers = []
    in_channels = 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2D(kernel_size=2, stride=2))
        else:
            conv2d = nn.Conv2D(in_channels, v, kernel_size=3, padding=1)
            if batch_norm:
                layers += [conv2d, nn.BatchNorm2D(v), nn.ReLU()]
            else:
                layers += [conv2d, nn.ReLU()]
            in_channels = v
    return nn.Sequential(*layers)


class VGG(nn.Layer):
    def __init__(self, features, num_classes=1000, with_pool=True):
        super().__init__()
        self.features = features
        self.num_classes = num_classes
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((7, 7))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def _vgg(arch, cfg, batch_norm, pretrained=False, **kwargs):
    model = VGG(make_layers(cfgs[cfg], batch_norm=batch_norm), **kwargs)
    if pretrained:
        from ._weights import load_pretrained
        load_pretrained(model, arch + ("_bn" if batch_norm else ""))
    return model


def vgg11(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("vgg11", "A", batch_norm, pretrained, **kwargs)


def vgg13(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("vgg13", "B", batch_norm, pretrained, **kwargs)


def vgg16(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("vgg16", "D", batch_norm, pretrained, **kwargs)


def vgg19(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("vgg19", "E", batch_norm, pretrained, **kwargs)
