"""Vision datasets (ref: python/paddle/vision/datasets/).

Zero-egress environment: MNIST/Cifar generate deterministic synthetic data
unless a local file path is provided (`image_path`/`data_file`). The API
(mode, transform, __getitem__ semantics) matches the reference.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as np

from ...io import Dataset


class MNIST(Dataset):
    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        if image_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                self.images = np.frombuffer(f.read(), np.uint8).reshape(
                    n, rows, cols)
            with gzip.open(label_path, "rb") as f:
                f.read(8)
                self.labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
        else:
            # synthetic fallback: class-conditional patterns so models can
            # actually fit (loss decreases) in tests/benchmarks
            n = 6000 if mode == "train" else 1000
            rng = np.random.RandomState(0 if mode == "train" else 1)
            self.labels = rng.randint(0, 10, n).astype(np.int64)
            base = rng.rand(10, 28, 28) * 255
            noise = rng.rand(n, 28, 28) * 64
            self.images = np.clip(base[self.labels] * 0.75 + noise, 0,
                                  255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = np.array([self.labels[idx]], np.int64)
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img[None].astype(np.float32) / 255.0
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode
        self.transform = transform
        self.num_classes = 10
        if data_file and os.path.exists(data_file):
            with open(data_file, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            self.images = d[b"data"].reshape(-1, 3, 32, 32)
            self.labels = np.asarray(d[b"labels"], np.int64)
        else:
            n = 5000 if mode == "train" else 1000
            rng = np.random.RandomState(2 if mode == "train" else 3)
            self.labels = rng.randint(0, self.num_classes, n).astype(np.int64)
            base = rng.rand(self.num_classes, 3, 32, 32) * 255
            noise = rng.rand(n, 3, 32, 32) * 64
            self.images = np.clip(base[self.labels] * 0.75 + noise, 0,
                                  255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = np.array([self.labels[idx]], np.int64)
        if self.transform is not None:
            img = self.transform(np.transpose(img, (1, 2, 0)))
        else:
            img = img.astype(np.float32) / 255.0
        return img, label

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.num_classes = 100


class ImageFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        extensions = extensions or (".png", ".jpg", ".jpeg", ".bmp", ".npy")
        self.samples = []
        for dirpath, _, files in os.walk(root):
            for fn in sorted(files):
                if fn.lower().endswith(tuple(extensions)):
                    self.samples.append(os.path.join(dirpath, fn))

    def __getitem__(self, idx):
        path = self.samples[idx]
        if path.endswith(".npy"):
            img = np.load(path)
        else:
            img = _load_image(path)
        if self.transform is not None:
            img = self.transform(img)
        return (img,)

    def __len__(self):
        return len(self.samples)


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        extensions = extensions or (".png", ".jpg", ".jpeg", ".bmp", ".npy")
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fn in sorted(os.listdir(cdir)):
                if fn.lower().endswith(tuple(extensions)):
                    self.samples.append((os.path.join(cdir, fn),
                                         self.class_to_idx[c]))

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = np.load(path) if path.endswith(".npy") else _load_image(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.samples)


def _load_image(path):
    try:
        from PIL import Image
        return np.asarray(Image.open(path).convert("RGB"))
    except ImportError:
        raise RuntimeError(f"cannot decode {path}: PIL unavailable; "
                           "use .npy files")


class Flowers(Dataset):
    """Synthetic stand-in matching the reference Flowers dataset API."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        n = 600 if mode == "train" else 100
        rng = np.random.RandomState(4)
        self.labels = rng.randint(0, 102, n).astype(np.int64)
        self.images = (rng.rand(n, 3, 64, 64) * 255).astype(np.uint8)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(np.transpose(img, (1, 2, 0)))
        else:
            img = img.astype(np.float32) / 255.0
        return img, np.array([self.labels[idx]], np.int64)

    def __len__(self):
        return len(self.images)


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation (ref: python/paddle/vision/datasets/
    voc2012.py); synthetic image/mask pairs in the zero-egress environment."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 backend=None):
        rng = np.random.RandomState(21)
        n = 200 if mode == "train" else 40
        self.images = rng.randint(0, 256, (n, 3, 32, 32)).astype(np.uint8)
        self.masks = rng.randint(0, 21, (n, 32, 32)).astype(np.int64)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.masks[idx]

    def __len__(self):
        return len(self.images)


import sys as _sys  # noqa: E402

_self = _sys.modules[__name__]
cifar = _self
flowers = _self
folder = _self
mnist = _self
voc2012 = _self
