"""Vision datasets (ref: python/paddle/vision/datasets/).

Zero-egress environment: MNIST/Cifar generate deterministic synthetic data
unless a local file path is provided (`image_path`/`data_file`). The API
(mode, transform, __getitem__ semantics) matches the reference.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as np

from ...io import Dataset


class MNIST(Dataset):
    """IDX-format reader (ref: python/paddle/vision/datasets/mnist.py —
    the same >IIII magic/count/rows/cols header + raw uint8 parse)."""

    _SYN_SEEDS = (0, 1)  # (train, test) synthetic-fallback seeds

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        if image_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                self.images = np.frombuffer(f.read(), np.uint8).reshape(
                    n, rows, cols)
            with gzip.open(label_path, "rb") as f:
                f.read(8)
                self.labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
        else:
            # synthetic fallback: class-conditional patterns so models can
            # actually fit. The class PROTOTYPES are shared between train
            # and test (only labels/noise differ per mode) — otherwise the
            # test split is a different task and eval accuracy is chance
            n = 6000 if mode == "train" else 1000
            seeds = type(self)._SYN_SEEDS
            # prototypes use their own stream, independent of the
            # per-mode label/noise draws (fixed arithmetic combine: tuple
            # hash() is interpreter-dependent)
            base = np.random.RandomState(
                ((seeds[0] << 16) ^ seeds[1] ^ 0x5EED) % (1 << 31)).rand(
                10, 28, 28) * 255
            rng = np.random.RandomState(
                seeds[0] if mode == "train" else seeds[1])
            self.labels = rng.randint(0, 10, n).astype(np.int64)
            noise = rng.rand(n, 28, 28) * 64
            self.images = np.clip(base[self.labels] * 0.75 + noise, 0,
                                  255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = np.array([self.labels[idx]], np.int64)
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img[None].astype(np.float32) / 255.0
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    """Fashion-MNIST (ref: python/paddle/vision/datasets — same IDX wire
    format as MNIST, different archive contents). Reads real
    train/t10k-images-idx3-ubyte.gz pairs via the shared IDX parser; the
    synthetic fallback draws from its own seeds so MNIST and FashionMNIST
    produce distinct data in tests."""

    _SYN_SEEDS = (40, 41)


class Cifar10(Dataset):
    """CIFAR-10 from the published cifar-10-python.tar.gz layout (ref:
    python/paddle/vision/datasets/cifar.py:140): walk the archive members,
    unpickle every data_batch_* (train) or test_batch (test), and
    concatenate. A bare single-batch pickle file still loads (legacy)."""

    NUM_CLASSES = 10
    _TRAIN_FLAG = "data_batch"
    _TEST_FLAG = "test_batch"
    _LABEL_KEYS = (b"labels", b"fine_labels")
    _SYN_SEEDS = (2, 3)

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode
        self.transform = transform
        self.num_classes = type(self).NUM_CLASSES
        if data_file and os.path.exists(data_file):
            self._load_file(data_file, mode)
        else:
            # shared class prototypes across modes (see MNIST note)
            n = 5000 if mode == "train" else 1000
            seeds = type(self)._SYN_SEEDS
            base = np.random.RandomState(
                ((seeds[0] << 16) ^ seeds[1] ^ 0x5EED) % (1 << 31)).rand(
                self.num_classes, 3, 32, 32) * 255
            rng = np.random.RandomState(
                seeds[0] if mode == "train" else seeds[1])
            self.labels = rng.randint(0, self.num_classes, n).astype(np.int64)
            noise = rng.rand(n, 3, 32, 32) * 64
            self.images = np.clip(base[self.labels] * 0.75 + noise, 0,
                                  255).astype(np.uint8)

    def _pick_labels(self, d):
        for k in type(self)._LABEL_KEYS:
            if k in d:
                return d[k]
        raise KeyError(f"no label key in batch (have {list(d)})")

    def _load_file(self, data_file, mode):
        import tarfile
        flag = type(self)._TRAIN_FLAG if mode == "train" \
            else type(self)._TEST_FLAG
        if tarfile.is_tarfile(data_file):
            imgs, labels = [], []
            with tarfile.open(data_file, mode="r:*") as tf:
                names = sorted(n for n in tf.getnames()
                               if flag in os.path.basename(n))
                if not names:
                    raise ValueError(
                        f"no '{flag}' members in {data_file} for "
                        f"mode={mode!r}")
                for name in names:
                    d = pickle.load(tf.extractfile(name), encoding="bytes")
                    imgs.append(np.asarray(d[b"data"], np.uint8)
                                .reshape(-1, 3, 32, 32))
                    labels.append(np.asarray(self._pick_labels(d), np.int64))
            self.images = np.concatenate(imgs, axis=0)
            self.labels = np.concatenate(labels, axis=0)
        else:  # legacy single-batch pickle
            with open(data_file, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            self.images = np.asarray(d[b"data"], np.uint8) \
                .reshape(-1, 3, 32, 32)
            self.labels = np.asarray(self._pick_labels(d), np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = np.array([self.labels[idx]], np.int64)
        if self.transform is not None:
            img = self.transform(np.transpose(img, (1, 2, 0)))
        else:
            img = img.astype(np.float32) / 255.0
        return img, label

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    """CIFAR-100: cifar-100-python.tar.gz holds single 'train'/'test'
    members with b'fine_labels' (ref: cifar.py CIFAR100 flags)."""

    NUM_CLASSES = 100
    _TRAIN_FLAG = "train"
    _TEST_FLAG = "test"
    _LABEL_KEYS = (b"fine_labels", b"labels")
    _SYN_SEEDS = (4, 5)


class ImageFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        extensions = extensions or (".png", ".jpg", ".jpeg", ".bmp", ".npy")
        self.samples = []
        for dirpath, _, files in os.walk(root):
            for fn in sorted(files):
                if fn.lower().endswith(tuple(extensions)):
                    self.samples.append(os.path.join(dirpath, fn))

    def __getitem__(self, idx):
        path = self.samples[idx]
        if path.endswith(".npy"):
            img = np.load(path)
        else:
            img = _load_image(path)
        if self.transform is not None:
            img = self.transform(img)
        return (img,)

    def __len__(self):
        return len(self.samples)


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        extensions = extensions or (".png", ".jpg", ".jpeg", ".bmp", ".npy")
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fn in sorted(os.listdir(cdir)):
                if fn.lower().endswith(tuple(extensions)):
                    self.samples.append((os.path.join(cdir, fn),
                                         self.class_to_idx[c]))

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = np.load(path) if path.endswith(".npy") else _load_image(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.samples)


def _load_image(path):
    try:
        from PIL import Image
        return np.asarray(Image.open(path).convert("RGB"))
    except ImportError:
        raise RuntimeError(f"cannot decode {path}: PIL unavailable; "
                           "use .npy files")


class Flowers(Dataset):
    """Oxford 102 Flowers in the PUBLISHED layout (ref:
    python/paddle/vision/datasets/flowers.py): 102flowers.tgz holding
    jpg/image_%05d.jpg, imagelabels.mat (1-based class per image) and
    setid.mat (trnid/valid/tstid index splits), parsed with scipy.io +
    PIL; jpgs decode lazily per access like the reference's tarfile walk.
    Synthetic fallback when no files are given (zero-egress)."""

    # the reference DELIBERATELY swaps the archive's split names — tstid is
    # the big (6149-image) set and serves as train
    # (ref: vision/datasets/flowers.py:40 MODE_FLAG_MAP)
    MODE_FLAG = {"train": "tstid", "valid": "valid", "test": "trnid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        self.transform = transform
        self._data_file = None
        self._tar = None
        self._members = None
        if data_file or label_file or setid_file:
            missing = [p for p in (data_file, label_file, setid_file)
                       if not (p and os.path.exists(p))]
            if missing:
                raise ValueError(
                    f"Flowers needs data_file+label_file+setid_file; "
                    f"missing/unreadable: {missing} (omit ALL three for "
                    f"the synthetic fallback)")
            import scipy.io
            labels = scipy.io.loadmat(label_file)["labels"][0]
            setid = scipy.io.loadmat(setid_file)
            self.indexes = np.asarray(
                setid[self.MODE_FLAG[mode]][0], np.int64)
            # labels are 1-based per image id; keep 1-based like the ref
            self.labels = np.asarray(labels, np.int64)
            self._data_file = data_file
            self.images = None
        else:
            n = 600 if mode == "train" else 100
            rng = np.random.RandomState(4)
            self.indexes = np.arange(1, n + 1)
            self.labels = rng.randint(1, 103, n + 1).astype(np.int64)
            self.images = (rng.rand(n, 3, 64, 64) * 255).astype(np.uint8)

    def _ensure_tar(self):
        # opened lazily PER PROCESS: an open TarFile neither pickles (the
        # multiprocess DataLoader ships the dataset to workers) nor should
        # hold an fd for the dataset's whole life
        if self._tar is None:
            import tarfile
            self._tar = tarfile.open(self._data_file, "r:*")
            self._members = {os.path.basename(m.name): m
                             for m in self._tar.getmembers()
                             if m.name.endswith(".jpg")}

    def __getstate__(self):
        d = dict(self.__dict__)
        d["_tar"] = None
        d["_members"] = None
        return d

    def _decode(self, image_id):
        from PIL import Image
        self._ensure_tar()
        f = self._tar.extractfile(self._members["image_%05d.jpg" % image_id])
        img = np.asarray(Image.open(f).convert("RGB"))
        return np.transpose(img, (2, 0, 1))  # CHW like the synthetic path

    def __getitem__(self, idx):
        image_id = int(self.indexes[idx])
        if self._data_file is not None:
            img = self._decode(image_id)
            label = int(self.labels[image_id - 1])  # 1-based image ids
        else:
            img = self.images[idx]
            label = int(self.labels[image_id])
        if self.transform is not None:
            img = self.transform(np.transpose(img, (1, 2, 0)))
        else:
            img = img.astype(np.float32) / 255.0
        return img, np.array([label], np.int64)

    def __len__(self):
        return len(self.indexes)


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation (ref: python/paddle/vision/datasets/
    voc2012.py); synthetic image/mask pairs in the zero-egress environment."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 backend=None):
        rng = np.random.RandomState(21)
        n = 200 if mode == "train" else 40
        self.images = rng.randint(0, 256, (n, 3, 32, 32)).astype(np.uint8)
        self.masks = rng.randint(0, 21, (n, 32, 32)).astype(np.int64)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.masks[idx]

    def __len__(self):
        return len(self.images)


import sys as _sys  # noqa: E402

_self = _sys.modules[__name__]
cifar = _self
flowers = _self
folder = _self
mnist = _self
voc2012 = _self
