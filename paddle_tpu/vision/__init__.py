"""paddle.vision (ref: python/paddle/vision/)."""
from __future__ import annotations

from . import datasets, models, ops, transforms  # noqa: F401
from .models import LeNet, MobileNetV1, MobileNetV2, ResNet, VGG  # noqa: F401
from .models import (  # noqa: F401
    mobilenet_v1, mobilenet_v2, resnet18, resnet34, resnet50, resnet101,
    resnet152, vgg11, vgg13, vgg16, vgg19,
)


def set_image_backend(backend):
    pass


def get_image_backend():
    return "numpy"

import sys as _sys  # noqa: E402

image = _sys.modules[__name__]  # ref: python/paddle/vision/image.py backend shims
