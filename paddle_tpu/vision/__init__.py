"""paddle.vision (ref: python/paddle/vision/)."""
from __future__ import annotations

from . import datasets, models, ops, transforms  # noqa: F401
from .models import LeNet, MobileNetV1, MobileNetV2, ResNet, VGG  # noqa: F401
from .models import (  # noqa: F401
    mobilenet_v1, mobilenet_v2, resnet18, resnet34, resnet50, resnet101,
    resnet152, vgg11, vgg13, vgg16, vgg19,
)
# the reference star-imports datasets + transforms to paddle.vision top
# level (ref: vision/__init__.py `from .datasets import *` etc.)
from .datasets import (  # noqa: F401
    Cifar10, Cifar100, DatasetFolder, FashionMNIST, Flowers, ImageFolder,
    MNIST, VOC2012)
from .transforms import (  # noqa: F401
    BaseTransform, BrightnessTransform, CenterCrop, ColorJitter, Compose,
    ContrastTransform, Grayscale, HueTransform, Normalize, Pad, RandomCrop,
    RandomHorizontalFlip, RandomResizedCrop, RandomRotation,
    RandomVerticalFlip, Resize, SaturationTransform, ToTensor, Transpose,
    adjust_brightness, adjust_contrast, adjust_hue, center_crop, crop,
    hflip, normalize, pad, resize, rotate, to_grayscale, to_tensor, vflip)


def set_image_backend(backend):
    if backend not in ("pil", "cv2", "numpy", "tensor"):
        raise ValueError(f"unsupported image backend {backend!r}")


def get_image_backend():
    return "numpy"


def image_load(path, backend=None):
    """Load an image file to an HWC numpy array (ref: vision/image.py
    image_load; the PIL decode feeds the numpy transform pipeline)."""
    import numpy as np
    if str(path).endswith(".npy"):
        return np.load(path)
    from PIL import Image
    with Image.open(path) as im:
        return np.asarray(im.convert("RGB"))

import sys as _sys  # noqa: E402

image = _sys.modules[__name__]  # ref: python/paddle/vision/image.py backend shims
