"""Vision transforms (ref: python/paddle/vision/transforms/).

Host-side numpy transforms (CHW/HWC aware); heavy lifting happens per-batch on
device. Images are numpy arrays (HWC uint8 or float)."""
from __future__ import annotations

import numbers
import random

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, img):
        return self._apply_image(img)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = np.transpose(arr, (2, 0, 1))
        return arr.astype(np.float32)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1)
            s = self.std.reshape(-1, 1, 1)
        else:
            m = self.mean
            s = self.std
        return (arr - m) / s


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        hwc = arr.ndim == 3 and arr.shape[-1] <= 4
        h, w = (arr.shape[0], arr.shape[1]) if hwc or arr.ndim == 2 \
            else (arr.shape[1], arr.shape[2])
        th, tw = self.size
        ys = (np.arange(th) * h / th).astype(int).clip(0, h - 1)
        xs = (np.arange(tw) * w / tw).astype(int).clip(0, w - 1)
        if arr.ndim == 2:
            return arr[np.ix_(ys, xs)]
        if hwc:
            return arr[np.ix_(ys, xs)]
        return arr[:, ys][:, :, xs]


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[0], arr.shape[1]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding
            pad = ((p, p), (p, p)) + (((0, 0),) if arr.ndim == 3 else ())
            arr = np.pad(arr, pad)
        h, w = arr.shape[0], arr.shape[1]
        th, tw = self.size
        i = random.randint(0, max(h - th, 0))
        j = random.randint(0, max(w - tw, 0))
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[::-1].copy()
        return np.asarray(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return np.transpose(arr, self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        f = 1 + random.uniform(-self.value, self.value)
        return np.clip(np.asarray(img, np.float32) * f, 0,
                       255 if np.asarray(img).dtype == np.uint8 else None)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()


def vflip(img):
    return np.asarray(img)[::-1].copy()
