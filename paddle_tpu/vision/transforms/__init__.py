"""Vision transforms (ref: python/paddle/vision/transforms/).

Host-side numpy transforms (CHW/HWC aware); heavy lifting happens per-batch on
device. Images are numpy arrays (HWC uint8 or float)."""
from __future__ import annotations

import numbers
import random

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, img):
        return self._apply_image(img)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = np.transpose(arr, (2, 0, 1))
        return arr.astype(np.float32)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1)
            s = self.std.reshape(-1, 1, 1)
        else:
            m = self.mean
            s = self.std
        return (arr - m) / s


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        hwc = arr.ndim == 3 and arr.shape[-1] <= 4
        h, w = (arr.shape[0], arr.shape[1]) if hwc or arr.ndim == 2 \
            else (arr.shape[1], arr.shape[2])
        th, tw = self.size
        ys = (np.arange(th) * h / th).astype(int).clip(0, h - 1)
        xs = (np.arange(tw) * w / tw).astype(int).clip(0, w - 1)
        if arr.ndim == 2:
            return arr[np.ix_(ys, xs)]
        if hwc:
            return arr[np.ix_(ys, xs)]
        return arr[:, ys][:, :, xs]


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[0], arr.shape[1]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding
            pad = ((p, p), (p, p)) + (((0, 0),) if arr.ndim == 3 else ())
            arr = np.pad(arr, pad)
        h, w = arr.shape[0], arr.shape[1]
        th, tw = self.size
        i = random.randint(0, max(h - th, 0))
        j = random.randint(0, max(w - tw, 0))
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[::-1].copy()
        return np.asarray(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return np.transpose(arr, self.order)


def _jitter_range(value, name):
    """Reference _check_input semantics (transforms.py:56): a scalar v
    becomes the factor range [max(0, 1-v), 1+v]; a (lo, hi) pair is taken
    verbatim. Factors never go negative."""
    if isinstance(value, (tuple, list)):
        lo, hi = float(value[0]), float(value[1])
        if lo > hi or lo < 0:
            raise ValueError(f"{name} range {value!r} must satisfy "
                             "0 <= lo <= hi")
        return lo, hi
    if value < 0:
        raise ValueError(f"{name} value should be non-negative")
    return max(0.0, 1.0 - float(value)), 1.0 + float(value)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = _jitter_range(value, "brightness")

    def _apply_image(self, img):
        f = random.uniform(*self.value)
        return np.clip(np.asarray(img, np.float32) * f, 0,
                       255 if np.asarray(img).dtype == np.uint8 else None)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()


def vflip(img):
    return np.asarray(img)[::-1].copy()

import sys as _sys  # noqa: E402

_self = _sys.modules[__name__]
functional = _self
transforms = _self


# functional transforms (ref: python/paddle/vision/transforms/functional.py);
# images are numpy HWC (or CHW for tensors) — no PIL dependency
def _hwc(img):
    import numpy as np
    a = np.asarray(img)
    chw = a.ndim == 3 and a.shape[0] in (1, 3) and a.shape[2] not in (1, 3)
    return (a.transpose(1, 2, 0), True) if chw else (a, False)


def _restore(a, was_chw):
    return a.transpose(2, 0, 1) if was_chw else a


def crop(img, top, left, height, width):
    a, chw = _hwc(img)
    return _restore(a[top:top + height, left:left + width], chw)


def center_crop(img, output_size):
    a, chw = _hwc(img)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    th, tw = output_size
    i = max((a.shape[0] - th) // 2, 0)
    j = max((a.shape[1] - tw) // 2, 0)
    return _restore(a[i:i + th, j:j + tw], chw)


def pad(img, padding, fill=0, padding_mode="constant"):
    import numpy as np
    a, chw = _hwc(img)
    if isinstance(padding, int):
        padding = (padding,) * 4
    if len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    l, t, r, b = padding
    pads = [(t, b), (l, r)] + [(0, 0)] * (a.ndim - 2)
    if padding_mode == "constant":
        out = np.pad(a, pads, constant_values=fill)
    else:
        out = np.pad(a, pads, mode={"edge": "edge", "reflect": "reflect",
                                    "symmetric": "symmetric"}[padding_mode])
    return _restore(out, chw)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    import numpy as np
    a, chw = _hwc(img)
    k = int(round(angle / 90.0)) % 4
    if abs(angle - 90.0 * round(angle / 90.0)) < 1e-6 \
            and (not expand or center is None):
        out = np.rot90(a, k)  # right-angle fast path, no resampling
        # for right angles rot90 IS the expanded canvas; without expand
        # the reference also returns the rotated (possibly transposed)
        # frame only when square — crop/pad back to the input frame
        if not expand and out.shape[:2] != a.shape[:2]:
            h, w = a.shape[:2]
            oh, ow = out.shape[:2]
            canvas = np.full_like(a, fill)
            ct, cl = max((oh - h) // 2, 0), max((ow - w) // 2, 0)
            t, l = max((h - oh) // 2, 0), max((w - ow) // 2, 0)
            ch_, cw_ = min(h, oh), min(w, ow)
            canvas[t:t + ch_, l:l + cw_] = out[ct:ct + ch_, cl:cl + cw_]
            out = canvas
    else:
        # nearest-neighbour rotation about the image center; expand=True
        # grows the canvas to hold the whole rotated image (ref:
        # functional rotate expand semantics)
        h, w = a.shape[:2]
        rad = np.deg2rad(angle)
        if expand:
            oh = int(np.ceil(abs(h * np.cos(rad)) + abs(w * np.sin(rad))))
            ow = int(np.ceil(abs(w * np.cos(rad)) + abs(h * np.sin(rad))))
        else:
            oh, ow = h, w
        cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None \
            else (center[1], center[0])
        ocy, ocx = ((oh - 1) / 2.0, (ow - 1) / 2.0) if expand \
            else (cy, cx)
        ys, xs = np.mgrid[0:oh, 0:ow]
        sy = cy + (ys - ocy) * np.cos(rad) - (xs - ocx) * np.sin(rad)
        sx = cx + (ys - ocy) * np.sin(rad) + (xs - ocx) * np.cos(rad)
        yi = np.clip(np.round(sy).astype(int), 0, h - 1)
        xi = np.clip(np.round(sx).astype(int), 0, w - 1)
        valid = (sy >= -0.5) & (sy < h - 0.5) & (sx >= -0.5) & (sx < w - 0.5)
        out = a[yi, xi]
        out[~valid] = fill
    return _restore(out, chw)


def to_grayscale(img, num_output_channels=1):
    import numpy as np
    a, chw = _hwc(img)
    gray = (0.299 * a[..., 0] + 0.587 * a[..., 1]
            + 0.114 * a[..., 2]).astype(a.dtype)
    out = np.stack([gray] * num_output_channels, axis=-1)
    return _restore(out, chw)


def adjust_brightness(img, brightness_factor):
    import numpy as np
    a, chw = _hwc(img)
    hi = 255 if a.dtype == np.uint8 else 1.0
    return _restore(np.clip(a * brightness_factor, 0, hi).astype(a.dtype),
                    chw)


def adjust_contrast(img, contrast_factor):
    import numpy as np
    a, chw = _hwc(img)
    hi = 255 if a.dtype == np.uint8 else 1.0
    mean = a.mean()
    out = np.clip((a - mean) * contrast_factor + mean, 0, hi).astype(a.dtype)
    return _restore(out, chw)


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5] turns) via RGB<->HSV."""
    import numpy as np
    a, chw = _hwc(img)
    scale = 255.0 if a.dtype == np.uint8 else 1.0
    x = a.astype(np.float32) / scale
    mx, mn = x.max(-1), x.min(-1)
    diff = mx - mn + 1e-12
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    h = np.where(mx == r, (g - b) / diff % 6,
                 np.where(mx == g, (b - r) / diff + 2, (r - g) / diff + 4))
    h = (h / 6.0 + hue_factor) % 1.0
    s = np.where(mx > 0, diff / (mx + 1e-12), 0)
    v = mx
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = i.astype(int) % 6
    rgb = np.select(
        [i[..., None] == k for k in range(6)],
        [np.stack(c, -1) for c in
         [(v, t, p), (q, v, p), (p, v, t), (p, q, v), (t, p, v), (v, p, q)]])
    out = (rgb * scale).astype(a.dtype)
    return _restore(out, chw)


def adjust_saturation(img, saturation_factor):
    """Blend between the grayscale image (factor 0) and the original
    (factor 1); >1 over-saturates. (ref: functional adjust_saturation)"""
    a, chw = _hwc(img)
    hi = 255 if a.dtype == np.uint8 else 1.0
    gray = (0.299 * a[..., 0] + 0.587 * a[..., 1]
            + 0.114 * a[..., 2])[..., None]
    out = np.clip(gray + (a.astype(np.float32) - gray) * saturation_factor,
                  0, hi).astype(a.dtype)
    return _restore(out, chw)


# ---- class transforms over the functionals above (ref: transforms.py) ----

class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = _jitter_range(value, "contrast")

    def _apply_image(self, img):
        return adjust_contrast(img, random.uniform(*self.value))


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = _jitter_range(value, "saturation")

    def _apply_image(self, img):
        return adjust_saturation(img, random.uniform(*self.value))


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if isinstance(value, (tuple, list)):
            lo, hi = float(value[0]), float(value[1])
            if not -0.5 <= lo <= hi <= 0.5:
                raise ValueError("hue range must be within [-0.5, 0.5]")
            self.value = (lo, hi)
        else:
            if not 0 <= value <= 0.5:
                raise ValueError("hue value should be in [0, 0.5]")
            self.value = (-float(value), float(value))

    def _apply_image(self, img):
        return adjust_hue(img, random.uniform(*self.value))


class ColorJitter(BaseTransform):
    """Randomly jitter brightness/contrast/saturation/hue, applying the
    four sub-transforms in random order (ref: transforms.py ColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        ops = []
        if brightness:
            ops.append(BrightnessTransform(brightness))
        if contrast:
            ops.append(ContrastTransform(contrast))
        if saturation:
            ops.append(SaturationTransform(saturation))
        if hue:
            ops.append(HueTransform(hue))
        self._ops = ops

    def _apply_image(self, img):
        for t in random.sample(self._ops, len(self._ops)):
            img = t(img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            if degrees < 0:
                raise ValueError("degrees must be non-negative")
            self.degrees = (-degrees, degrees)
        else:
            self.degrees = tuple(degrees)
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return rotate(img, angle, self.interpolation, self.expand,
                      self.center, self.fill)


class RandomResizedCrop(BaseTransform):
    """Crop a random area/aspect patch then resize to `size` — the
    standard ImageNet train-time augmentation (ref: transforms.py
    RandomResizedCrop)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4, 4.0 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) \
            else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        import math
        arr = np.asarray(img)
        h, w = arr.shape[0], arr.shape[1]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            log_r = (math.log(self.ratio[0]), math.log(self.ratio[1]))
            ar = math.exp(random.uniform(*log_r))
            cw = int(round(math.sqrt(target * ar)))
            ch = int(round(math.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = random.randint(0, h - ch)
                j = random.randint(0, w - cw)
                patch = arr[i:i + ch, j:j + cw]
                break
        else:  # fallback: center crop of the feasible aspect
            ch = cw = min(h, w)
            i, j = (h - ch) // 2, (w - cw) // 2
            patch = arr[i:i + ch, j:j + cw]
        return np.asarray(Resize(self.size, self.interpolation)(patch))
