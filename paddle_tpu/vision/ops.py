"""Vision ops: boxes, NMS, RoI align, deformable-conv-lite.

Reference: python/paddle/vision/ops.py + detection ops in
paddle/fluid/operators/detection/. NMS is inherently sequential — implemented
with a fixed-iteration lax.while over score order (static shapes, TPU-safe).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops._registry import defop


@defop()
def box_area(boxes):
    return (boxes[..., 2] - boxes[..., 0]) * (boxes[..., 3] - boxes[..., 1])


@defop()
def box_iou(boxes1, boxes2):
    a1 = (boxes1[:, 2] - boxes1[:, 0]) * (boxes1[:, 3] - boxes1[:, 1])
    a2 = (boxes2[:, 2] - boxes2[:, 0]) * (boxes2[:, 3] - boxes2[:, 1])
    lt = jnp.maximum(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = jnp.minimum(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(a1[:, None] + a2[None, :] - inter, 1e-9)


@defop(nondiff=True)
def nms(boxes, scores=None, iou_threshold=0.3, top_k=None):
    """Returns indices of kept boxes (padded with -1 to len(boxes))."""
    n = boxes.shape[0]
    if scores is None:
        scores = jnp.arange(n, 0, -1).astype(jnp.float32)
    order = jnp.argsort(-scores)
    iou = box_iou.__raw_fn__(boxes, boxes)
    iou_sorted = iou[order][:, order]

    def body(i, keep):
        # suppress j>i overlapping a kept i
        sup = (iou_sorted[i] > iou_threshold) & (jnp.arange(n) > i) & keep[i]
        return keep & ~sup

    keep0 = jnp.ones(n, bool)
    keep = jax.lax.fori_loop(0, n, body, keep0)
    kept_sorted_idx = jnp.where(keep, order, -1)
    # compact: kept first, -1 padding after
    key = jnp.where(keep, jnp.arange(n), n + jnp.arange(n))
    perm = jnp.argsort(key)
    out = kept_sorted_idx[perm]
    if top_k is not None:
        out = out[:top_k]
    return out.astype(jnp.int32)


@defop()
def roi_align(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
              sampling_ratio=1, aligned=True):
    """RoI Align via bilinear grid sampling (NCHW; boxes [K, 4] in image
    coords, all on batch item 0 unless boxes_num maps them)."""
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    n, c, h, w = x.shape
    k = boxes.shape[0]
    offset = 0.5 if aligned else 0.0
    x1 = boxes[:, 0] * spatial_scale - offset
    y1 = boxes[:, 1] * spatial_scale - offset
    x2 = boxes[:, 2] * spatial_scale - offset
    y2 = boxes[:, 3] * spatial_scale - offset
    bw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-4)
    bh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-4)
    ys = y1[:, None] + (jnp.arange(oh) + 0.5)[None, :] * (bh[:, None] / oh)
    xs = x1[:, None] + (jnp.arange(ow) + 0.5)[None, :] * (bw[:, None] / ow)

    # map rois to batch items
    if boxes_num is not None:
        bn = jnp.asarray(boxes_num)
        batch_idx = jnp.repeat(jnp.arange(bn.shape[0]), bn, total_repeat_length=k) \
            if hasattr(jnp, "repeat") else jnp.zeros(k, jnp.int32)
    else:
        batch_idx = jnp.zeros(k, jnp.int32)

    def sample_one(bi, ys_i, xs_i):
        img = x[bi]  # [C, H, W]
        yy = jnp.clip(ys_i, 0, h - 1)
        xx = jnp.clip(xs_i, 0, w - 1)
        y0 = jnp.floor(yy).astype(jnp.int32)
        x0 = jnp.floor(xx).astype(jnp.int32)
        y1i = jnp.minimum(y0 + 1, h - 1)
        x1i = jnp.minimum(x0 + 1, w - 1)
        wy = yy - y0
        wx = xx - x0
        # gather 4 corners: [C, oh, ow]
        g = lambda yi, xi: img[:, yi][:, :, xi]  # noqa: E731
        va = g(y0, x0)
        vb = g(y1i, x0)
        vc = g(y0, x1i)
        vd = g(y1i, x1i)
        return (va * (1 - wy)[None, :, None] * (1 - wx)[None, None, :]
                + vb * wy[None, :, None] * (1 - wx)[None, None, :]
                + vc * (1 - wy)[None, :, None] * wx[None, None, :]
                + vd * wy[None, :, None] * wx[None, None, :])

    return jax.vmap(sample_one)(batch_idx, ys, xs)


@defop()
def yolo_box_decode(pred, anchors, downsample_ratio=32, class_num=80,
                    conf_thresh=0.01):
    """Decode YOLO head predictions to boxes (simplified yolo_box op)."""
    b, _, h, w = pred.shape
    na = len(anchors) // 2
    pred = pred.reshape(b, na, 5 + class_num, h, w)
    gx = jnp.arange(w)[None, None, None, :]
    gy = jnp.arange(h)[None, None, :, None]
    ax = jnp.asarray(anchors[0::2], jnp.float32).reshape(1, na, 1, 1)
    ay = jnp.asarray(anchors[1::2], jnp.float32).reshape(1, na, 1, 1)
    cx = (jax.nn.sigmoid(pred[:, :, 0]) + gx) / w
    cy = (jax.nn.sigmoid(pred[:, :, 1]) + gy) / h
    bw = jnp.exp(pred[:, :, 2]) * ax / (w * downsample_ratio)
    bh = jnp.exp(pred[:, :, 3]) * ay / (h * downsample_ratio)
    conf = jax.nn.sigmoid(pred[:, :, 4])
    boxes = jnp.stack([cx - bw / 2, cy - bh / 2, cx + bw / 2, cy + bh / 2],
                      axis=-1)
    return boxes.reshape(b, -1, 4), conf.reshape(b, -1)


# reference public names (ref: python/paddle/vision/ops.py __all__)
def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, name=None, scale_x_y=1.0):
    from ..nn.functional.detection import yolo_box as _yb
    return _yb(x, img_size, anchors, class_num, conf_thresh,
               downsample_ratio, clip_bbox, name, scale_x_y)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh=0.7, downsample_ratio=32, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    from ..nn.functional.detection import yolov3_loss
    return yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                       ignore_thresh, downsample_ratio, gt_score,
                       use_label_smooth, name, scale_x_y)
