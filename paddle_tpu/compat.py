"""Fluid 1.x-era top-level aliases kept by the 2.0 namespace.

Reference: python/paddle/__init__.py re-exports these legacy names
(elementwise_*, reduce_*, VarBase/LoDTensor, fill_constant, ...) alongside the
2.0 API. They are thin aliases over the TPU-native ops — no separate kernels.
"""
from __future__ import annotations

import numpy as np

from . import ops
from .core.tensor import Tensor


# ---- legacy elementwise_* names (ref: fluid/layers/nn.py) ----
def elementwise_add(x, y, axis=-1, name=None):
    return ops.add(x, y)


def elementwise_sub(x, y, axis=-1, name=None):
    return ops.subtract(x, y)


def elementwise_mul(x, y, axis=-1, name=None):
    return ops.multiply(x, y)


def elementwise_div(x, y, axis=-1, name=None):
    return ops.divide(x, y)


def elementwise_mod(x, y, axis=-1, name=None):
    return ops.mod(x, y)


def elementwise_pow(x, y, axis=-1, name=None):
    return ops.pow(x, y)


def elementwise_floordiv(x, y, axis=-1, name=None):
    return ops.floor_divide(x, y)


def elementwise_max(x, y, axis=-1, name=None):
    return ops.maximum(x, y)


def elementwise_min(x, y, axis=-1, name=None):
    return ops.minimum(x, y)


# ---- legacy reduce_* names ----
def reduce_sum(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return ops.sum(input, axis=dim, keepdim=keep_dim)


def reduce_mean(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return ops.mean(input, axis=dim, keepdim=keep_dim)


def reduce_max(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return ops.max(input, axis=dim, keepdim=keep_dim)


def reduce_min(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return ops.min(input, axis=dim, keepdim=keep_dim)


def reduce_prod(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return ops.prod(input, axis=dim, keepdim=keep_dim)


def reduce_all(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return ops.all(input, axis=dim, keepdim=keep_dim)


def reduce_any(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return ops.any(input, axis=dim, keepdim=keep_dim)


# ---- small tensor ops (ref: python/paddle/tensor/) ----
def addcmul(input, tensor1, tensor2, value=1.0, name=None):  # noqa: A002
    return ops.add(input, ops.multiply(ops.multiply(tensor1, tensor2), value))


def multiplex(inputs, index, name=None):
    """Select rows from a list of tensors by per-row index (ref:
    paddle/fluid/operators/multiplex_op.cc)."""
    import jax.numpy as jnp
    stacked = ops.stack(inputs, axis=0)  # [n, batch, ...]
    idx = index if isinstance(index, Tensor) else Tensor(np.asarray(index))
    flat_idx = ops.reshape(idx, [-1])
    batch = ops.arange(0, stacked.shape[1], dtype="int64")
    out = stacked._value[flat_idx._value.astype(jnp.int32), batch._value]
    return Tensor(out)


def tensordot(x, y, axes=2, name=None):
    import jax.numpy as jnp
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    if isinstance(axes, Tensor):
        axes = np.asarray(axes.numpy()).tolist()
    return Tensor(jnp.tensordot(xv, yv, axes=axes))


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def crop_tensor(x, shape=None, offsets=None, name=None):
    return ops.crop(x, shape, offsets)


def numel(x, name=None):
    return Tensor(np.int64(int(np.prod(x.shape)) if x.shape else 1))


def rank(input, name=None):  # noqa: A002
    return Tensor(np.int32(len(input.shape)))


def shape(input, name=None):  # noqa: A002
    return Tensor(np.asarray(input.shape, np.int32))


def is_empty(x, name=None):
    return Tensor(np.bool_(int(np.prod(x.shape)) == 0))


def has_inf(x, name=None):
    return ops.any(ops.isinf(x))


def has_nan(x, name=None):
    return ops.any(ops.isnan(x))


def get_tensor_from_selected_rows(x, name=None):
    return x


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


# ---- legacy class aliases (ref: fluid framework types) ----
VarBase = Tensor
LoDTensor = Tensor
LoDTensorArray = list
ComplexVariable = Tensor
ComplexTensor = Tensor  # pre-2.0 complex type; complex dtypes are native


def in_dynamic_mode():
    """2.0 spelling of in_dygraph_mode (ref: paddle/__init__.py)."""
    from .core.mode import in_dygraph_mode
    return in_dygraph_mode()


def reverse(x, axis):
    """fluid.layers.reverse at the paddle root (ref: paddle/__init__.py
    re-export) — one shim, shared with fluid.layers."""
    from .fluid.layers_legacy import reverse as _impl
    return _impl(x, axis)


# ---- dygraph mode toggles (ref: fluid/dygraph/base.py) ----
def enable_dygraph(place=None):
    from .core.mode import disable_static
    disable_static()


def disable_dygraph():
    from .core.mode import enable_static
    enable_static()


# ---- rng-state passthroughs (CUDA names kept for API parity; the state is
# the TPU PRNG key manager's) ----
def get_cuda_rng_state():
    from .core import rng
    return [(rng._default_generator._key, rng._default_generator._count)]


def set_cuda_rng_state(state):
    from .core import rng
    if state:
        key, count = state[0]
        rng._default_generator._key = key
        rng._default_generator._count = count


def get_cudnn_version():
    return None


def monkey_patch_math_varbase():  # pragma: no cover - Tensor methods are
    pass                          # installed at import time in this rebuild


def monkey_patch_variable():  # pragma: no cover
    pass


# star-import from here must export only the legacy alias names — not rebind
# paddle_tpu.np / paddle_tpu.ops / paddle_tpu.Tensor at top level (ADVICE r1)
__all__ = [_n for _n in list(globals())
           if not _n.startswith("_")
           and _n not in ("np", "ops", "Tensor", "annotations")]
