"""Host-RAM KV tier — the capacity layer BELOW the device block pool
(long-context serving round).

`PagedKVCache` retention parks cold published prefix blocks in-pool:
they cost device HBM until pool pressure reclaims them, and a reclaim
DESTROYS the cached content — a preempted session or a shared system
prompt that lost its blocks pays full prefill recompute on return.
`HostKVTier` adds a second chance: instead of dropping a cold retained
block's index entries, the cache DEMOTES the block to pinned host
memory (this module) and frees the device slot; a later
`attach_prefix`/`match_prefix_len` whose chain continues into the tier
PROMOTES the blocks back into the pool before the attach claims them
(prefetch-on-attach: promotion happens at admission-match time, and
the host->device writes dispatch asynchronously — the engine only
synchronizes when the next dispatch consumes the pool arrays).

Tier format — the r20 int8 codes+scales codec (`kv_quant`):

  * int8 pools demote/promote their native codes+scales BIT-EXACTLY
    (a round-trip through the tier is the identity);
  * dense pools encode on demote (`kv_encode`: per-vector absmax int8,
    |x - deq| <= absmax/254) and decode on promote — the same error
    envelope the quantized-KV serving path runs under, so the pinned
    parity workloads stay token-identical (tested) at ~4x fewer host
    bytes than a raw bf16/f32 park.

The tier is dumb indexed storage: one entry per prefix-chain hash
(`kv_cache.prefix_block_hash`), carrying the entry's fill, parent hash
and the encoded K/V rows.  The DEVICE cache drives every policy
decision (watermark demotion, promotion walks, disjointness of the
device and tier indexes); `capacity_blocks` bounds host memory with
its own LRU — a tier eviction is the true end of the content.

Ownership invariant (fuzz-tested): a chain hash lives in EITHER the
device index or the tier index, never both, and tier entries never
name device blocks — so free ∪ retained ∪ live tables still partition
the device pool exactly as before, with the tier a disjoint host-side
class.
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np


class HostKVTier:
    """Host-memory tier below one `PagedKVCache`.

    capacity_blocks: max resident tier entries (each holds <= one
        block's rows).  The tier LRU-evicts past it — that eviction is
        the real content drop the device retention list used to do.
    watermark: demotion trigger — whenever the DEVICE pool's free-list
        fraction drops below this, the cache demotes LRU retained
        blocks into the tier until the free fraction recovers (or no
        retained blocks remain).  0 disables pressure-driven demotion
        (reclaim-path demotion still applies: an allocation that would
        have evicted a retained block demotes it instead).
    """

    def __init__(self, capacity_blocks=256, watermark=0.25):
        self.capacity_blocks = int(capacity_blocks)
        if self.capacity_blocks < 1:
            raise ValueError(
                f"capacity_blocks must be >= 1, got {capacity_blocks}")
        self.watermark = float(watermark)
        if not 0.0 <= self.watermark < 1.0:
            raise ValueError(
                f"watermark must be in [0, 1), got {watermark}")
        # hash -> (fill, parent, k_payload, v_payload); insertion order
        # doubles as the LRU (move_to_end on touch)
        self._entries: "OrderedDict[int, tuple]" = OrderedDict()
        # parent hash -> {fill: count} — the same candidate-fills walk
        # shape as the device index, so the cache's chain walk continues
        # seamlessly from device into tier
        self._child_fills: dict[int, dict[int, int]] = {}
        self.evictions = 0

    def __len__(self):
        return len(self._entries)

    def has(self, h):
        return h in self._entries

    def child_fills(self, parent):
        """Candidate fills published under `parent` (the chain-walk
        probe — same contract as the device `_child_fills`)."""
        return self._child_fills.get(parent)

    def put(self, h, fill, parent, k_payload, v_payload):
        """Store one demoted entry; first publisher wins (a duplicate
        hash keeps the resident copy and refreshes its LRU position).
        Returns the list of hashes the capacity LRU evicted (`len()`
        of it is the old eviction count; the hashes let the owning
        cache settle per-tenant host-byte attribution)."""
        if h in self._entries:
            self._entries.move_to_end(h)
            return []
        self._entries[h] = (int(fill), int(parent), k_payload, v_payload)
        fills = self._child_fills.setdefault(int(parent), {})
        fills[int(fill)] = fills.get(int(fill), 0) + 1
        evicted = []
        while len(self._entries) > self.capacity_blocks:
            old, ent = self._entries.popitem(last=False)
            self._unlink_fills(ent[0], ent[1])
            self.evictions += 1
            evicted.append(old)
        return evicted

    def _unlink_fills(self, fill, parent):
        fills = self._child_fills.get(parent)
        if fills is None:
            return
        left = fills.get(fill, 1) - 1
        if left > 0:
            fills[fill] = left
        else:
            fills.pop(fill, None)
            if not fills:
                del self._child_fills[parent]

    def get(self, h):
        """(fill, parent, k_payload, v_payload) or None; touches LRU."""
        ent = self._entries.get(h)
        if ent is not None:
            self._entries.move_to_end(h)
        return ent

    def pop(self, h):
        """Remove and return an entry (promotion takes ownership —
        move semantics keep the device/tier indexes disjoint)."""
        ent = self._entries.pop(h, None)
        if ent is not None:
            self._unlink_fills(ent[0], ent[1])
        return ent

    def drop(self, h):
        """Discard a stale entry (e.g. the device re-published the same
        hash — the device copy wins and the tier copy is redundant)."""
        self.pop(h)

    def tokens_resident(self):
        return sum(ent[0] for ent in self._entries.values())

    def bytes_resident(self):
        total = 0
        for _fill, _parent, kp, vp in self._entries.values():
            for pay in (kp, vp):
                total += sum(int(np.asarray(a).nbytes)
                             for a in _leaves(pay))
        return total

    def stats(self):
        return {
            "capacity_blocks": self.capacity_blocks,
            "watermark": self.watermark,
            "tiered_blocks": len(self._entries),
            "tiered_tokens": self.tokens_resident(),
            "bytes_resident": self.bytes_resident(),
            "evictions": self.evictions,
        }


def _leaves(payload):
    """Flatten a tier payload: plain ndarray, or a (codes, scales)
    QuantizedKV-like pair (duck-typed — this module must not import
    jax)."""
    if hasattr(payload, "codes"):
        return (payload.codes, payload.scales)
    if isinstance(payload, (tuple, list)):
        out = []
        for p in payload:
            out.extend(_leaves(p))
        return out
    return (payload,)


def payload_nbytes(payload):
    """Total bytes of one K/V payload tree (plain ndarrays, nested
    tuples/lists, or QuantizedKV codes+scales pairs) — the unit the
    tier residency accounting and the migration wire charge share."""
    return sum(int(np.asarray(a).nbytes) for a in _leaves(payload))


def normalize_kv_tier(kv_tier):
    """Normalize the server's `kv_tier=` ctor value: None stays off,
    True builds the default tier, an instance passes through."""
    if kv_tier is None or kv_tier is False:
        return None
    if kv_tier is True:
        return HostKVTier()
    if not isinstance(kv_tier, HostKVTier):
        raise TypeError(f"kv_tier must be a HostKVTier, True or None, "
                        f"got {type(kv_tier).__name__}")
    return kv_tier


def disabled_tier_stats():
    """Zeroed, schema-congruent `stats()["tier"]` block (the standing
    zeroed-when-disabled convention: dashboards and bench records need
    no gating)."""
    return {
        "enabled": False,
        "capacity_blocks": 0,
        "tiered_blocks": 0,
        "tiered_tokens": 0,
        "bytes_resident": 0,
        "demotions": 0,
        "promotions": 0,
        "evictions": 0,
        "bytes_out": 0,
        "bytes_in": 0,
        "hit_tokens": 0,
    }
