"""int8 KV-cache quantization primitives (quantized serving round).

The paged pool can store K/V blocks as int8 codes plus a parallel scale
buffer (`PagedKVCache(kv_dtype="int8")`) — roughly half the HBM per
resident token, so the same pool bytes hold ~2x the concurrent
sequences, and the saving compounds with prefix caching (more retained
prefixes per byte). EQuARX (PAPERS.md) is the direction: serving decode
is memory-bound, so low-bit compression of the streamed bytes is where
TPU wins come from.

Scale layout: one symmetric absmax scale PER STORED VECTOR — i.e. per
(layer, block, row, head) over the Dh lanes, `scales[l, b, r, h] =
max|K[l, b, r, h, :]| / 127`. This is the finest granularity the
write path can produce exactly: every cache append quantizes only the
vectors it writes (the running per-block absmax IS the per-row absmax
— no already-written code ever needs rescaling, so the functional
jitted writers stay single-scatter), and a block copy (CoW), share
(prefix attach), swap-out or truncate moves codes and scales by the
same block index, keeping the scale buffer in lockstep with the block
table machinery by construction. The cost is one scale element per
Dh codes (~3% at Dh=32, ~1.5% at Dh=64) — still ~1.9x fewer bytes per
token than bf16.

Round-trip bound (unit-tested): symmetric round-to-nearest gives
|x - dequant(quant(x))| <= scale/2 = absmax/254 per element.

`QuantizedKV` is a NamedTuple, hence automatically a JAX pytree: the
serving engine passes it through jitted dispatches exactly where a
plain bf16 array went, `jax.tree.map` copies handle CoW, and donation
donates both leaves. Attention ops detect it by the `codes` attribute
(duck-typed — no import cycle) and dequantize INSIDE the kernel, so a
bf16 copy of the cache never materializes in HBM.
"""
from __future__ import annotations

from typing import Any, NamedTuple


class QuantizedKV(NamedTuple):
    """One K or V pool quantized: int8 `codes` plus the per-vector
    `scales` buffer (codes.shape[:-1], compute dtype)."""
    codes: Any   # int8  [..., BS, H, Dh]
    scales: Any  # float [..., BS, H]


def kv_encode(t, scale_dtype=None):
    """Quantize `t` [..., Dh] to (int8 codes, per-vector scales [...]).

    Symmetric absmax over the last axis, computed in f32 regardless of
    the input dtype (a bf16 absmax would quantize against a value up to
    0.4% off). Zero vectors get the 1e-12 floor, so their codes are 0
    and the round trip is exact."""
    import jax.numpy as jnp

    sd = t.dtype if scale_dtype is None else scale_dtype
    tf = t.astype(jnp.float32)
    amax = jnp.max(jnp.abs(tf), axis=-1)
    sc = jnp.maximum(amax, 1e-12) / 127.0
    codes = jnp.clip(jnp.round(tf / sc[..., None]),
                     -127, 127).astype(jnp.int8)
    return codes, sc.astype(sd)


def kv_decode(codes, scales, dtype):
    """Dequantize int8 codes [..., Dh] with per-vector scales [...] to
    `dtype`. Library/test helper — the attention kernels fold the
    scales into their score/output contractions instead of calling
    this on the full cache."""
    return codes.astype(dtype) * scales[..., None].astype(dtype)


def is_quantized(kv):
    """Duck-typed QuantizedKV check (usable from modules that must not
    import this package at module scope)."""
    return hasattr(kv, "codes") and hasattr(kv, "scales")
