"""Paged KV cache — block-pool cache for the continuous-batching server.

Reference direction: Ragged Paged Attention (arXiv:2604.15464) — the
TPU-native answer to the static-cache serving loop. Instead of one
contiguous [B, S_max] cache slab per batch (which pins every slot to the
longest possible sequence), K/V live in a pool of fixed-size blocks:

    k_blocks, v_blocks: [L, num_blocks, block_size, H, Dh]

Each sequence owns an ordered *block table* (a list of block ids); token
`t` of a sequence lives at (table[t // block_size], t % block_size).
Attention gathers keys by block table, masked by the sequence's true
length — no pad-token-value matching anywhere, so a prompt that
legitimately contains `pad_token_id` can never be corrupted.

Block 0 is a reserved *trash* block: it is never allocated, and jitted
writers route masked-out lanes (padding tail of a prefill bucket,
inactive decode slots) into it so a scatter always has a legal target.
Block tables are padded with 0 for the same reason — gathered trash
positions are masked by length before the softmax.

The pool itself is host-side bookkeeping (allocate/ensure/free on Python
ints); the device arrays are functional — jitted prefill/step functions
take them as inputs and return the updated arrays, and the cache swaps
them in via `swap_arrays`.

Prefix caching (round 9): the pool is CONTENT-ADDRESSED. A full block
holding tokens `B_i` of a sequence whose earlier blocks hash to `h_i-1`
gets the rolling prefix hash `h_i = H(h_i-1, B_i)`; an index maps hash
-> block id, and blocks carry REFCOUNTS (number of block tables
containing them). A new request whose prompt prefix matches a chain of
cached blocks is attached to them by `attach_prefix` — its block table
simply names the cached blocks (refcount bumped), so the shared prefix
is never prefilled again. The last PARTIAL block of a published prompt
is indexed too (entry carries its fill), which is what makes
conversation-continuation and identical-prompt resubmission hits
possible; writing into a shared or index-claimed region goes through
`prepare_write`, which COPIES the block first (copy-on-write) so the
cached content and every other referent stay intact. Freed blocks that
still hold indexed content are not returned to the free list — they are
parked in an LRU *retention* list and reclaimed (index entries dropped,
block freed) only when an allocation would otherwise exhaust the pool.

Quantized pool (quantized-serving round): `kv_dtype="int8"` stores the
K/V blocks as int8 codes plus a parallel per-vector scale buffer
(kv_quant.QuantizedKV, same [*, num_blocks, ...] leading layout), so
the same HBM holds ~2x the resident tokens. The block-table API is
UNCHANGED — scales ride their block index through alloc/free/CoW/
attach/retain/truncate/swap-out automatically — and the jitted
writers quantize on append while the attention kernels dequantize on
read, so a bf16 copy of the cache never exists in HBM.

Host-RAM tier (long-context serving round): attaching a
`kv_tier.HostKVTier` gives cold retained content a second life BELOW
the device pool. Pool pressure (watermark or an allocation's reclaim)
DEMOTES the LRU retained block: its index entries move to the tier's
host-side index (int8 codes+scales — bit-exact for an int8 pool,
`kv_quant` encode for a dense one) and the device slot frees. A later
`attach_prefix` / `match_prefix_len` / `export_prefix` whose chain
continues into the tier PROMOTES those entries back into device blocks
first (prefetch-on-attach: the host->device writes dispatch
asynchronously at match time, before the attach claims the chain).
Without a tier nothing changes — reclaim drops entries exactly as
before.

Invariants (fuzz-tested in tests/test_prefix_cache.py):
  * free list, retention list and the union of live block tables
    PARTITION the usable pool (block 0 in none of them);
  * `_ref[b]` equals the number of live tables containing `b`; a block
    leaves the partition's "live" class exactly when it hits zero;
  * an index entry (hash -> block, fill) only ever describes rows
    `[0, fill)` of its block, and those rows are immutable while the
    entry exists (writers CoW or drop the entry first);
  * a chain hash lives in EITHER the device index or the tier index,
    never both (promotion pops the tier entry, demotion drops the
    device entry, re-publication drops the stale tier copy).
"""
from __future__ import annotations

import functools
import hashlib
import itertools
import time
from collections import OrderedDict

import numpy as np

from ..observability import metrics as _metrics

# Pool telemetry (ISSUE 2): pushed on every alloc/grow/free, one bool
# check each while PADDLE_TPU_TELEMETRY is off. Every series carries a
# `pool` label (one per cache instance) so several live caches — the
# serving cache plus an offline generate(), say — can no longer alias
# each other's gauges.
_POOL_LABEL = ("pool",)
# Block-count gauges carry a `tier` label (long-context round):
# tier="device" is the in-pool series (the only one when no host tier
# is attached); tier="host" reports the HostKVTier — used is always 0
# there (tier content backs no live table), retained is the resident
# promotable entries, free is the remaining tier capacity.
_POOL_TIER_LABELS = ("pool", "tier")
_m_used_blocks = _metrics.gauge(
    "kv_pool_used_blocks", "allocated blocks (trash block excluded); "
    "tier='device' in-pool, tier='host' always 0",
    labelnames=_POOL_TIER_LABELS)
_m_free_blocks = _metrics.gauge(
    "kv_pool_free_blocks", "blocks available for allocation "
    "(tier='host': remaining HostKVTier entry capacity)",
    labelnames=_POOL_TIER_LABELS)
_m_retained_blocks = _metrics.gauge(
    "kv_pool_retained_blocks", "freed-but-indexed blocks parked in the "
    "prefix-cache LRU retention list (reclaimed under pool pressure); "
    "tier='host': promotable entries resident in the HostKVTier",
    labelnames=_POOL_TIER_LABELS)
_m_utilization = _metrics.gauge(
    "kv_pool_utilization", "live tokens / usable pool tokens",
    labelnames=_POOL_LABEL)
_m_block_fill = _metrics.gauge(
    "kv_pool_block_fill", "live tokens / allocated block capacity "
    "(1.0 = no internal fragmentation; can exceed 1.0 when prefix "
    "blocks are shared)", labelnames=_POOL_LABEL)
_m_sequences = _metrics.gauge(
    "kv_pool_sequences", "sequences holding blocks",
    labelnames=_POOL_LABEL)
_m_alloc_failures = _metrics.counter(
    "kv_pool_alloc_failures_total",
    "allocations refused because the pool was exhausted",
    labelnames=_POOL_LABEL)
# HBM accounting (quantized-serving round): dtype-aware, so the int8
# halving is observable per pool instead of inferred from config.
# The byte gauges carry a `shard` label (sharded-serving round):
# shard="all" is the whole-pool total; when the pool's device arrays
# are sharded over a mesh (serving_dist), per-shard series
# shard="0".."n-1" report each device's equal slice — the number that
# has to fit ONE device's HBM.
_POOL_SHARD_LABELS = ("pool", "shard")
_m_pool_bytes = _metrics.gauge(
    "kv_pool_bytes_total", "device bytes held by the K/V block pool "
    "(codes + scale buffers when kv_dtype='int8'; dtype-aware); "
    "shard='all' = pool total, shard='k' = device k's slice when the "
    "pool is mesh-sharded", labelnames=_POOL_SHARD_LABELS)
_m_bytes_per_token = _metrics.gauge(
    "kv_pool_bytes_per_token", "pool bytes per usable token slot "
    "(bytes_total / capacity_tokens — ~half under int8 KV); same "
    "shard label semantics as kv_pool_bytes_total",
    labelnames=_POOL_SHARD_LABELS)

# Prefix-cache telemetry (round 9 tentpole).
_m_prefix_lookups = _metrics.counter(
    "kv_prefix_cache_lookups_total",
    "attach_prefix calls (one per admitted request when caching is on)",
    labelnames=_POOL_LABEL)
_m_prefix_hits = _metrics.counter(
    "kv_prefix_cache_hits_total",
    "attach_prefix calls that matched at least one cached token",
    labelnames=_POOL_LABEL)
_m_prefix_hit_tokens = _metrics.counter(
    "kv_prefix_cache_hit_tokens_total",
    "prompt tokens served from cached blocks instead of prefill",
    labelnames=_POOL_LABEL)
_m_prefix_lookup_tokens = _metrics.counter(
    "kv_prefix_cache_lookup_tokens_total",
    "prompt tokens eligible for matching (prompt length - 1: the last "
    "token is always recomputed to sample token 0)",
    labelnames=_POOL_LABEL)
_m_prefix_evictions = _metrics.counter(
    "kv_prefix_cache_evictions_total",
    "retained blocks reclaimed (index entries dropped) under pool "
    "pressure", labelnames=_POOL_LABEL)
_m_prefix_cow = _metrics.counter(
    "kv_prefix_cache_cow_copies_total",
    "copy-on-write block copies (a write landed in a shared or "
    "index-claimed block)", labelnames=_POOL_LABEL)

# Host-RAM tier telemetry (long-context serving round).
_m_tier_demotions = _metrics.counter(
    "kv_tier_demotions_total",
    "retained blocks demoted from the device pool into the host tier "
    "(index entries moved, device slot freed)", labelnames=_POOL_LABEL)
_m_tier_promotions = _metrics.counter(
    "kv_tier_promotions_total",
    "tier entries promoted back into device blocks ahead of a prefix "
    "match (prefetch-on-attach)", labelnames=_POOL_LABEL)
_m_tier_bytes = _metrics.counter(
    "kv_tier_bytes_total",
    "host tier traffic in encoded (int8 codes+scales) bytes; "
    "direction='out' = device->host demotion, 'in' = host->device "
    "promotion", labelnames=("pool", "direction"))
_m_tier_hit_tokens = _metrics.counter(
    "kv_tier_hit_tokens_total",
    "prompt tokens served from promoted tier blocks instead of prefill "
    "recompute (counted once, at promotion)", labelnames=_POOL_LABEL)

_pool_ids = itertools.count()

#: parent hash of a sequence's first block (nothing hashes to 0).
ROOT_HASH = 0


class BlockPoolExhausted(RuntimeError):
    """Raised when an allocation needs more free blocks than the pool has
    (after reclaiming every LRU-retained prefix-cache block).

    Carries structured pressure fields (r17) so the reliability layer
    can report and reason about the shortfall without parsing the
    message: `needed` blocks requested, `available` blocks obtainable
    (free + reclaimable) at raise time. Both default to -1 for
    messages raised without them (e.g. injected faults)."""

    def __init__(self, msg, *, needed=-1, available=-1):
        super().__init__(msg)
        self.needed = int(needed)
        self.available = int(available)


def blocks_for(num_tokens: int, block_size: int) -> int:
    """Blocks needed to hold `num_tokens` tokens."""
    return max(0, -(-int(num_tokens) // int(block_size)))


def prefix_block_hash(parent: int, tokens) -> int:
    """Rolling content hash of one block: H(parent_hash, block_tokens).

    blake2b over the 16-byte parent digest + the tokens as int64 LE —
    deterministic, dtype-normalized, and collision-safe in a way
    Python's randomized builtin hash() is not (a collision here would
    serve the wrong K/V)."""
    data = int(parent).to_bytes(16, "little") + \
        np.ascontiguousarray(np.asarray(tokens, np.int64)).tobytes()
    return int.from_bytes(
        hashlib.blake2b(data, digest_size=16).digest(), "little")


@functools.lru_cache(maxsize=8)
def _copy_block_fn(donate):
    """Jitted whole-block device copy (the CoW kernel): one dynamic
    slice + scatter per array leaf, recompiled per (structure, shape,
    dtype) only. kc/vc may be plain arrays or `QuantizedKV`
    (codes, scales) pytrees — block ids index axis 1 of every leaf, so
    one tree-mapped copy moves codes and scales in lockstep."""
    import jax

    def cp(kc, vc, src, dst):
        return jax.tree.map(lambda a: a.at[:, dst].set(a[:, src]),
                            (kc, vc))

    return jax.jit(cp, donate_argnums=(0, 1) if donate else ())


class PagedKVCache:
    """Block-pool KV cache: fixed-size blocks, per-sequence block tables.

    num_layers/num_heads/head_dim: transformer shape (GPT-2 layout).
    block_size: tokens per block. 128 keeps the Pallas ragged-decode
        kernel's lane alignment on TPU; smaller (8/16) wastes less on CPU
        smokes and short sequences.
    num_blocks: pool size INCLUDING the reserved trash block 0, so the
        usable capacity is (num_blocks - 1) * block_size tokens.
    kv_dtype: None stores K/V in `dtype` (the pre-quantization pool).
        "int8" stores int8 codes plus a parallel per-vector scale
        buffer (kv_quant.QuantizedKV) — ~half the bytes per resident
        token; every block operation (alloc/free/CoW/attach/retain/
        truncate/swap-out) moves scales with their block by
        construction, because both live under the same block index.
        The DISPATCH side must match: pair an int8 pool with
        `PagedDecoder(kv_dtype="int8")` (the decoder checks eagerly).
    name: label for the `kv_pool_*` / `kv_prefix_cache_*` metric series
        (auto-assigned "poolN" when omitted, so concurrent caches never
        alias each other's telemetry).
    tier: optional `kv_tier.HostKVTier` (or True for the default one)
        attached below the pool — cold retained blocks demote to host
        RAM instead of being dropped, and prefix matches promote them
        back. None (default) keeps the pre-tier behaviour exactly.
    """

    def __init__(self, num_layers, num_heads, head_dim, *, block_size=128,
                 num_blocks=64, dtype=None, kv_dtype=None, name=None,
                 tier=None):
        import jax.numpy as jnp

        if num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is the "
                             "reserved trash block)")
        if kv_dtype not in (None, "int8"):
            raise ValueError(f"unknown kv_dtype {kv_dtype!r} "
                             "(supported: None, 'int8')")
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.kv_dtype = kv_dtype
        self._name = str(name) if name else f"pool{next(_pool_ids)}"
        dt = jnp.float32 if dtype is None else dtype
        self.dtype = dt
        shape = (self.num_layers, self.num_blocks, self.block_size,
                 self.num_heads, self.head_dim)
        if kv_dtype == "int8":
            from .kv_quant import QuantizedKV

            self.k_blocks = QuantizedKV(jnp.zeros(shape, jnp.int8),
                                        jnp.zeros(shape[:-1], dt))
            self.v_blocks = QuantizedKV(jnp.zeros(shape, jnp.int8),
                                        jnp.zeros(shape[:-1], dt))
        else:
            self.k_blocks = jnp.zeros(shape, dt)
            self.v_blocks = jnp.zeros(shape, dt)
        # block 0 reserved: free list starts at 1
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._tables: dict[object, list[int]] = {}
        self._lens: dict[object, int] = {}
        # prefix-cache state: refcounts (tables containing each block),
        # the content index hash -> (block, fill, parent), the reverse
        # block -> entry-hashes map, candidate fills per parent hash
        # (lookup iteration), and the LRU retention list of freed blocks
        # that still hold indexed content.
        self._ref: dict[int, int] = {}
        self._index: dict[int, tuple[int, int, int]] = {}
        self._block_entries: dict[int, set[int]] = {}
        self._child_fills: dict[int, dict[int, int]] = {}
        self._retained: OrderedDict[int, None] = OrderedDict()
        self._shard_count = 1  # device shards (serving_dist sets > 1)
        self._peak_blocks = 0
        self._peak_retained = 0
        self._prefix_lookups = 0
        self._prefix_hits = 0
        self._hit_tokens = 0
        self._lookup_tokens = 0
        self._evictions = 0
        self._cow_copies = 0
        # host-RAM tier (long-context round): None = pre-tier behaviour
        self._tier = None
        #: optional callback(kind, **fields) the engine wires to its
        #: flight recorder / tracing — kind is "demote" or "promote"
        self.on_tier_event = None
        self._tier_demotions = 0
        self._tier_promotions = 0
        self._tier_bytes_out = 0
        self._tier_bytes_in = 0
        self._tier_hit_tokens = 0
        #: resource attribution (ISSUE 17): an
        #: `observability.attribution.ResourceLedger` the engine
        #: attaches BEFORE the first allocation. Every non-free block
        #: then carries exactly one (tenant, rid) owner — assigned
        #: when `_take_blocks` pulls it off the free list, cleared
        #: only when the block returns there — so per-tenant block
        #: counts sum to pool occupancy no matter how prefix sharing,
        #: retention, revival or CoW shuffle the references
        #: (the publisher keeps paying for shared blocks; attachers
        #: are credited prefix savings instead).
        self.ledger = None
        self._seq_owner: dict[object, tuple] = {}   # seq -> (tenant, rid)
        self._block_owner: dict[int, tuple] = {}    # block -> (tenant, rid)
        self._tier_owner: dict[int, tuple] = {}     # hash -> (tenant, bytes)
        if tier is not None:
            self.attach_tier(tier)

    # ---- pool bookkeeping (host-side) ---------------------------------
    @property
    def free_block_count(self):
        return len(self._free)

    @property
    def retained_block_count(self):
        return len(self._retained)

    @property
    def available_block_count(self):
        """Blocks an allocation can obtain: the free list plus the
        LRU-retained blocks it may reclaim — the number admission
        control should reason about. Invariant under tiering: a
        demotion moves a block retained -> free (the sum is
        unchanged), so admission never under-counts when content is
        parked in the host tier — the tiered entries cost no device
        block until a match promotes them back into this sum."""
        return len(self._free) + len(self._retained)

    @property
    def capacity_tokens(self):
        return (self.num_blocks - 1) * self.block_size

    @property
    def pool_bytes_total(self):
        """Device bytes held by the K/V pool arrays (codes + scale
        buffers under int8 — dtype-aware, fixed at construction)."""
        import jax

        return sum(int(a.nbytes) for a in
                   jax.tree.leaves((self.k_blocks, self.v_blocks)))

    @property
    def scale_bytes(self):
        """Bytes of the per-vector scale buffers (0 for a dense pool) —
        the quantization overhead on top of the int8 codes."""
        if self.kv_dtype != "int8":
            return 0
        return int(self.k_blocks.scales.nbytes
                   + self.v_blocks.scales.nbytes)

    @property
    def bytes_per_token(self):
        """Pool bytes per usable token slot (includes the trash block's
        amortized share — the honest per-token HBM cost)."""
        return self.pool_bytes_total / (self.capacity_tokens or 1)

    def set_shard_count(self, n):
        """Record how many device shards the pool arrays are placed
        over (serving_dist): the byte gauges then also emit per-shard
        series. Pure telemetry — the block-table API is shard-blind."""
        n = int(n)
        if n < 1:
            raise ValueError(f"shard count must be >= 1, got {n}")
        self._shard_count = n
        self._push_gauges()

    def stats_kv_dtype(self):
        """The stored element dtype as a stats/dashboard string:
        "int8" for a quantized pool, else the dense dtype name."""
        return self.kv_dtype or np.dtype(self.dtype).name

    def _get_table(self, seq_id, op):
        try:
            return self._tables[seq_id]
        except KeyError:
            raise KeyError(
                f"unknown sequence {seq_id!r} in {op}(): not allocated "
                f"in this cache (live sequences: {len(self._tables)})"
            ) from None

    def set_seq_owner(self, seq_id, tenant, rid=None):
        """Register who pays for `seq_id`'s future allocations
        (attribution, ISSUE 17). The engine calls this at slot install,
        before the first `ensure_many` growth; unowned sequences charge
        the "default" tenant. Cleared by `free`."""
        self._seq_owner[seq_id] = (str(tenant), rid)

    def _ledger_block_freed(self, b):
        """A block re-entered the free list: close out its ownership."""
        own = self._block_owner.pop(b, None)
        if own is not None and self.ledger is not None:
            self.ledger.block_event(own[0], own[1], -1)

    def _ledger_tier_add(self, h, tenant, nbytes):
        if self.ledger is None or h in self._tier_owner:
            return
        self._tier_owner[h] = (tenant, nbytes)
        self.ledger.host_bytes_event(tenant, nbytes)

    def _ledger_tier_drop(self, h):
        own = self._tier_owner.pop(h, None)
        if own is not None and self.ledger is not None:
            self.ledger.host_bytes_event(own[0], -own[1])

    def _take_blocks(self, n, owner=None):
        """Pop `n` blocks off the free list (refcount 1 each),
        reclaiming LRU-retained prefix blocks as needed. Callers must
        pre-check availability when they need all-or-nothing semantics
        (`ensure_many` does). `owner` is the (tenant, rid) pair charged
        for the blocks while they stay off the free list."""
        while len(self._free) < n and self._retained:
            self._reclaim_lru()
        if n > len(self._free):
            _m_alloc_failures.labels(pool=self._name).inc()
            raise BlockPoolExhausted(
                f"need {n} blocks, only {len(self._free)} free "
                f"(pool {self.num_blocks - 1})",
                needed=n, available=len(self._free))
        taken = [self._free.pop() for _ in range(n)]
        for b in taken:
            self._ref[b] = 1
        if self.ledger is not None and taken:
            tenant, rid = owner if owner is not None else ("default", None)
            for b in taken:
                self._block_owner[b] = (tenant, rid)
            self.ledger.block_event(tenant, rid, len(taken))
        used = self.num_blocks - 1 - len(self._free) - len(self._retained)
        self._peak_blocks = max(self._peak_blocks, used)
        return taken

    def _release_block(self, b):
        """Drop one table reference to `b`; at refcount zero the block
        goes to the LRU retention list if the prefix index still names
        it, else back to the free list."""
        left = self._ref.get(b, 0) - 1
        if left > 0:
            self._ref[b] = left
            return
        self._ref.pop(b, None)
        if self._block_entries.get(b):
            self._retained[b] = None
            self._retained.move_to_end(b)
            self._peak_retained = max(self._peak_retained,
                                      len(self._retained))
        else:
            self._free.append(b)
            self._ledger_block_freed(b)

    def _reclaim_lru(self):
        """Evict the least-recently-retained block: drop its index
        entries and return it to the free list. With a host tier
        attached the content is demoted instead of dropped — the
        device slot still frees, but the entries stay promotable."""
        if self._tier is not None:
            self._demote_lru()
            return
        b, _ = self._retained.popitem(last=False)
        for h in list(self._block_entries.get(b, ())):
            self._drop_entry(h)
        self._free.append(b)
        self._ledger_block_freed(b)
        self._evictions += 1
        _m_prefix_evictions.labels(pool=self._name).inc()

    def _register_entry(self, h, block, fill, parent):
        self._index[h] = (block, fill, parent)
        self._block_entries.setdefault(block, set()).add(h)
        fills = self._child_fills.setdefault(parent, {})
        fills[fill] = fills.get(fill, 0) + 1
        if self._tier is not None:
            # move semantics: a hash never lives in both indexes — the
            # freshly written device copy wins over a stale tier copy
            self._tier.drop(h)
            self._ledger_tier_drop(h)

    # ---- host-RAM tier (long-context serving round) -------------------
    def attach_tier(self, tier):
        """Attach a `kv_tier.HostKVTier` below this pool (True builds
        the default tier; None detaches — resident tier content is
        simply forgotten). Returns the attached tier (or None)."""
        from .kv_tier import normalize_kv_tier

        self._tier = normalize_kv_tier(tier)
        if self._tier is None:
            for h in list(self._tier_owner):  # forgotten content is
                self._ledger_tier_drop(h)     # no longer anyone's cost
        self._push_gauges()
        return self._tier

    @property
    def tier(self):
        return self._tier

    def _tier_grab(self, b, fill):
        """Host-side copy of rows [0, fill) of block `b` in the tier
        codec: the pool's native codes+scales for an int8 pool
        (bit-exact round trip), `kv_quant.kv_encode` for a dense one."""
        from .kv_quant import QuantizedKV, kv_encode

        if self.kv_dtype == "int8":
            def grab(arr):
                return QuantizedKV(
                    np.asarray(arr.codes[:, b, :fill]),
                    np.asarray(arr.scales[:, b, :fill]))
        else:
            def grab(arr):
                codes, scales = kv_encode(arr[:, b, :fill])
                return QuantizedKV(np.asarray(codes),
                                   np.asarray(scales))
        return grab(self.k_blocks), grab(self.v_blocks)

    def _tier_install(self, b, fill, k_pay, v_pay):
        """Write a tier payload into rows [0, fill) of device block
        `b`. The .at[].set dispatches ASYNCHRONOUSLY — this is the
        prefetch: by the time the next jitted dispatch consumes the
        pool arrays, the copy has overlapped with host work."""
        import jax.numpy as jnp

        from .kv_quant import kv_decode

        if self.kv_dtype == "int8":
            def put(arr, pay):
                return type(arr)(
                    arr.codes.at[:, b, :fill].set(
                        jnp.asarray(pay.codes, arr.codes.dtype)),
                    arr.scales.at[:, b, :fill].set(
                        jnp.asarray(pay.scales, arr.scales.dtype)))
        else:
            def put(arr, pay):
                rows = kv_decode(jnp.asarray(pay.codes),
                                 jnp.asarray(pay.scales), arr.dtype)
                return arr.at[:, b, :fill].set(rows)
        self.k_blocks = put(self.k_blocks, k_pay)
        self.v_blocks = put(self.v_blocks, v_pay)

    @staticmethod
    def _payload_bytes(*payloads):
        return sum(int(p.codes.nbytes) + int(p.scales.nbytes)
                   for p in payloads)

    def _demote_lru(self):
        """Demote the LRU retained block into the host tier: every
        index entry on it MOVES to the tier (with an encoded host copy
        of its rows) and the device slot joins the free list."""
        b, _ = self._retained.popitem(last=False)
        owner = self._block_owner.get(b, ("default", None))
        moved = 0
        nbytes = 0
        for h in list(self._block_entries.get(b, ())):
            _blk, fill, parent = self._index[h]
            kp, vp = self._tier_grab(b, fill)
            evicted = self._tier.put(h, fill, parent, kp, vp)
            per = self._payload_bytes(kp, vp)
            nbytes += per
            # the demoting block's owner keeps paying — now in host
            # byte-seconds; a capacity eviction ends the old owner's
            self._ledger_tier_add(h, owner[0], per)
            for old in evicted:
                self._ledger_tier_drop(old)
            self._drop_entry(h)
            moved += 1
        self._free.append(b)
        self._ledger_block_freed(b)
        self._tier_demotions += 1
        self._tier_bytes_out += nbytes
        if _metrics.enabled():
            _m_tier_demotions.labels(pool=self._name).inc()
            _m_tier_bytes.labels(pool=self._name,
                                 direction="out").inc(nbytes)
        cb = self.on_tier_event
        if cb is not None:
            cb("demote", block=b, entries=moved, bytes=nbytes)

    def maybe_demote(self):
        """Watermark-driven demotion sweep: while the free list is
        below `tier.watermark` of the usable pool and retained blocks
        remain, demote the coldest. Called from every release path;
        cheap no-op without a tier. Returns blocks demoted."""
        if self._tier is None or self._tier.watermark <= 0:
            return 0
        low = int(self._tier.watermark * (self.num_blocks - 1))
        n = 0
        while len(self._free) < low and self._retained:
            self._demote_lru()
            n += 1
        if n:
            self._push_gauges()
        return n

    def demote_cold(self, n=1):
        """Explicitly demote up to `n` LRU retained blocks to the tier
        (operator / test hook — the watermark sweep is the automatic
        path). Returns blocks actually demoted."""
        moved = 0
        while (moved < int(n) and self._retained
               and self._tier is not None):
            self._demote_lru()
            moved += 1
        if moved:
            self._push_gauges()
        return moved

    def _promote_entry(self, h):
        """Pull one tier entry back into a device block: allocate,
        decode the payload in, register + park in retention (MRU) so
        the caller's chain walk claims it. Returns the promoted
        payload bytes (0 when the device re-published the hash
        meanwhile and the chain walk just continues), or None when the
        entry is gone or no device block is obtainable."""
        ent = self._tier.get(h)
        if ent is None:
            return None
        if h in self._index:
            # the device re-published the same hash meanwhile — the
            # device copy wins, the tier copy is redundant
            self._tier.drop(h)
            self._ledger_tier_drop(h)
            return 0
        if self.available_block_count < 1:
            return None
        fill, parent, kp, vp = ent
        # the promoted device block belongs to whoever paid for the
        # tier entry (the demoter), not whoever triggered the match
        own = self._tier_owner.get(h)
        b = self._take_blocks(
            1, owner=(own[0], None) if own is not None else None)[0]
        self._tier_install(b, fill, kp, vp)
        self._tier.pop(h)
        self._ledger_tier_drop(h)
        self._register_entry(h, b, fill, parent)
        self._release_block(b)  # refcount 0 + indexed -> retention MRU
        nbytes = self._payload_bytes(kp, vp)
        self._tier_promotions += 1
        self._tier_bytes_in += nbytes
        if _metrics.enabled():
            _m_tier_promotions.labels(pool=self._name).inc()
            _m_tier_bytes.labels(pool=self._name,
                                 direction="in").inc(nbytes)
        cb = self.on_tier_event
        if cb is not None:
            cb("promote", block=b, tokens=fill, bytes=nbytes)
        return nbytes

    def _promote_for(self, ids, max_match, limit_blocks=None,
                     overlapped=False, collect=None):
        """Prefetch-on-match: walk the DEVICE chain along `ids` to its
        end, then continue the walk through the TIER index, promoting
        each tiered entry back into the device pool so the subsequent
        `_match_chain` (and the attach claim on top of it) sees one
        unbroken device chain. Returns tokens promoted.

        The tier half of the walk is TIMED and, when it promoted
        anything, reported as ONE aggregated `tier_promote` callback
        event (blocks/tokens/bytes/dur_s/overlapped) — the serving
        layer turns it into its own trace event so promotion wall time
        never hides inside the admission span (the per-entry `promote`
        events are kept for block-level forensics).  `limit_blocks`
        bounds how many device blocks one walk may consume (the
        prefetch tick's anti-thrash budget); `overlapped=True` marks a
        prefetch-ahead walk riding the async round window; `collect`
        (a list) receives the chain hashes actually promoted."""
        if self._tier is None or not len(self._tier):
            return 0
        n = int(ids.size)
        h = ROOT_HASH
        pos = 0
        # device half: same longest-match walk as _match_chain, but
        # tracking the chain hash so the tier walk continues from it
        while pos < max_match:
            cand = self._child_fills.get(h)
            hit = None
            if cand:
                avail = n - pos
                for f in sorted(cand, reverse=True):
                    if f > avail:
                        continue
                    hh = prefix_block_hash(h, ids[pos:pos + f])
                    if hh in self._index:
                        hit = (hh, f)
                        break
            if hit is None:
                break
            hh, f = hit
            use = min(f, max_match - pos)
            pos += use
            if f < self.block_size or use < f:
                return 0       # partial block ends the chain for good
            h = hh
        promoted_tokens = 0
        blocks = 0
        nbytes = 0
        t0 = time.perf_counter()
        while pos < max_match:
            if limit_blocks is not None and blocks >= int(limit_blocks):
                break
            cand = self._tier.child_fills(h)
            hit = None
            if cand:
                avail = n - pos
                for f in sorted(cand, reverse=True):
                    if f > avail:
                        continue
                    hh = prefix_block_hash(h, ids[pos:pos + f])
                    if self._tier.has(hh):
                        hit = (hh, f)
                        break
            if hit is None:
                break
            hh, f = hit
            nb = self._promote_entry(hh)
            if nb is None:
                break          # pool full — serve what promoted so far
            if nb > 0:
                blocks += 1
                nbytes += nb
                if collect is not None:
                    collect.append(hh)
            use = min(f, max_match - pos)
            promoted_tokens += use
            pos += use
            if f < self.block_size or use < f:
                break
            h = hh
        if blocks:
            cb = self.on_tier_event
            if cb is not None:
                cb("tier_promote", blocks=blocks,
                   tokens=promoted_tokens, bytes=nbytes,
                   dur_s=time.perf_counter() - t0,
                   overlapped=bool(overlapped))
        if promoted_tokens:
            self._tier_hit_tokens += promoted_tokens
            if _metrics.enabled():
                _m_tier_hit_tokens.labels(pool=self._name).inc(
                    promoted_tokens)
            self._push_gauges()
        return promoted_tokens

    def prefetch_promote(self, ids, limit_blocks=None):
        """Tier prefetch-ahead (serving round): promote the tiered
        chain tail for `ids` NOW, while the current round's dispatch
        computes, so a later `attach_prefix` for the same stream finds
        the chain already device-resident and pays no promotion wall
        time.  The `_tier_install` writes dispatch asynchronously —
        host→device copies overlap whatever the device is running.
        `limit_blocks` caps the device blocks one call may consume.
        Returns (hashes, tokens, bytes) of what was actually promoted;
        content-identical to the synchronous attach-time promote (the
        same MOVE-semantics walk), so a prefetch that never lands is
        only a wasted copy, never a wrong one."""
        ids = np.asarray(ids).reshape(-1)
        hashes: list = []
        before = self._tier_bytes_in
        tokens = self._promote_for(
            ids, int(ids.size) - 1, limit_blocks=limit_blocks,
            overlapped=True, collect=hashes)
        return hashes, tokens, self._tier_bytes_in - before

    def device_resident_count(self, hashes):
        """How many of `hashes` are device-index-resident right now —
        the prefetch settlement probe (hit = a prefetched block still
        resident when its session is admitted)."""
        return sum(1 for h in hashes if h in self._index)

    def _drop_entry(self, h):
        block, fill, parent = self._index.pop(h)
        ents = self._block_entries.get(block)
        if ents is not None:
            ents.discard(h)
            if not ents:
                del self._block_entries[block]
        fills = self._child_fills.get(parent)
        if fills is not None:
            left = fills.get(fill, 1) - 1
            if left > 0:
                fills[fill] = left
            else:
                fills.pop(fill, None)
                if not fills:
                    del self._child_fills[parent]

    def _push_gauges(self):
        if not _metrics.enabled():  # keep the hot path one branch
            return
        p = self._name
        used = self.num_blocks - 1 - len(self._free) - len(self._retained)
        held = sum(self._lens.values())
        _m_used_blocks.labels(pool=p, tier="device").set(used)
        _m_free_blocks.labels(pool=p, tier="device").set(len(self._free))
        _m_retained_blocks.labels(pool=p,
                                  tier="device").set(len(self._retained))
        if self._tier is not None:
            t = self._tier
            _m_used_blocks.labels(pool=p, tier="host").set(0)
            _m_free_blocks.labels(pool=p, tier="host").set(
                max(0, t.capacity_blocks - len(t)))
            _m_retained_blocks.labels(pool=p, tier="host").set(len(t))
        _m_sequences.labels(pool=p).set(len(self._tables))
        _m_utilization.labels(pool=p).set(held / (self.capacity_tokens
                                                  or 1))
        _m_block_fill.labels(pool=p).set(
            held / ((used * self.block_size) or 1))
        _m_pool_bytes.labels(pool=p, shard="all").set(
            self.pool_bytes_total)
        _m_bytes_per_token.labels(pool=p, shard="all").set(
            self.bytes_per_token)
        if self._shard_count > 1:
            # per-shard slice: the pool arrays shard evenly over the
            # mesh (heads over tp, blocks over dp), so each device
            # holds 1/n of the bytes — the per-HBM number
            per = self.pool_bytes_total / self._shard_count
            per_tok = self.bytes_per_token / self._shard_count
            for s in range(self._shard_count):
                _m_pool_bytes.labels(pool=p, shard=str(s)).set(per)
                _m_bytes_per_token.labels(pool=p,
                                          shard=str(s)).set(per_tok)

    def allocate(self, seq_id, num_tokens):
        """Start a new sequence holding `num_tokens` tokens; returns its
        block table. Raises BlockPoolExhausted without side effects.
        (Thin wrapper over `ensure_many` — every create/grow path shares
        its bookkeeping so the pool invariants live in one place.)"""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already allocated")
        self.ensure_many([(seq_id, num_tokens)])
        return list(self._tables[seq_id])

    def ensure(self, seq_id, num_tokens):
        """Grow `seq_id` so positions [0, num_tokens) have backing blocks
        (length is also advanced to num_tokens if it grew)."""
        self._get_table(seq_id, "ensure")  # descriptive unknown-seq error
        self.ensure_many([(seq_id, num_tokens)])
        return list(self._tables[seq_id])

    def ensure_many(self, updates):
        """Bulk multi-sequence allocation: atomically create-or-grow
        several sequences so each covers its requested token count.
        `updates`: iterable of (seq_id, num_tokens). Either every
        sequence ends up covered or — when the pool can't hold the
        TOTAL demand even after reclaiming every retained block —
        BlockPoolExhausted is raised with NO side effects. One call
        serves a whole packed prefill chunk plan
        (inference/serving.py), so a mid-plan exhaustion can never
        leave half the chunk's sequences grown."""
        updates = [(s, int(n)) for s, n in updates]
        need = []
        total = 0
        for seq_id, n in updates:
            grow = blocks_for(n, self.block_size) \
                - len(self._tables.get(seq_id, ()))
            need.append(max(0, grow))
            total += max(0, grow)
        if total > len(self._free) + len(self._retained):
            _m_alloc_failures.labels(pool=self._name).inc()
            raise BlockPoolExhausted(
                f"need {total} blocks across {len(updates)} sequences, "
                f"only {len(self._free)} free + {len(self._retained)} "
                f"reclaimable (pool {self.num_blocks - 1})",
                needed=total, available=self.available_block_count)
        for (seq_id, n), grow in zip(updates, need):
            table = self._tables.setdefault(seq_id, [])
            if grow:
                table.extend(self._take_blocks(
                    grow, owner=self._seq_owner.get(seq_id)))
            self._lens[seq_id] = max(self._lens.get(seq_id, 0), n)
        self.maybe_demote()    # allocation raised pool pressure
        self._push_gauges()

    def append(self, seq_id, n=1):
        """Reserve room for `n` more tokens; returns the (possibly grown)
        block table."""
        return self.ensure(seq_id, self.seq_len(seq_id) + int(n))

    def free(self, seq_id):
        """Release a sequence's blocks (refcount-aware: shared prefix
        blocks stay live for their other referents, indexed blocks park
        in the LRU retention list); returns how many table entries were
        released."""
        table = self._get_table(seq_id, "free")
        del self._tables[seq_id]
        del self._lens[seq_id]
        self._seq_owner.pop(seq_id, None)
        for b in reversed(table):
            self._release_block(b)
        self.maybe_demote()    # retention may have grown past watermark
        self._push_gauges()
        return len(table)

    def truncate_seq(self, seq_id, new_len):
        """Roll a sequence back to `new_len` live tokens — the rollback
        half of speculative decoding (rejected draft positions leave the
        cache) and a general shrink primitive. Tail blocks no longer
        covering any live position are released refcount-aware: shared
        prefix blocks stay live for their other referents, blocks the
        index still names park in the LRU retention list. Rows
        >= new_len inside the kept tail block become dead — masking is
        by length everywhere, and later writes simply overwrite them.

        Safe under prefix sharing/CoW because of two standing
        invariants: `publish_prefix` only ever indexes PROMPT tokens, so
        a sequence's speculative tail rows are never entry-claimed; and
        rows >= an entry's fill are outside the immutable region, so
        rewriting them after a rollback needs no copy. Callers that
        truncate below a published/attached region they intend to
        rewrite must route the next write through `prepare_write` (the
        serving engine never truncates below prompt_len + 1).

        Returns the number of table entries released."""
        table = self._get_table(seq_id, "truncate_seq")
        new_len = int(new_len)
        cur = self._lens[seq_id]
        if new_len < 0 or new_len > cur:
            raise ValueError(
                f"cannot truncate sequence {seq_id!r} to {new_len}: "
                f"live length is {cur} (truncate_seq only rolls back)")
        keep = blocks_for(new_len, self.block_size)
        dropped = table[keep:]
        del table[keep:]
        self._lens[seq_id] = new_len
        for b in reversed(dropped):
            self._release_block(b)
        self.maybe_demote()
        self._push_gauges()
        return len(dropped)

    def seq_len(self, seq_id):
        try:
            return self._lens[seq_id]
        except KeyError:
            raise KeyError(
                f"unknown sequence {seq_id!r} in seq_len(): not "
                f"allocated in this cache") from None

    def block_table(self, seq_id):
        return list(self._get_table(seq_id, "block_table"))

    def blocks_held(self, seq_id):
        """Blocks currently backing seq_id (0 if not yet allocated)."""
        return len(self._tables.get(seq_id, ()))

    def has_seq(self, seq_id):
        """Whether seq_id currently owns a block table (the public form
        of the `seq in cache._tables` probe exception handlers need)."""
        return seq_id in self._tables

    # ---- prefix caching (round 9) -------------------------------------
    def _match_chain(self, ids, max_match):
        """Walk the content index along `ids`: the longest chain of
        cached blocks covering a prefix of ids[:max_match]. Returns
        (blocks, fills, pos) — fills[i] is how many tokens block i
        contributes (== block_size for interior blocks; the final
        block may be a partial-tail entry or capped by max_match,
        either of which ends the chain). READ-ONLY: no refcounts,
        counters or gauges move — `attach_prefix` claims on top of
        this, `match_prefix_len`/`export_prefix` (fleet round) just
        read."""
        matched: list[int] = []
        fills: list[int] = []
        h = ROOT_HASH
        pos = 0
        n = int(ids.size)
        while pos < max_match:
            cand = self._child_fills.get(h)
            if not cand:
                break
            avail = n - pos            # tokens we can hash from here
            hit = None
            for f in sorted(cand, reverse=True):  # longest match first
                if f > avail:
                    continue
                hh = prefix_block_hash(h, ids[pos:pos + f])
                ent = self._index.get(hh)
                if ent is not None:
                    hit = (hh, ent, f)
                    break
            if hit is None:
                break
            hh, (block, _fill, _parent), f = hit
            use = min(f, max_match - pos)
            matched.append(block)
            fills.append(use)
            pos += use
            if f < self.block_size or use < f:
                break                  # partial block ends the chain
            h = hh
        return matched, fills, pos

    def match_prefix_len(self, token_ids):
        """Read-only longest-cached-prefix probe: how many tokens of
        `token_ids` an `attach_prefix` with the same stream would
        serve from cache RIGHT NOW (same len-1 cap — the last token is
        always recomputed), with zero side effects: nothing is
        claimed, no hit/lookup counter moves. The fleet router's
        prefix-aware placement signal (route a request to the replica
        already holding its longest prefix).

        With a host tier attached the probe is no longer free: a chain
        continuing into the tier is PROMOTED first (prefetch-on-match
        — by the time the admission decision lands the blocks are
        device-resident), so the returned length counts tiered
        content too."""
        ids = np.asarray(token_ids).reshape(-1)
        self._promote_for(ids, int(ids.size) - 1)
        return self._match_chain(ids, int(ids.size) - 1)[2]

    def attach_prefix(self, seq_id, token_ids):
        """Content-addressed prefix attach: find the longest chain of
        cached blocks matching `token_ids` and start `seq_id` on them by
        copying table entries (refcount bump — no compute, no device
        work). Returns the number of cached tokens (0 = no match, and
        the sequence is NOT created: the caller's normal allocate path
        applies).

        At most `len(token_ids) - 1` tokens ever match: the final
        prompt token is always left to the prefill dispatch, which
        needs at least one real position to sample token 0 from. The
        match may end mid-block (the index also holds the tail partial
        block of every published prompt) — the claimed rows of that
        block are shared, and the sequence's first write into it goes
        through `prepare_write` (copy-on-write)."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already allocated")
        ids = np.asarray(token_ids).reshape(-1)
        n = int(ids.size)
        max_match = n - 1
        self._prefix_lookups += 1
        self._lookup_tokens += max(0, max_match)
        if _metrics.enabled():
            _m_prefix_lookups.labels(pool=self._name).inc()
            _m_prefix_lookup_tokens.labels(pool=self._name).inc(
                max(0, max_match))
        self._promote_for(ids, max_match)  # prefetch tiered chain tail
        matched, _fills, pos = self._match_chain(ids, max_match)
        if pos == 0:
            return 0
        for b in matched:              # claim the chain
            r = self._ref.get(b, 0)
            if r == 0:                 # parked in retention: revive
                self._retained.pop(b, None)
            self._ref[b] = r + 1
        self._tables[seq_id] = matched
        self._lens[seq_id] = pos
        self._prefix_hits += 1
        self._hit_tokens += pos
        if _metrics.enabled():
            _m_prefix_hits.labels(pool=self._name).inc()
            _m_prefix_hit_tokens.labels(pool=self._name).inc(pos)
        self._push_gauges()
        return pos

    def publish_prefix(self, seq_id, token_ids):
        """Index `seq_id`'s blocks under their rolling content hashes so
        later sequences can attach them. Call AFTER the K/V for
        `token_ids` has actually been written to the device arrays
        (i.e. once the prompt is fully prefilled). Full blocks chain;
        the tail partial block (if any) is indexed with its fill.
        Hashes that already exist keep their original block (first
        publisher wins)."""
        table = self._get_table(seq_id, "publish_prefix")
        ids = np.asarray(token_ids).reshape(-1)
        n = int(ids.size)
        if n > self._lens[seq_id]:
            raise ValueError(
                f"cannot publish {n} tokens for sequence {seq_id!r}: "
                f"only {self._lens[seq_id]} are live")
        bs = self.block_size
        h = ROOT_HASH
        nfull = n // bs
        for i in range(nfull):
            hh = prefix_block_hash(h, ids[i * bs:(i + 1) * bs])
            if hh not in self._index:
                self._register_entry(hh, table[i], bs, h)
            h = hh
        fill = n - nfull * bs
        if fill:
            hh = prefix_block_hash(h, ids[nfull * bs:])
            if hh not in self._index:
                self._register_entry(hh, table[nfull], fill, h)

    def prepare_write(self, seq_id, pos):
        """Make the block holding position `pos` exclusively writable
        for `seq_id` before a dispatch writes K/V there. No-op for
        fresh blocks. If the block is shared (refcount > 1) or the
        prefix index claims rows at/after `pos`, the block is COPIED
        on the device and the table entry swapped (copy-on-write) —
        every other referent and the index keep the original. When the
        pool has no spare block and the sequence is the sole referent,
        the blocking index entries are dropped instead and the write
        proceeds in place (no copy needed). Returns True iff a CoW
        copy happened."""
        import jax
        import jax.numpy as jnp

        table = self._get_table(seq_id, "prepare_write")
        bi = int(pos) // self.block_size
        if bi >= len(table):
            return False               # growth region: nothing cached
        block = table[bi]
        row = int(pos) % self.block_size
        shared = self._ref.get(block, 0) > 1
        blocking = [h for h in self._block_entries.get(block, ())
                    if self._index[h][1] > row]
        if not shared and not blocking:
            return False               # exclusive + unclaimed rows
        if self.available_block_count >= 1:
            new = self._take_blocks(
                1, owner=self._seq_owner.get(seq_id))[0]
            fn = _copy_block_fn(jax.default_backend() not in ("cpu",))
            self.k_blocks, self.v_blocks = fn(
                self.k_blocks, self.v_blocks, jnp.int32(block),
                jnp.int32(new))
            table[bi] = new
            self._release_block(block)
            self._cow_copies += 1
            _m_prefix_cow.labels(pool=self._name).inc()
            self._push_gauges()
            return True
        if shared:
            _m_alloc_failures.labels(pool=self._name).inc()
            raise BlockPoolExhausted(
                f"copy-on-write for sequence {seq_id!r} at position "
                f"{pos} needs 1 block, pool exhausted "
                f"(pool {self.num_blocks - 1})", needed=1, available=0)
        for h in blocking:             # sole referent: cede the cache
            self._drop_entry(h)        # entries, write in place
        return False

    def swap_out_seq(self, seq_id, token_ids):
        """Preemption swap-out hook (round 12): publish the sequence's
        LIVE K/V prefix into the content index, then release its blocks.
        `token_ids` is the full known token stream (prompt + generated);
        only the first `seq_len(seq_id)` of them have K/V written, and
        exactly those are indexed — the freed blocks park in the LRU
        retention list instead of being scrubbed, so a later
        `attach_prefix` with the same stream resumes the sequence with
        near-zero recompute (one token) unless pool pressure reclaimed
        the blocks in between. Returns the number of tokens published
        (0 for an empty sequence — nothing to index)."""
        live = self.seq_len(seq_id)
        ids = np.asarray(token_ids).reshape(-1)
        if live > ids.size:
            raise ValueError(
                f"swap_out_seq of {seq_id!r}: {live} live tokens but "
                f"only {ids.size} token ids supplied")
        if live > 0:
            self.publish_prefix(seq_id, ids[:live])
        self.free(seq_id)
        return live

    # ---- cross-pool migration (fleet round) ---------------------------
    def export_prefix(self, token_ids):
        """Serialize the longest cached chain matching `token_ids` for
        migration to ANOTHER pool: host-side numpy copies of the block
        contents (int8 codes + scales travel together under a
        quantized pool) plus per-block fills and the pool layout.
        Returns None when the index covers nothing. The inverse,
        `import_prefix`, re-publishes the chain into a
        layout-identical pool so a later `attach_prefix` there resumes
        the session with zero prefill recompute. Read-only on the
        DEVICE chain — but a chain continuing into the host tier is
        promoted first, so a partially-tiered session migrates whole
        (the payload always carries the longest recoverable chain)."""
        import jax

        ids = np.asarray(token_ids).reshape(-1)
        self._promote_for(ids, int(ids.size))
        blocks, fills, pos = self._match_chain(ids, int(ids.size))
        if pos == 0:
            return None

        def grab(arr, b):
            return jax.tree.map(lambda a: np.asarray(a[:, b]), arr)

        return {
            "tokens": [int(t) for t in ids[:pos]],
            "block_size": self.block_size,
            "kv_dtype": self.kv_dtype,
            "num_layers": self.num_layers,
            "num_heads": self.num_heads,
            "head_dim": self.head_dim,
            "fills": list(fills),
            "k": [grab(self.k_blocks, b) for b in blocks],
            "v": [grab(self.v_blocks, b) for b in blocks],
        }

    def import_prefix(self, payload, owner=None):
        """Install an `export_prefix` payload into THIS pool: allocate
        blocks, write the K/V contents on device, and register the
        chain in the content index exactly as `publish_prefix` would
        have — the imported blocks park in the LRU retention list
        (refcount 0, indexed) until an `attach_prefix` claims them.
        First publisher wins: a chain entry whose hash this pool
        already holds keeps the existing block and the redundant
        import block returns to the free list. Raises
        BlockPoolExhausted when the pool cannot cover the chain (the
        caller falls back to journal-replay resume) and ValueError on
        a layout mismatch. `owner` is the attribution (tenant, rid)
        charged for the imported blocks (migration target side).
        Returns the number of tokens published."""
        import jax

        for field in ("block_size", "kv_dtype", "num_layers",
                      "num_heads", "head_dim"):
            if payload[field] != getattr(self, field):
                raise ValueError(
                    f"import_prefix layout mismatch on {field}: "
                    f"payload has {payload[field]!r}, pool has "
                    f"{getattr(self, field)!r}")
        ids = np.asarray(payload["tokens"], np.int64).reshape(-1)
        fills = [int(f) for f in payload["fills"]]
        if not fills or int(ids.size) != sum(fills):
            raise ValueError(
                f"import_prefix payload inconsistent: {ids.size} "
                f"tokens vs fills {fills}")
        new_blocks = self._take_blocks(len(fills),
                                       owner=owner)  # may raise
        for b, pk, pv in zip(new_blocks, payload["k"], payload["v"]):
            self.k_blocks = jax.tree.map(
                lambda a, p, _b=b: a.at[:, _b].set(p),
                self.k_blocks, pk)
            self.v_blocks = jax.tree.map(
                lambda a, p, _b=b: a.at[:, _b].set(p),
                self.v_blocks, pv)
        h = ROOT_HASH
        pos = 0
        for b, f in zip(new_blocks, fills):
            hh = prefix_block_hash(h, ids[pos:pos + f])
            if hh not in self._index:
                self._register_entry(hh, b, f, h)
            # release the construction refcount: indexed blocks park
            # in retention, an already-published duplicate frees
            # outright (first publisher wins)
            self._release_block(b)
            pos += f
            if f < self.block_size:
                break                  # partial tail ends the chain
            h = hh
        self.maybe_demote()
        self._push_gauges()
        return pos

    def table_array(self, seq_ids, width=None):
        """Dense int32 [len(seq_ids), width] block-table matrix for the
        jitted step; unused entries point at trash block 0. A seq_id of
        None yields an all-trash row (an idle server slot)."""
        rows = [self._tables.get(s, []) if s is not None else []
                for s in seq_ids]
        if width is None:
            width = max((len(r) for r in rows), default=1) or 1
        out = np.zeros((len(rows), int(width)), np.int32)
        for i, r in enumerate(rows):
            if len(r) > width:
                raise ValueError(f"block table of {seq_ids[i]!r} "
                                 f"({len(r)}) exceeds width {width}")
            out[i, :len(r)] = r
        return out

    def swap_arrays(self, k_blocks, v_blocks):
        """Install the updated device arrays a jitted prefill/step
        returned (the functional write-back half of the cycle)."""
        self.k_blocks = k_blocks
        self.v_blocks = v_blocks

    def block_fill(self):
        """Live tokens / allocated block capacity — the
        `stats()["block_fill"]` value without building the full stats
        dict (both serving engines sample it every decode round)."""
        used = self.num_blocks - 1 - len(self._free) - len(self._retained)
        return sum(self._lens.values()) / ((used * self.block_size) or 1)

    def headroom(self):
        """Lightweight capacity view for the pressure sampler (ISSUE
        17): host-side counters only — no device-array touches, safe
        at per-round sampling rates."""
        held = sum(self._lens.values())
        used = self.num_blocks - 1 - len(self._free) - len(self._retained)
        return {
            "num_blocks": self.num_blocks - 1,
            "used_blocks": used,
            "free_blocks": len(self._free),
            "retained_blocks": len(self._retained),
            "available_blocks": len(self._free) + len(self._retained),
            "sequences": len(self._tables),
            "held_tokens": held,
            "utilization": held / (self.capacity_tokens or 1),
        }

    def stats(self):
        used = self.num_blocks - 1 - len(self._free) - len(self._retained)
        held = sum(self._lens.values())
        return {
            "block_size": self.block_size,
            "num_blocks": self.num_blocks - 1,  # usable (trash excluded)
            # HBM accounting (quantized-serving round): dtype-aware
            # byte cost of the pool arrays, so the int8 halving shows
            # up in stats and dashboards, not just in config
            "kv_dtype": self.stats_kv_dtype(),
            "pool_bytes_total": self.pool_bytes_total,
            "pool_bytes_per_token": self.bytes_per_token,
            # device shards the pool arrays are placed over (1 =
            # unsharded); per-shard bytes are what one HBM must hold
            "shards": self._shard_count,
            "pool_bytes_per_shard": (self.pool_bytes_total
                                     / self._shard_count),
            "scale_bytes": self.scale_bytes,
            "used_blocks": used,
            "free_blocks": len(self._free),
            "retained_blocks": len(self._retained),
            "peak_retained_blocks": self._peak_retained,
            "peak_used_blocks": self._peak_blocks,
            "sequences": len(self._tables),
            "held_tokens": held,
            # fraction of usable pool tokens occupied by live tokens
            # (per-sequence lengths: shared prefix blocks count once
            # per referent, so >1.0 is possible under heavy sharing)
            "utilization": held / (self.capacity_tokens or 1),
            # live tokens per allocated slot (internal fragmentation:
            # 1.0 = every allocated block byte holds a real token;
            # sharing can push it above 1.0)
            "block_fill": held / ((used * self.block_size) or 1),
            "prefix_cache": {
                "index_entries": len(self._index),
                "lookups": self._prefix_lookups,
                "hits": self._prefix_hits,
                "hit_tokens": self._hit_tokens,
                "lookup_tokens": self._lookup_tokens,
                # matched fraction of matchable prompt tokens (the
                # last token of every prompt is never matchable)
                "hit_rate": self._hit_tokens / (self._lookup_tokens
                                                or 1),
                "evictions": self._evictions,
                "cow_copies": self._cow_copies,
            },
            # host-RAM tier block: zeroed-when-disabled, so the schema
            # is identical with and without a tier attached
            "tier": self._tier_stats(),
        }

    def _tier_stats(self):
        from .kv_tier import disabled_tier_stats

        if self._tier is None:
            return disabled_tier_stats()
        s = self._tier.stats()
        return {
            "enabled": True,
            "capacity_blocks": s["capacity_blocks"],
            "tiered_blocks": s["tiered_blocks"],
            "tiered_tokens": s["tiered_tokens"],
            "bytes_resident": s["bytes_resident"],
            "demotions": self._tier_demotions,
            "promotions": self._tier_promotions,
            "evictions": s["evictions"],
            "bytes_out": self._tier_bytes_out,
            "bytes_in": self._tier_bytes_in,
            "hit_tokens": self._tier_hit_tokens,
        }
