"""Paged KV cache — block-pool cache for the continuous-batching server.

Reference direction: Ragged Paged Attention (arXiv:2604.15464) — the
TPU-native answer to the static-cache serving loop. Instead of one
contiguous [B, S_max] cache slab per batch (which pins every slot to the
longest possible sequence), K/V live in a pool of fixed-size blocks:

    k_blocks, v_blocks: [L, num_blocks, block_size, H, Dh]

Each sequence owns an ordered *block table* (a list of block ids); token
`t` of a sequence lives at (table[t // block_size], t % block_size).
Attention gathers keys by block table, masked by the sequence's true
length — no pad-token-value matching anywhere, so a prompt that
legitimately contains `pad_token_id` can never be corrupted.

Block 0 is a reserved *trash* block: it is never allocated, and jitted
writers route masked-out lanes (padding tail of a prefill bucket,
inactive decode slots) into it so a scatter always has a legal target.
Block tables are padded with 0 for the same reason — gathered trash
positions are masked by length before the softmax.

The pool itself is host-side bookkeeping (allocate/ensure/free on Python
ints); the device arrays are functional — jitted prefill/step functions
take them as inputs and return the updated arrays, and the cache swaps
them in via `swap_arrays`.
"""
from __future__ import annotations

from ..observability import metrics as _metrics

# Pool telemetry (ISSUE 2): pushed on every alloc/grow/free, one bool
# check each while PADDLE_TPU_TELEMETRY is off. With several live
# caches the gauges reflect the most recently mutated pool (serving
# runs exactly one).
_m_used_blocks = _metrics.gauge(
    "kv_pool_used_blocks", "allocated blocks (trash block excluded)")
_m_free_blocks = _metrics.gauge(
    "kv_pool_free_blocks", "blocks available for allocation")
_m_utilization = _metrics.gauge(
    "kv_pool_utilization", "live tokens / usable pool tokens")
_m_block_fill = _metrics.gauge(
    "kv_pool_block_fill", "live tokens / allocated block capacity "
    "(1.0 = no internal fragmentation)")
_m_sequences = _metrics.gauge(
    "kv_pool_sequences", "sequences holding blocks")
_m_alloc_failures = _metrics.counter(
    "kv_pool_alloc_failures_total",
    "allocations refused because the pool was exhausted")


class BlockPoolExhausted(RuntimeError):
    """Raised when an allocation needs more free blocks than the pool has."""


def blocks_for(num_tokens: int, block_size: int) -> int:
    """Blocks needed to hold `num_tokens` tokens."""
    return max(0, -(-int(num_tokens) // int(block_size)))


class PagedKVCache:
    """Block-pool KV cache: fixed-size blocks, per-sequence block tables.

    num_layers/num_heads/head_dim: transformer shape (GPT-2 layout).
    block_size: tokens per block. 128 keeps the Pallas ragged-decode
        kernel's lane alignment on TPU; smaller (8/16) wastes less on CPU
        smokes and short sequences.
    num_blocks: pool size INCLUDING the reserved trash block 0, so the
        usable capacity is (num_blocks - 1) * block_size tokens.
    """

    def __init__(self, num_layers, num_heads, head_dim, *, block_size=128,
                 num_blocks=64, dtype=None):
        import jax.numpy as jnp

        if num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is the "
                             "reserved trash block)")
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        dt = jnp.float32 if dtype is None else dtype
        shape = (self.num_layers, self.num_blocks, self.block_size,
                 self.num_heads, self.head_dim)
        self.k_blocks = jnp.zeros(shape, dt)
        self.v_blocks = jnp.zeros(shape, dt)
        # block 0 reserved: free list starts at 1
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._tables: dict[object, list[int]] = {}
        self._lens: dict[object, int] = {}
        self._peak_blocks = 0

    # ---- pool bookkeeping (host-side) ---------------------------------
    @property
    def free_block_count(self):
        return len(self._free)

    @property
    def capacity_tokens(self):
        return (self.num_blocks - 1) * self.block_size

    def _take_blocks(self, n):
        if n > len(self._free):
            _m_alloc_failures.inc()
            raise BlockPoolExhausted(
                f"need {n} blocks, only {len(self._free)} free "
                f"(pool {self.num_blocks - 1})")
        taken = [self._free.pop() for _ in range(n)]
        used = self.num_blocks - 1 - len(self._free)
        self._peak_blocks = max(self._peak_blocks, used)
        return taken

    def _push_gauges(self):
        if not _metrics.enabled():  # keep the hot path one branch
            return
        used = self.num_blocks - 1 - len(self._free)
        held = sum(self._lens.values())
        _m_used_blocks.set(used)
        _m_free_blocks.set(len(self._free))
        _m_sequences.set(len(self._tables))
        _m_utilization.set(held / (self.capacity_tokens or 1))
        _m_block_fill.set(held / ((used * self.block_size) or 1))

    def allocate(self, seq_id, num_tokens):
        """Start a new sequence holding `num_tokens` tokens; returns its
        block table. Raises BlockPoolExhausted without side effects."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already allocated")
        table = self._take_blocks(blocks_for(num_tokens, self.block_size))
        self._tables[seq_id] = table
        self._lens[seq_id] = int(num_tokens)
        self._push_gauges()
        return list(table)

    def ensure(self, seq_id, num_tokens):
        """Grow `seq_id` so positions [0, num_tokens) have backing blocks
        (length is also advanced to num_tokens if it grew)."""
        table = self._tables[seq_id]
        need = blocks_for(num_tokens, self.block_size) - len(table)
        if need > 0:
            table.extend(self._take_blocks(need))
        self._lens[seq_id] = max(self._lens[seq_id], int(num_tokens))
        self._push_gauges()
        return list(table)

    def ensure_many(self, updates):
        """Bulk multi-sequence allocation: atomically create-or-grow
        several sequences so each covers its requested token count.
        `updates`: iterable of (seq_id, num_tokens). Either every
        sequence ends up covered or — when the pool can't hold the
        TOTAL demand — BlockPoolExhausted is raised with NO side
        effects. One call serves a whole packed prefill chunk plan
        (inference/serving.py), so a mid-plan exhaustion can never
        leave half the chunk's sequences grown."""
        updates = [(s, int(n)) for s, n in updates]
        need = []
        total = 0
        for seq_id, n in updates:
            grow = blocks_for(n, self.block_size) \
                - len(self._tables.get(seq_id, ()))
            need.append(max(0, grow))
            total += max(0, grow)
        if total > len(self._free):
            _m_alloc_failures.inc()
            raise BlockPoolExhausted(
                f"need {total} blocks across {len(updates)} sequences, "
                f"only {len(self._free)} free "
                f"(pool {self.num_blocks - 1})")
        for (seq_id, n), grow in zip(updates, need):
            table = self._tables.setdefault(seq_id, [])
            if grow:
                table.extend(self._take_blocks(grow))
            self._lens[seq_id] = max(self._lens.get(seq_id, 0), n)
        self._push_gauges()

    def append(self, seq_id, n=1):
        """Reserve room for `n` more tokens; returns the (possibly grown)
        block table."""
        return self.ensure(seq_id, self._lens[seq_id] + int(n))

    def free(self, seq_id):
        """Return a sequence's blocks to the pool; returns how many."""
        table = self._tables.pop(seq_id)
        del self._lens[seq_id]
        self._free.extend(reversed(table))
        self._push_gauges()
        return len(table)

    def seq_len(self, seq_id):
        return self._lens[seq_id]

    def block_table(self, seq_id):
        return list(self._tables[seq_id])

    def blocks_held(self, seq_id):
        """Blocks currently backing seq_id (0 if not yet allocated)."""
        return len(self._tables.get(seq_id, ()))

    def has_seq(self, seq_id):
        """Whether seq_id currently owns a block table (the public form
        of the `seq in cache._tables` probe exception handlers need)."""
        return seq_id in self._tables

    def table_array(self, seq_ids, width=None):
        """Dense int32 [len(seq_ids), width] block-table matrix for the
        jitted step; unused entries point at trash block 0. A seq_id of
        None yields an all-trash row (an idle server slot)."""
        import numpy as np

        rows = [self._tables.get(s, []) if s is not None else []
                for s in seq_ids]
        if width is None:
            width = max((len(r) for r in rows), default=1) or 1
        out = np.zeros((len(rows), int(width)), np.int32)
        for i, r in enumerate(rows):
            if len(r) > width:
                raise ValueError(f"block table of {seq_ids[i]!r} "
                                 f"({len(r)}) exceeds width {width}")
            out[i, :len(r)] = r
        return out

    def swap_arrays(self, k_blocks, v_blocks):
        """Install the updated device arrays a jitted prefill/step
        returned (the functional write-back half of the cycle)."""
        self.k_blocks = k_blocks
        self.v_blocks = v_blocks

    def stats(self):
        used = self.num_blocks - 1 - len(self._free)
        held = sum(self._lens.values())
        return {
            "block_size": self.block_size,
            "num_blocks": self.num_blocks - 1,  # usable (trash excluded)
            "used_blocks": used,
            "free_blocks": len(self._free),
            "peak_used_blocks": self._peak_blocks,
            "sequences": len(self._tables),
            "held_tokens": held,
            # fraction of usable pool tokens occupied by live tokens
            "utilization": held / (self.capacity_tokens or 1),
            # live tokens per allocated slot (internal fragmentation:
            # 1.0 = every allocated block byte holds a real token)
            "block_fill": held / ((used * self.block_size) or 1),
        }
