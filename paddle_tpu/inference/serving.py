"""Request batching over the exported decode artifact (VERDICT r4 #7).

Reference: paddle/fluid/inference/api/analysis_predictor.cc — the
reference's inference engine exists to serve under concurrency
(zero-copy tensors, predictor pools, thread-safe clone). The rebuild's
deployment artifact is the StableHLO decode program from
`models.gpt2.export_generator` (fixed [B, prompt_len] batch); this
module adds the piece that turns the measured W8A16/int8-KV decode wins
into served throughput: a thread-safe request queue and a batcher loop
that assembles dynamic batches, pads the tail, runs the program, and
fans results back out to per-request futures with latency accounting.

    server = GenerationServer(jit.load(prefix), pad_token_id=0)
    server.start()
    fut = server.submit([12, 53, 99])        # any length <= prompt_len
    tokens = fut.result()                    # [prompt_len + new] int32
    print(server.stats())                    # throughput + p50/p99
    server.stop()

Batching policy: wait for the first request, then gather more until the
program's batch size B is full or `max_wait_ms` has elapsed; pad the
remainder by repeating the first row (a full-size program run costs the
same regardless — decode time is batch-invariant at fixed B).
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass

import numpy as np


@dataclass
class _Req:
    ids: np.ndarray
    future: Future
    t_submit: float
    padded: bool = False


class GenerationServer:
    """Dynamic-batching server over one compiled decode program.

    program: a TranslatedLayer from `paddle.jit.load(prefix)` of an
        `export_generator` artifact, or any callable
        (ids[B, P] int32, seed, temperature, eos, top_p, pad) -> [B, T].
    batch_size: the program's static B (inferred from the artifact's
        input spec when available).
    prompt_len: the program's static P (inferred likewise). Shorter
        prompts are LEFT-padded with pad_token_id (the program masks
        pads from attention and the output keeps the pad prefix).

    Pad caveat: the decode program detects padding by VALUE equality, so
    pad masking is only engaged for batches that contain a padded row;
    in such a mixed batch, a full-length prompt that legitimately
    contains pad_token_id gets those positions masked too — pick a pad
    id outside the prompt alphabet if prompts mix lengths.
    """

    def __init__(self, program, batch_size=None, prompt_len=None,
                 pad_token_id=0, max_wait_ms=5.0, temperature=0.0,
                 seed=0, eos_token_id=-1, top_p=1.0):
        self._program = program
        # export_generator artifacts record prompt_len and batch_size
        # (batch_size None = batch-polymorphic: the server picks its own)
        meta = getattr(program, "_meta", {}) or {}
        prompt_len = prompt_len or meta.get("prompt_len")
        batch_size = batch_size or meta.get("batch_size")
        if not batch_size and prompt_len and meta.get("batch_size", 0) \
                is None:
            batch_size = 8  # polymorphic artifact: serving default
        if not batch_size or not prompt_len:
            raise ValueError(
                "batch_size/prompt_len not given and not recorded in the "
                "artifact meta (re-export with models.gpt2."
                "export_generator, or pass them explicitly)")
        self.batch_size = int(batch_size)
        self.prompt_len = int(prompt_len)
        self.pad_token_id = int(pad_token_id)
        self.max_wait_ms = float(max_wait_ms)
        self._defaults = (np.uint32(seed), np.float32(temperature),
                          np.int32(eos_token_id), np.float32(top_p),
                          np.int32(pad_token_id))
        self._lock = threading.Condition()
        self._queue: list[_Req] = []
        self._stop = False
        self._thread = None
        # stats
        self._lat = []
        self._tokens_out = 0
        self._batches = 0
        self._batches_at_reset = 0
        self._rows = 0
        self._t0 = None

    # ---- client API ----------------------------------------------------
    def submit(self, ids):
        """Enqueue one prompt (list/array of ints, length <= prompt_len).
        Returns a Future resolving to the [prompt_len + new] int32 row."""
        ids = np.asarray(ids, np.int32).reshape(-1)
        if ids.size == 0 or ids.size > self.prompt_len:
            raise ValueError(
                f"prompt length {ids.size} not in [1, {self.prompt_len}]")
        row = np.full((self.prompt_len,), self.pad_token_id, np.int32)
        row[self.prompt_len - ids.size:] = ids  # LEFT padding
        req = _Req(ids=row, future=Future(), t_submit=time.perf_counter(),
                   padded=ids.size < self.prompt_len)
        with self._lock:
            if self._stop:
                raise RuntimeError("server stopped")
            self._queue.append(req)
            self._lock.notify()
        return req.future

    def start(self):
        if self._thread is not None:
            return self
        if self._stop:
            raise RuntimeError(
                "server was stopped; build a new GenerationServer")
        self._t0 = time.perf_counter()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        with self._lock:
            self._stop = True
            self._lock.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=60)
            self._thread = None
        with self._lock:
            for req in self._queue:  # fail, don't strand, late arrivals
                req.future.set_exception(RuntimeError("server stopped"))
            self._queue.clear()

    def reset_stats(self):
        """Zero the latency/throughput counters (benchmark windows); the
        batch counter keeps advancing so sampling seeds never repeat."""
        with self._lock:
            self._lat.clear()
            self._tokens_out = 0
            self._rows = 0
            self._batches_at_reset = self._batches
            self._t0 = time.perf_counter()

    def stats(self):
        """Throughput and latency of the current measurement WINDOW —
        everything since start() or the last reset_stats() call."""
        with self._lock:
            lat = sorted(self._lat)
            dt = (time.perf_counter() - self._t0) if self._t0 else 0.0
            n = len(lat)
            nb = self._batches - self._batches_at_reset
            pct = (lambda p: lat[min(n - 1, int(p * n))] if n else 0.0)
            return {
                "requests": n,
                "batches": nb,
                "batch_fill": self._rows / ((nb or 1) * self.batch_size),
                "new_tokens": self._tokens_out,
                "tokens_per_sec": self._tokens_out / dt if dt else 0.0,
                "p50_ms": pct(0.50) * 1e3,
                "p90_ms": pct(0.90) * 1e3,
                "p99_ms": pct(0.99) * 1e3,
                "wall_s": dt,
            }

    # ---- batcher loop --------------------------------------------------
    def _take_batch(self):
        """Block for the first request, then gather until full batch or
        the max_wait deadline. Returns [] on stop."""
        with self._lock:
            while not self._queue and not self._stop:
                self._lock.wait(timeout=0.1)
            if self._stop and not self._queue:
                return []
            deadline = time.perf_counter() + self.max_wait_ms * 1e-3
            while len(self._queue) < self.batch_size and not self._stop:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                self._lock.wait(timeout=remaining)
            batch = self._queue[:self.batch_size]
            del self._queue[:len(batch)]
            return batch

    def _loop(self):
        while True:
            batch = self._take_batch()
            if not batch:
                return
            rows = [r.ids for r in batch]
            while len(rows) < self.batch_size:  # pad: same device cost
                rows.append(rows[0])
            ids = np.stack(rows)
            # pad masking is VALUE-equality in the decode program: only
            # engage it when some row is actually padded, so full-length
            # prompts that legitimately contain pad_token_id aren't
            # masked at those positions
            defaults = list(self._defaults)
            if not any(r.padded for r in batch):
                defaults[-1] = np.int32(-1)
            # per-batch seed: with temperature > 0 a FIXED seed would
            # draw identical sampling noise for every batch (identical
            # prompts -> identical completions, forever)
            defaults[0] = np.uint32(
                (int(self._defaults[0]) + self._batches) & 0xFFFFFFFF)
            try:
                out = self._program(ids, *defaults)
                out = np.asarray(getattr(out, "numpy", lambda: out)())
            except Exception as e:  # noqa: BLE001 — fan the error out
                for r in batch:
                    r.future.set_exception(e)
                continue
            t_done = time.perf_counter()
            new_tokens = out.shape[1] - self.prompt_len
            with self._lock:
                self._batches += 1
                self._rows += len(batch)
                self._tokens_out += new_tokens * len(batch)
                for i, r in enumerate(batch):
                    self._lat.append(t_done - r.t_submit)
            for i, r in enumerate(batch):
                r.future.set_result(out[i])


def measure_offered_load(server, prompts, offered_rps, duration_s):
    """Drive `server` at a target request rate for `duration_s`; returns
    the server stats plus achieved rate. `prompts`: pool of int lists,
    cycled."""
    futs = []
    t0 = time.perf_counter()
    i = 0
    while time.perf_counter() - t0 < duration_s:
        target = t0 + i / offered_rps
        now = time.perf_counter()
        if now < target:
            time.sleep(target - now)
        futs.append(server.submit(prompts[i % len(prompts)]))
        i += 1
    t_submit_end = time.perf_counter()  # the OFFER window ends here —
    # draining the queue below must not dilute the achieved rate
    for f in futs:
        f.result(timeout=600)
    out = server.stats()
    out["offered_rps"] = offered_rps
    out["achieved_rps"] = i / (t_submit_end - t0)
    return out
