"""Request batching over the exported decode artifact (VERDICT r4 #7).

Reference: paddle/fluid/inference/api/analysis_predictor.cc — the
reference's inference engine exists to serve under concurrency
(zero-copy tensors, predictor pools, thread-safe clone). The rebuild's
deployment artifact is the StableHLO decode program from
`models.gpt2.export_generator` (fixed [B, prompt_len] batch); this
module adds the piece that turns the measured W8A16/int8-KV decode wins
into served throughput: a thread-safe request queue and a batcher loop
that assembles dynamic batches, pads the tail, runs the program, and
fans results back out to per-request futures with latency accounting.

    server = GenerationServer(jit.load(prefix), pad_token_id=0)
    server.start()
    fut = server.submit([12, 53, 99])        # any length <= prompt_len
    tokens = fut.result()                    # [prompt_len + new] int32
    print(server.stats())                    # throughput + p50/p99
    server.stop()

Batching policy: wait for the first request, then gather more until the
program's batch size B is full or `max_wait_ms` has elapsed; pad the
remainder by repeating the first row (a full-size program run costs the
same regardless — decode time is batch-invariant at fixed B).
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass

import numpy as np

from ..observability import compile_tracker as _compile_tracker
from ..observability import flight_recorder as _flight
from ..observability import log as _obs_log
from ..observability import metrics as _metrics
from ..observability import tracing as _tracing
from ..observability.attribution import (ResourceLedger,
                                         disabled_attribution_stats)
from ..observability.capacity import PressureSignals
from ..observability.slo import SLO, SLOEngine
from ..observability.trace_context import TraceContext
from ..reliability import (AdmissionShed, QuarantinedRequest,
                           RecoveryPolicy, RequestTimeout,
                           SessionJournal, resolve_fault_plan)
from ..sampling import SamplingParams
from .kv_cache import BlockPoolExhausted
from .kv_tier import payload_nbytes as _payload_nbytes

_logger = _obs_log.get_logger(__name__)

ENV_METRICS_PORT = "PADDLE_TPU_METRICS_PORT"

# Shared serving telemetry (ISSUE 2): near-zero cost while
# PADDLE_TPU_TELEMETRY is off — every update is one bool check.
_m_queue_depth = _metrics.gauge(
    "serving_queue_depth", "requests waiting for a batch/slot",
    labelnames=("server",))
_m_slots_busy = _metrics.gauge(
    "serving_slots_busy", "occupied decode slots (paged) / in-flight "
    "batch rows (dense)", labelnames=("server",))
_m_requests_done = _metrics.counter(
    "serving_requests_total", "requests completed",
    labelnames=("server",))
_m_request_latency = _metrics.histogram(
    "serving_request_latency_seconds", "submit -> future resolved",
    labelnames=("server",))
_m_ttft = _metrics.histogram(
    "serving_ttft_seconds", "submit -> first generated token (paged)")
_m_slot_releases = _metrics.counter(
    "serving_slot_releases_total", "paged slots freed, by why the "
    "request finished", labelnames=("reason",))
_m_slot_refills = _metrics.counter(
    "serving_slot_refills_total",
    "idle paged slots refilled from the queue mid-flight")
_m_itl = _metrics.histogram(
    "paddle_tpu_serving_itl_seconds",
    "inter-token latency per generated token (decode-dispatch gap "
    "amortized over the tokens it emitted, paged) — the metric the "
    "prefill_chunk_tokens knob is tuned against")
_m_prefill_dispatches = _metrics.counter(
    "serving_prefill_dispatches_total",
    "packed ragged prefill chunk dispatches (paged); an admission "
    "burst of N requests costs O(1) of these per decode round, not N")
_m_decode_stall = _metrics.histogram(
    "serving_decode_stall_seconds",
    "time in-flight decode slots stalled while a packed prefill chunk "
    "dispatch ran (bounded by the chunk token budget)")
_m_stop_reason = _metrics.counter(
    "serving_stop_reason_total",
    "finished requests by why generation stopped "
    "(eos | stop_token | stop_string | budget)",
    labelnames=("server", "reason"))
_m_sampling_fast = _metrics.counter(
    "serving_sampling_fast_path_dispatches_total",
    "decode dispatches that took the all-greedy fast path (no resident "
    "request samples: bare argmax, no sort/PRNG cost)")
_m_sampling_sampled = _metrics.counter(
    "serving_sampling_sampled_dispatches_total",
    "decode dispatches through the full vectorized sampling pipeline "
    "(>= 1 resident sampled request)")
# Speculative decoding (round 11): proposal/acceptance accounting.
_m_spec_proposed = _metrics.counter(
    "serving_spec_proposed_tokens_total",
    "draft tokens proposed by the drafter across all slots/rounds")
_m_spec_accepted = _metrics.counter(
    "serving_spec_accepted_tokens_total",
    "proposed draft tokens the packed verification accepted")
_m_spec_rolled_back = _metrics.counter(
    "serving_spec_rolled_back_tokens_total",
    "rejected draft positions rolled back out of the paged cache "
    "(PagedKVCache.truncate_seq)")
_m_spec_verify = _metrics.counter(
    "serving_spec_verify_dispatches_total",
    "packed verification dispatches (one per round scores every "
    "speculating slot's drafts)")
_m_spec_accept_rate = _metrics.histogram(
    "serving_spec_acceptance_rate",
    "per-slot per-round accepted/proposed draft fraction",
    buckets=(0.1, 0.25, 0.5, 0.75, 0.9, 1.0))
# Front door (round 12): preemption, SLO lanes, multi-tenant queueing.
_m_preemptions = _metrics.counter(
    "serving_preemptions_total",
    "slots evicted mid-flight to make room for a higher-priority "
    "admission (the victim's live K/V is published through the "
    "prefix-cache index when caching is on, then the request requeues)",
    labelnames=("reason",))
_m_resumes = _metrics.counter(
    "serving_preempt_resumes_total",
    "preempted requests re-admitted (resume = re-prefill of "
    "prompt + generated-so-far, served from the prefix cache when the "
    "swapped-out blocks survived retention)")
_m_preempt_cached = _metrics.counter(
    "serving_preempt_cached_tokens_total",
    "tokens of victim K/V published into the prefix-cache index at "
    "swap-out (the work preemption preserves instead of recomputing)")
_m_deadline_miss = _metrics.counter(
    "serving_deadline_misses_total",
    "requests whose first token landed after their TTFT deadline",
    labelnames=("lane",))
_m_deadline_overage = _metrics.histogram(
    "serving_deadline_overage_seconds",
    "by how much a missed TTFT deadline was missed (first token time "
    "minus deadline; only observed on misses)",
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0))
# Operations plane (ISSUE 10): goodput + health accounting.
_m_decoded = _metrics.counter(
    "serving_tokens_decoded_total",
    "generated-token positions computed on device (decode steps, "
    "verify positions, prefill token-0 samples, and preempt-resume "
    "re-prefill of already-generated tokens)")
_m_replayed = _metrics.counter(
    "serving_tokens_replayed_total",
    "decoded-token positions whose work was wasted: multi-step "
    "post-stop discards, verify positions truncated by a stop, and "
    "preempt-resume re-prefill of already-generated tokens")
_m_goodput = _metrics.gauge(
    "serving_goodput_ratio",
    "emitted tokens / decoded-token positions for the current stats "
    "window (1.0 = every device token reached a client; speculation "
    "rollback, multi-step overrun and preemption replay lower it)")
_m_engine_exc = _metrics.counter(
    "serving_engine_exceptions_total",
    "engine dispatch exceptions fanned out to request futures, by "
    "dispatch kind", labelnames=("where",))
# One-kernel round (r16): dispatch-per-round + async overlap accounting.
_m_round_dispatches = _metrics.histogram(
    "serving_dispatches_per_round",
    "attention dispatches one scheduler round issued (split path: "
    "chunk prefill, decode and verify can each fire; unified round: "
    "always 1)", buckets=(1.0, 2.0, 3.0, 4.0))
_m_round_overlap = _metrics.histogram(
    "serving_round_overlap_seconds",
    "host plan+dispatch time of round N+1 hidden behind round N's "
    "device execution (async double-buffered engine loop; only "
    "observed while a round was in flight)",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.5))
# Reliability (r17): fault injection, recovery ladder, quarantine,
# per-request timeouts.
_m_fault_injected = _metrics.counter(
    "serving_fault_injected_total",
    "deterministic FaultPlan faults fired at an engine seam "
    "(injection is opt-in: ctor fault_plan= or PADDLE_TPU_FAULT_PLAN)",
    labelnames=("seam",))
_m_dispatch_retries = _metrics.counter(
    "serving_dispatch_retries_total",
    "failing dispatches absorbed by the recovery ladder: implicated "
    "requests were snapshotted and requeued for retry instead of "
    "having their futures failed")
_m_quarantined = _metrics.counter(
    "serving_requests_quarantined_total",
    "requests failed by the recovery ladder after implicating "
    "themselves in quarantine_after consecutive dispatch failures "
    "(co-resident requests resume token-identically)")
_m_recoveries = _metrics.counter(
    "serving_recoveries_total",
    "clean recoveries: first successful dispatch after >= 1 dispatch "
    "failure — health returns degraded -> ok")
_m_timeouts = _metrics.counter(
    "serving_request_timeouts_total",
    "requests cancelled by their per-request timeout_s (queued or "
    "resident; the slot and its blocks are freed, the stream "
    "terminates with reason='timeout')")
# Memory-flat long-context round: sequence-parallel attention byte
# accounting + KV-tier prefetch-ahead.
_m_sp_peak_bytes = _metrics.gauge(
    "serving_sp_attention_bytes_peak",
    "peak per-shard cross-shard fresh-K/V bytes any packed-prefill "
    "dispatch of this server materialized (analytic accounting from "
    "serving_dist.sp_attention — linear in chunk length for "
    "'allgather', flat O(block) for 'ring'/'ulysses'; 0 when sp<=1)")
_m_prefetch_issued = _metrics.counter(
    "kv_tier_prefetch_issued_total",
    "host-tier blocks promoted AHEAD of admission by the prefetch "
    "loop, overlapped with the in-flight round's device execution")
_m_prefetch_hit = _metrics.counter(
    "kv_tier_prefetch_hit_total",
    "prefetched tier blocks still device-resident when their request "
    "was admitted — promotion wall time the admission path never paid")
_m_prefetch_wasted = _metrics.counter(
    "kv_tier_prefetch_wasted_total",
    "prefetched tier blocks whose request left the queue unadmitted "
    "(timeout/stop) or that pool pressure reclaimed before admission")
_m_promote_overlap = _metrics.histogram(
    "kv_tier_promote_overlap_seconds",
    "wall time of overlapped (prefetch-ahead) tier promote batches — "
    "host copy time hidden behind device execution instead of being "
    "charged to the admission path",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.5))
_req_ids = itertools.count()

STOP_REASONS = ("eos", "stop_token", "stop_string", "budget")

HEALTH_CODES = {"ok": 0.0, "degraded": 1.0, "stalled": 2.0}


@dataclass
class RequestMeta:
    """Scheduling metadata the front-door scheduler reads (round 12).

    lane: SLO lane name ("interactive" = TTFT-sensitive, "batch" =
        throughput). The engine itself is lane-agnostic — lanes only
        mean something to the installed scheduler policy.
    tenant: fair-share / rate-limit accounting bucket.
    deadline_s: relative TTFT deadline in SECONDS from submit; the
        engine counts (never enforces) misses at first-token time.
    cost: tokens the tenant's rate bucket is charged at admission
        (conventionally prompt_len + token budget)."""
    lane: str = "interactive"
    tenant: str = "default"
    deadline_s: float | None = None
    cost: int = 0


@dataclass
class _Req:
    ids: np.ndarray
    future: Future
    t_submit: float
    padded: bool = False
    rid: str = ""
    ttft: float | None = None
    sampling: SamplingParams | None = None
    seed: int = 0
    # front door (round 12): scheduling metadata, streaming callback,
    # and preemption resume state. gen0 = tokens generated before the
    # last preemption (the slot's token list is re-seeded with them so
    # position/PRNG-step/budget arithmetic is residency-invariant);
    # resume_ids = ids ++ gen0, the prompt the resume re-prefills.
    meta: "RequestMeta | None" = None
    on_token: object = None
    gen0: tuple = ()
    resume_ids: np.ndarray | None = None
    preempts: int = 0  # times this request has been swapped out
    # reliability (r17): per-request wall-clock cancellation deadline
    # (seconds from submit; None = never)
    timeout_s: float | None = None
    # causal tracing (ISSUE 14): the TraceContext stamped onto every
    # event/span/ring entry/journal record this request touches; hop
    # bumps on retry requeue (engine) and failover/migration (router)
    trace: TraceContext | None = None


class GenerationServer:
    """Dynamic-batching server over one compiled decode program.

    program: a TranslatedLayer from `paddle.jit.load(prefix)` of an
        `export_generator` artifact, or any callable
        (ids[B, P] int32, seed, temperature, eos, top_p, pad) -> [B, T].
    batch_size: the program's static B (inferred from the artifact's
        input spec when available).
    prompt_len: the program's static P (inferred likewise). Shorter
        prompts are LEFT-padded with pad_token_id (the program masks
        pads from attention and the output keeps the pad prefix).

    Pad caveat: the decode program detects padding by VALUE equality, so
    pad masking is only engaged for batches that contain a padded row;
    in such a mixed batch, a full-length prompt that legitimately
    contains pad_token_id gets those positions masked too — pick a pad
    id outside the prompt alphabet if prompts mix lengths. submit()
    GUARDS this case (ADVICE r5): a full-length prompt containing
    pad_token_id logs a warning naming the positions, or raises when
    the server is built with strict_pad_check=True. (The paged server
    masks by length and has no such caveat.)
    """

    def __init__(self, program, batch_size=None, prompt_len=None,
                 pad_token_id=0, max_wait_ms=5.0, temperature=0.0,
                 seed=0, eos_token_id=-1, top_p=1.0,
                 strict_pad_check=False, attribution=False):
        self._program = program
        # export_generator artifacts record prompt_len and batch_size
        # (batch_size None = batch-polymorphic: the server picks its own)
        meta = getattr(program, "_meta", {}) or {}
        prompt_len = prompt_len or meta.get("prompt_len")
        batch_size = batch_size or meta.get("batch_size")
        if not batch_size and prompt_len and meta.get("batch_size", 0) \
                is None:
            batch_size = 8  # polymorphic artifact: serving default
        if not batch_size or not prompt_len:
            raise ValueError(
                "batch_size/prompt_len not given and not recorded in the "
                "artifact meta (re-export with models.gpt2."
                "export_generator, or pass them explicitly)")
        self.batch_size = int(batch_size)
        self.prompt_len = int(prompt_len)
        # quantization block (schema-congruent with the paged server):
        # the dense program's quantization is baked into the exported
        # artifact — report what its meta records (scale buffers live
        # inside the program's params, so scale bytes read 0 here)
        wq = meta.get("weight_quant")
        kq = meta.get("kv_quant")
        self._quant_stats = {
            "enabled": bool(wq or kq),
            "mode": "w8a16" if wq == "int8" else "none",
            "kv_dtype": kq or "native",
            "kv_scale_bytes": 0,
            "kv_pool_bytes_total": 0,
        }
        self.pad_token_id = int(pad_token_id)
        self.strict_pad_check = bool(strict_pad_check)
        self.max_wait_ms = float(max_wait_ms)
        self._defaults = (np.uint32(seed), np.float32(temperature),
                          np.int32(eos_token_id), np.float32(top_p),
                          np.int32(pad_token_id))
        self._lock = threading.Condition()
        self._queue: list[_Req] = []
        self._stop = False
        self._thread = None
        # stats
        self._lat = []
        self._tokens_out = 0
        self._batches = 0
        self._batches_at_reset = 0
        self._rows = 0
        self._stop_reasons = dict.fromkeys(STOP_REASONS, 0)
        self._t0 = None
        # attribution (ISSUE 17): same ledger class as the paged
        # server — the dense batcher charges whole-batch device time
        # apportioned evenly over its rows (rows cost the same at
        # fixed B by construction)
        self._ledger = ResourceLedger() if attribution else None

    def _req_sig(self, sampling):
        """Program-level parameter signature a batch must share: the
        dense decode program takes ONE (temperature, top_p, seed, eos)
        per dispatch, so the batcher only groups requests whose
        signatures match. None = server defaults (rolling batch seed).
        Returns (temp, top_p, seed|None, eos, from_stop_ids)."""
        seed0, temp0, eos0, top_p0, _ = self._defaults
        if sampling is None:
            return (float(temp0), float(top_p0), None, int(eos0), False)
        s = sampling
        # the dense program has no per-slot param buffers: fields that
        # need them are rejected EAGERLY, naming the field (the paged
        # server supports all of them)
        for field_name, bad in (
                ("top_k", s.top_k != 0),
                ("min_p", s.min_p != 0.0),
                ("repetition_penalty", s.repetition_penalty != 1.0),
                ("presence_penalty", s.presence_penalty != 0.0),
                ("frequency_penalty", s.frequency_penalty != 0.0),
                ("stop_strings", bool(s.stop_strings)),
                ("max_new_tokens", s.max_new_tokens is not None)):
            if bad:
                raise ValueError(
                    f"GenerationServer (dense) does not support "
                    f"SamplingParams.{field_name}="
                    f"{getattr(s, field_name)!r}; use "
                    f"PagedGenerationServer")
        if len(s.stop_token_ids) > 1:
            raise ValueError(
                "GenerationServer (dense) supports at most one stop "
                f"token id (the program's eos), got "
                f"{s.stop_token_ids!r}; use PagedGenerationServer")
        eos = (int(s.stop_token_ids[0]) if s.stop_token_ids
               else int(eos0))
        return (s.temperature, s.top_p, s.seed, eos,
                bool(s.stop_token_ids))

    # ---- client API ----------------------------------------------------
    def submit(self, ids, sampling=None, tenant="default"):
        """Enqueue one prompt (list/array of ints, length <= prompt_len).
        Returns a Future resolving to the [prompt_len + new] int32 row.

        sampling: optional SamplingParams. The dense program runs one
        (temperature, top_p, seed, eos) per dispatch, so requests are
        batched with same-signature peers; per-slot fields (top_k,
        min_p, penalties, stop strings, per-request budgets) raise
        eagerly — the paged server supports them.
        tenant: attribution account the request's device time is
        charged to when the server was built with attribution=True."""
        if sampling is not None and not isinstance(sampling,
                                                   SamplingParams):
            raise TypeError(f"sampling must be a SamplingParams, "
                            f"got {type(sampling).__name__}")
        ids = np.asarray(ids, np.int32).reshape(-1)
        if ids.size == 0 or ids.size > self.prompt_len:
            raise ValueError(
                f"prompt length {ids.size} not in [1, {self.prompt_len}]")
        if ids.size == self.prompt_len and (ids == self.pad_token_id).any():
            # the documented value-masking corruption case (ADVICE r5):
            # this prompt needs no padding itself, but batched with ANY
            # padded row the program masks its pad-valued positions too
            at = np.flatnonzero(ids == self.pad_token_id).tolist()
            msg = (f"full-length prompt contains pad_token_id="
                   f"{self.pad_token_id} at positions {at}: batched "
                   f"with padded rows those positions would be masked "
                   f"(value-equality padding); use "
                   f"PagedGenerationServer (length masking) or a pad "
                   f"id outside the prompt alphabet")
            if self.strict_pad_check:
                raise ValueError(msg)
            _logger.warning("GenerationServer.submit: %s", msg)
        sig = self._req_sig(sampling)  # eager validation
        row = np.full((self.prompt_len,), self.pad_token_id, np.int32)
        row[self.prompt_len - ids.size:] = ids  # LEFT padding
        req = _Req(ids=row, future=Future(), t_submit=time.perf_counter(),
                   padded=ids.size < self.prompt_len,
                   rid=f"d{next(_req_ids)}", sampling=sampling,
                   meta=RequestMeta(tenant=str(tenant)))
        req.sig = sig
        if self._ledger is not None:
            self._ledger.request_begin(req.rid, str(tenant))
        with self._lock:
            if self._stop:
                raise RuntimeError("server stopped")
            self._queue.append(req)
            _m_queue_depth.labels(server="dense").set(len(self._queue))
            self._lock.notify()
        _tracing.event("request_submitted", request_id=req.rid,
                       prompt_len=int(ids.size))
        return req.future

    def start(self):
        if self._thread is not None:
            return self
        if self._stop:
            raise RuntimeError(
                "server was stopped; build a new GenerationServer")
        self._t0 = time.perf_counter()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        with self._lock:
            self._stop = True
            self._lock.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=60)
            self._thread = None
        with self._lock:
            for req in self._queue:  # fail, don't strand, late arrivals
                req.future.set_exception(RuntimeError("server stopped"))
            self._queue.clear()

    def reset_stats(self):
        """Zero the latency/throughput counters (benchmark windows); the
        batch counter keeps advancing so sampling seeds never repeat."""
        with self._lock:
            self._lat.clear()
            self._tokens_out = 0
            self._rows = 0
            self._batches_at_reset = self._batches
            self._stop_reasons = dict.fromkeys(STOP_REASONS, 0)
            self._t0 = time.perf_counter()
        if self._ledger is not None:
            self._ledger.reset()

    def stats(self):
        """Throughput and latency of the current measurement WINDOW —
        everything since start() or the last reset_stats() call.
        `stop_reasons` carries the same four-key breakdown as the paged
        server's stats (the dense program only ever produces eos /
        stop_token / budget — stop_string stays 0)."""
        with self._lock:
            lat = sorted(self._lat)
            dt = (time.perf_counter() - self._t0) if self._t0 else 0.0
            n = len(lat)
            nb = self._batches - self._batches_at_reset
            pct = (lambda p: lat[min(n - 1, int(p * n))] if n else 0.0)
            return {
                "requests": n,
                "batches": nb,
                "batch_fill": self._rows / ((nb or 1) * self.batch_size),
                "new_tokens": self._tokens_out,
                "tokens_per_sec": self._tokens_out / dt if dt else 0.0,
                "p50_ms": pct(0.50) * 1e3,
                "p90_ms": pct(0.90) * 1e3,
                "p99_ms": pct(0.99) * 1e3,
                "stop_reasons": dict(self._stop_reasons),
                "quantization": dict(self._quant_stats),
                # attribution (ISSUE 17): same schema as the paged
                # server — zeroed when the ledger is off
                "attribution": (self._ledger.stats()
                                if self._ledger is not None
                                else disabled_attribution_stats()),
                "wall_s": dt,
            }

    def cost_report(self):
        """`CostReport` billing export for the current window (ISSUE
        17); None when the server was built without attribution."""
        return self._ledger.report() if self._ledger is not None else None

    # ---- batcher loop --------------------------------------------------
    def _take_batch(self):
        """Block for the first request, then gather until full batch or
        the max_wait deadline; only requests sharing the head-of-line
        request's program signature (temperature/top_p/seed/eos) join —
        mismatched requests keep their queue order for a later batch.
        Returns [] on stop."""
        with self._lock:
            while not self._queue and not self._stop:
                self._lock.wait(timeout=0.1)
            if self._stop and not self._queue:
                return []
            deadline = time.perf_counter() + self.max_wait_ms * 1e-3
            while len(self._queue) < self.batch_size and not self._stop:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                self._lock.wait(timeout=remaining)
            sig = self._queue[0].sig
            batch = []
            for r in self._queue:
                if len(batch) == self.batch_size:
                    break
                if r.sig == sig:
                    batch.append(r)
            for r in batch:
                self._queue.remove(r)
            _m_queue_depth.labels(server="dense").set(len(self._queue))
            return batch

    def _loop(self):
        while True:
            batch = self._take_batch()
            if not batch:
                return
            for r in batch:
                _tracing.event("request_admitted", request_id=r.rid)
            _m_slots_busy.labels(server="dense").set(len(batch))
            rows = [r.ids for r in batch]
            while len(rows) < self.batch_size:  # pad: same device cost
                rows.append(rows[0])
            ids = np.stack(rows)
            # pad masking is VALUE-equality in the decode program: only
            # engage it when some row is actually padded, so full-length
            # prompts that legitimately contain pad_token_id aren't
            # masked at those positions
            temp, top_p, seed, eos, _from_stop = batch[0].sig
            defaults = [np.uint32(0), np.float32(temp), np.int32(eos),
                        np.float32(top_p), self._defaults[-1]]
            if not any(r.padded for r in batch):
                defaults[-1] = np.int32(-1)
            if seed is not None:
                # explicit per-request seed (SamplingParams.seed): part
                # of the batch signature, so every row asked for it —
                # reproducible by construction
                defaults[0] = np.uint32(seed)
            else:
                # per-batch seed: with temperature > 0 a FIXED seed
                # would draw identical sampling noise for every batch
                # (identical prompts -> identical completions, forever)
                defaults[0] = np.uint32(
                    (int(self._defaults[0]) + self._batches) & 0xFFFFFFFF)
            t_disp = time.perf_counter()
            try:
                with _tracing.span("decode_dispatch",
                                   request_ids=[r.rid for r in batch],
                                   batch=len(batch)):
                    out = self._program(ids, *defaults)
                    out = np.asarray(getattr(out, "numpy", lambda: out)())
            except Exception as e:  # noqa: BLE001 — fan the error out
                for r in batch:
                    r.future.set_exception(e)
                continue
            t_done = time.perf_counter()
            if self._ledger is not None:
                self._ledger.charge_device(
                    int((t_done - t_disp) * 1e9),
                    [(r.meta.tenant, r.rid, 1) for r in batch])
            new_tokens = out.shape[1] - self.prompt_len
            # stop accounting (schema-congruent with the paged server):
            # the program keeps emitting eos after a hit, so "did any
            # generated token match the batch's eos id" is exact
            reasons = []
            for i, r in enumerate(batch):
                gen = out[i, self.prompt_len:]
                if eos >= 0 and (gen == eos).any():
                    reasons.append("stop_token" if _from_stop else "eos")
                else:
                    reasons.append("budget")
            with self._lock:
                self._batches += 1
                self._rows += len(batch)
                self._tokens_out += new_tokens * len(batch)
                for i, r in enumerate(batch):
                    self._lat.append(t_done - r.t_submit)
                    self._stop_reasons[reasons[i]] += 1
            _m_slots_busy.labels(server="dense").set(0)
            for i, r in enumerate(batch):
                cost = (self._ledger.request_done(r.rid, new_tokens)
                        if self._ledger is not None else None)
                _tracing.event("request_done", request_id=r.rid,
                               new_tokens=int(new_tokens), cost=cost)
                _m_requests_done.labels(server="dense").inc()
                _m_stop_reason.labels(server="dense",
                                      reason=reasons[i]).inc()
                _m_request_latency.labels(server="dense").observe(
                    t_done - r.t_submit)
                r.future.set_result(out[i])


class PagedGenerationServer:
    """Continuous-batching server over the paged KV cache.

    Where `GenerationServer` pads every request to one global prompt_len
    and holds its slot for the full max_new even after EOS, this server
    runs the PagedDecoder engine directly against a `PagedKVCache`:

      * per-slot sequence lengths — a 70-token prompt costs 70 cache
        positions, not prompt_len;
      * every decode step, finished slots (EOS or the request's token
        budget) resolve their futures, free their blocks, and are
        REFILLED from the queue before the next step — new requests join
        mid-flight instead of waiting for the whole batch to drain;
      * masking is by length, so a prompt that legitimately contains
        pad_token_id can never be corrupted (the dense server's
        value-equality caveat does not exist here).

    Admission is reservation-based: a request is admitted only when the
    pool can cover its worst case (ceil((len + max_new)/block_size)
    blocks) on top of every active slot's outstanding worst case, so
    mid-flight block exhaustion is impossible. Blocks are still
    allocated lazily (`cache.append`) as sequences grow — the
    reservation is accounting, not allocation.

    model: a GPT2 (or same-layout) module; its params are snapshotted at
    construction (weight_quant="int8" serves W8A16).

    Prefill is PACKED and CHUNKED (Ragged Paged Attention direction,
    arXiv:2604.15464; Sarathi-style chunk budget): every loop round, up
    to `prefill_chunk_tokens` prompt tokens across ALL slots still
    feeding their prompts are concatenated into one token-packed stream
    and run as ONE packed ragged prefill dispatch — an admission burst
    of N requests costs O(1) prefill dispatches per decode round
    instead of N sequential B=1 dispatches (each paying the 8-70ms
    tunnel floor, PERF.md). Prompts longer than the chunk budget are
    split across rounds, the partial K/V state living in the paged
    cache (which supports it natively), so in-flight decode slots see
    at most one chunk-budget prefill between decode dispatches and
    inter-token latency stays bounded during admission churn. The
    packed stream is bucketed to a power of two, so compile count is
    logarithmic in the packed token budget rather than per
    prompt-length bucket.

    prefill_chunk_tokens: max REAL prompt tokens per packed prefill
        dispatch (default 512). Smaller bounds decode ITL tighter
        during bursts; larger finishes prefills (TTFT) sooner.
    pack_align: each prompt chunk's packed region is aligned to this
        many tokens (default: 128 on TPU — the Pallas ragged-prefill
        kernel's query-tile contract — else 8). Alignment padding is
        routed to the trash block.

    steps_per_dispatch > 1 turns on multi-step scheduling: that many
    decode tokens run as ONE jitted lax.scan dispatch, amortizing the
    per-dispatch floor (8-70ms through the dev tunnel, PERF.md) that
    would otherwise bound a token-per-dispatch loop. The cost is
    granularity: EOS/budget is only observed every k tokens, so up to
    k-1 tokens per request are decoded and discarded, and slot refill
    waits for the scan to return. k=1 is exact continuous batching.

    enable_prefix_cache=True turns on block-level PREFIX CACHING
    (round 9): on admission the request's prompt is matched against
    the pool's content index (`PagedKVCache.attach_prefix`) and the
    longest cached block chain is attached by table-entry copy — those
    tokens are marked already-fed and the packed ragged prefill starts
    at the first uncached token (the PR 3 chunk path already resumes
    mid-sequence, so no engine change is needed). A fully cached
    prompt prefills exactly ONE token: the last prompt token is always
    recomputed to sample token 0. Completed prompts are published back
    to the index; freed blocks with indexed content park in the
    cache's LRU retention list and are reclaimed only under pool
    pressure. Admission reserves one extra block per request for the
    (at most one) copy-on-write a mid-block shared tail can force.
    Default OFF: a disabled server takes the exact pre-cache
    allocation path (no lookups, no publishes, no spare block).

    kv_tier (long-context round) adds a HOST-RAM TIER below the device
    pool (True for the default `kv_tier.HostKVTier`, or an instance
    for explicit capacity/watermark; requires enable_prefix_cache).
    Cold retained prefix blocks demote to pinned host memory as int8
    codes+scales instead of being dropped under pool pressure, and a
    later prompt/resume whose prefix chain continues into the tier
    promotes them back before the attach (prefetch-on-attach) — so
    preempted sessions and shared system prompts survive pool churn
    without recompute. kv_tier=None keeps the exact pre-tier engine.

    QUANTIZED SERVING (this round): `quantization="w8a16"` packs the
    decoder weights to int8 ONCE at construction
    (`model.quantize_weights()`, the shared PTQ implementation) and
    every dispatch — decode step, packed chunked prefill, speculative
    verify — streams half the weight bytes with a fused rescale
    epilogue. `kv_dtype="int8"` additionally quantizes the KV POOL:
    blocks hold int8 codes + per-vector scales
    (`PagedKVCache(kv_dtype="int8")`), appends quantize on write,
    attention dequantizes inside the kernel, and prefix-cache
    publish/attach, CoW, swap-out and truncate all carry the scale
    buffer with the block — so sharing and preemption keep working
    quantized, at ~2x resident tokens per pool byte. Both knobs
    default OFF (the exact pre-round bf16 path); `stats()` reports a
    schema-stable "quantization" block either way. See docs/SERVING.md
    "Quantized serving" for the parity-tolerance policy and when NOT
    to enable.

    sharding=ShardedEngineConfig(tp, dp) (or True for a 1-device mesh)
    turns on SHARDED SERVING (serving_dist round): the snapshotted
    (and optionally quantized) weights are placed on a
    `jax.sharding.Mesh` per the training TP plan (column/row-split
    attention + MLP, vocab-parallel head), the KV pool's head axis
    shards per-device behind the unchanged block-table API (+ the
    block axis over dp), and every decode program is jitted with
    explicit in/out shardings — XLA inserts the two TP collectives.
    The engine loop, prefix cache, speculation, sampling and the
    front door run unmodified (token parity tested across mesh
    sizes); a 1-device mesh is bitwise the unsharded engine, and the
    default None never imports serving_dist. See docs/SERVING.md
    "Sharded serving".

    OPERATIONS PLANE (ISSUE 10): `expose_port=` (or the
    PADDLE_TPU_METRICS_PORT env var; 0 = ephemeral, tests) starts a
    stdlib http.server daemon thread serving `/metrics` (Prometheus
    text from the process registry), `/statusz` (live JSON engine
    state — the `statusz()` method), and `/healthz`
    (ok | degraded | stalled; stalled answers 503). It also enables
    the per-server FLIGHT RECORDER — a bounded ring of structured
    engine events (admission, chunk plans, dispatch shapes,
    preempt/resume, pool levels, XLA compiles, exceptions) — and the
    STALL WATCHDOG, which flips health to "stalled" and auto-dumps the
    ring when work is pending with no dispatch progress past
    `stall_timeout_s` (an engine dispatch exception also dumps).
    XLA compiles at every decode jit boundary are tracked process-wide
    regardless (`observability.compile_tracker`) and windowed into
    `stats()["compiles"]`; `stats()["goodput"]` accounts decoded
    device tokens vs. emitted / speculation-rolled-back / replayed.
    Default OFF: no port, no threads, and every recorder hook is one
    bool check — the exact pre-round engine.

    ONE-KERNEL ROUND (r16): `unified_round=True` fuses each scheduler
    round's up-to-three attention dispatches — packed chunk prefill,
    plain decode, speculative verify — into ONE
    `nn.decode.unified_round` dispatch over a single packed stream
    (prefill chunks, decode rows and verify regions are all just
    ragged segments under the same segment-causal mask; see
    docs/SERVING.md "One-kernel round"). `async_rounds=True` (implies
    unified) additionally DOUBLE-BUFFERS the loop: round N+1 is
    planned on host and dispatched while round N executes on device,
    with round N's sampled tokens feeding round N+1's decode rows
    through a slot-indexed device carry — the only host<->device sync
    point is the detokenize/stop-check boundary, one round behind the
    device. Stop flags are device-computed either way; host-side stop
    checks (stop strings, budgets) drain one round late and the
    overshoot round is discarded, so output is TOKEN-IDENTICAL to the
    split path across the whole composed stack (prefix cache,
    speculation, quantization, sharding, preemption — parity-tested).
    Requires steps_per_dispatch=1. Both default OFF: the exact split
    scheduler path.

    RELIABILITY (r17, docs/RELIABILITY.md): the engine runs a RECOVERY
    LADDER by default — a dispatch exception no longer fans out to
    every in-flight future. Implicated requests are snapshotted
    through the preemption swap-out machinery (tokens-so-far + resume
    prompt; live K/V published into the prefix index when caching is
    on), requeued at the front of their queue, and retried with
    capped exponential backoff; a request implicated in
    `RecoveryPolicy.quarantine_after` consecutive failures is
    QUARANTINED (its future fails with `QuarantinedRequest` naming
    the fault seam) while every co-resident request completes
    token-identically. `recovery=False` restores the legacy
    fail-everything path. `/healthz` is degraded only while
    UNRECOVERED: the first successful dispatch after a failure counts
    a recovery and returns health to ok. `fault_plan=` (or
    PADDLE_TPU_FAULT_PLAN) installs a deterministic `FaultPlan` —
    fixed-seed faults by seam x occurrence at the engine's hazard
    seams (dispatch raise, pool exhaustion, watchdog-visible slow
    dispatch, detokenize error, stream-consumer death) — one bool
    check per seam when off. `journal=` (path or `SessionJournal`)
    records every accepted request + emitted token append-only;
    `recover_from_journal()` on a fresh server re-admits whatever a
    crash (`kill()` in tests) interrupted, token-identically. Per-
    request `submit(timeout_s=)` cancels overdue requests slot-
    freeingly; `shed_queue_depth=` refuses admissions past a queue
    depth with an `AdmissionShed.retry_after_s` hint.

    OBSERVABILITY, FLEET-GRADE (ISSUE 14): every request carries a
    `TraceContext` (minted at submit or passed by a router via
    `submit(trace_ctx=)`) whose trace_id / hop / cause stamp every
    trace event, span, flight-recorder entry and journal record the
    request touches — `observability.assemble_causal_traces` stitches
    a request's whole fleet lifetime (retries, failover, migration)
    into one causal tree. `slos=` (list of `observability.SLO`, or
    True for `default_slos()`) attaches an SLO burn-rate engine fed
    from the TTFT/ITL/availability/goodput hot paths: multi-window
    ok|warn|page states, `slo_*` gauges, a `/slo` ops endpoint, and a
    `stats()["slo"]` block (schema-stable zeros when off).
    `export_timeline(path)` writes the Chrome/Perfetto timeline of
    the span sink + flight-recorder ring.

    speculation=SpecConfig(...) (or True for defaults) turns on
    SPECULATIVE DECODING (round 11): each round, eligible decode-phase
    slots ask the drafter (default: the self-drafting n-gram /
    prompt-lookup drafter — no second model) for up to K draft tokens,
    and ONE packed verification dispatch (`nn.decode.packed_verify`,
    the PR 3 packed-prefill kernel shape with per-row sample indices)
    scores every slot's drafts against the target model. Because the
    per-request PRNG is counter-based, the target's token at every
    position is deterministic, so rejection sampling reduces to exact
    match and fixed-seed output — greedy or sampled, penalties
    included — is token-identical to non-speculative decode no matter
    how many drafts were accepted. Accepted tokens plus the bonus
    token emit in one round (1..K+1 tokens per slot per dispatch);
    rejected speculative K/V positions roll back via
    `PagedKVCache.truncate_seq`. Slots with no proposal this round
    take the plain decode dispatch, interleaved as before. Requires
    steps_per_dispatch=1; admission reserves a K-token overrun per
    request. Default OFF: the scheduler round is the exact
    pre-speculation path.
    """

    def __init__(self, model, *, max_slots=4, block_size=16,
                 max_prompt_len=None, max_new_tokens=32, num_blocks=None,
                 eos_token_id=None, temperature=0.0, seed=0,
                 weight_quant=None, quantization=None, kv_dtype=None,
                 steps_per_dispatch=1,
                 prefill_chunk_tokens=512, pack_align=None,
                 enable_prefix_cache=False, kv_tier=None,
                 detokenize=None,
                 stop_tail_tokens=16, speculation=None, sharding=None,
                 unified_round=False, async_rounds=False,
                 expose_port=None, flight_recorder=None,
                 stall_timeout_s=30.0, fault_plan=None, recovery=True,
                 journal=None, shed_queue_depth=None, slos=None,
                 attribution=None, tier_prefetch=None):
        import jax
        import jax.numpy as jnp

        from ..sampling import SlotParamStore
        from ..nn.decode import PagedDecoder
        from .kv_cache import PagedKVCache, blocks_for

        self._jnp, self._jax = jnp, jax
        cfg = model.cfg
        self.max_new = int(max_new_tokens)
        self.steps_per_dispatch = max(1, int(steps_per_dispatch))
        # speculation (round 11): True -> default SpecConfig; a
        # SpecConfig configures the drafter and the K budget. None
        # keeps the EXACT pre-speculation scheduler path.
        if speculation is True:
            from ..spec_decode import SpecConfig

            speculation = SpecConfig()
        elif speculation is not None:
            from ..spec_decode import SpecConfig

            if not isinstance(speculation, SpecConfig):
                raise TypeError(
                    f"speculation must be a SpecConfig, True or None, "
                    f"got {type(speculation).__name__}")
        self.speculation = speculation
        # sharded serving: normalize (True -> defaults) and validate
        # the mesh config EAGERLY — tp must divide the head count
        # before the pool layout is fixed below. The disabled path
        # never imports serving_dist.
        if sharding is not None:
            from ..serving_dist import normalize_sharding

            sharding = normalize_sharding(sharding, cfg.num_heads)
        # sequence-parallel prefill (long-context round): sp multiplies
        # the packed chunk budget — the sp-sharded program prefills
        # sp * prefill_chunk_tokens prompt tokens per dispatch at the
        # same per-shard token load, so one huge prompt stops
        # serializing through a single replica's budget. sp=1 (or
        # unsharded) keeps the exact pre-round budget and programs.
        self._sp_degree = sharding.sp if sharding is not None else 1
        # sp attention strategy (memory-flat long-context round): how
        # the sp>1 packed-prefill trunk attends across shards —
        # "allgather" (exact r21 seam, linear peak bytes) or the
        # memory-flat "ring"/"ulysses" modes (config-validated and
        # sp=1-normalized by ShardedEngineConfig itself)
        self._sp_attention = (sharding.sp_attention
                              if sharding is not None else "allgather")
        self._spec_k = (speculation.max_draft_tokens
                        if speculation is not None else 0)
        self._drafter = (speculation.make_drafter()
                         if speculation is not None else None)
        if speculation is not None and self.steps_per_dispatch > 1:
            raise ValueError(
                "speculation requires steps_per_dispatch=1 (the verify "
                "dispatch already amortizes the per-dispatch floor over "
                "up to K+1 tokens; fusing verify rounds into a scan "
                "would need host drafting mid-scan)")
        # one-kernel round (r16): unified_round=True fuses the whole
        # scheduler round — chunk prefill rows, decode rows, verify
        # regions — into ONE attention dispatch; async_rounds=True
        # additionally double-buffers the loop (plan round N+1 on host
        # while round N runs on device, tokens chained via the device
        # carry). async implies unified. Default OFF: the exact
        # split-path scheduler.
        self._async = bool(async_rounds)
        self._unified = bool(unified_round) or self._async
        if self._unified and self.steps_per_dispatch > 1:
            raise ValueError(
                "unified_round/async_rounds require steps_per_dispatch"
                "=1 (the fused round already amortizes the dispatch "
                "floor over the whole round)")
        if self._unified and self._sp_degree > 1:
            raise ValueError(
                "sequence-parallel prefill (ShardedEngineConfig.sp > 1) "
                "requires the split scheduler path — the unified round "
                "packs decode/verify rows into the same stream the sp "
                "program would shard, and decode stays TP by design "
                "(set unified_round/async_rounds False)")
        self._uk1 = self._spec_k + 1  # pinned unified readout width
        # overrun horizon past the budget: a multi-step scan may write
        # up to k-1 discarded tokens, and a verify dispatch up to K
        # speculative positions past the last emitted token (rolled
        # back on rejection, but the blocks must be reservable). The
        # async loop adds ONE round of optimistic overshoot: the host
        # learns about stops a round late, so the device may write up
        # to 1 + K extra positions past where the split engine stops.
        slack = max(self.steps_per_dispatch - 1, self._spec_k)
        if self._async:
            slack += 1 + self._spec_k
        self._overrun = slack
        self.max_prompt_len = int(
            max_prompt_len or cfg.max_position - self.max_new - slack)
        if self.max_prompt_len + self.max_new + slack > cfg.max_position:
            raise ValueError(
                f"max_prompt_len ({self.max_prompt_len}) + max_new_tokens "
                f"({self.max_new}) + overrun slack ({slack}, "
                f"steps_per_dispatch/speculation) "
                f"exceeds max_position ({cfg.max_position})")
        self.max_slots = int(max_slots)
        self.block_size = int(block_size)
        self.prefill_chunk_tokens = int(prefill_chunk_tokens)
        if self.prefill_chunk_tokens < 1:
            raise ValueError("prefill_chunk_tokens must be >= 1")
        if pack_align is None:  # Pallas kernel query-tile contract on TPU
            pack_align = 128 if jax.default_backend() not in ("cpu",) else 8
        self._pack_align = int(pack_align)
        # verify regions only need alignment where the Pallas kernel
        # runs; the XLA fallback takes any packing, and a verify
        # dispatch fires every round — off TPU, padding each K+1-token
        # region to the prefill alignment would be pure wasted compute
        self._verify_align = (self._pack_align
                              if jax.default_backend() not in ("cpu",)
                              else 1)
        self.eos = -1 if eos_token_id is None else int(eos_token_id)
        self.temperature = float(temperature)
        # quantized serving hot path: `quantization="w8a16"` packs the
        # decoder weights ONCE here (model.quantize_weights — the shared
        # PTQ implementation) and every dispatch — decode, chunked
        # ragged prefill, speculative verify — runs int8 dots with the
        # fused rescale epilogue; `weight_quant="int8"` is the pre-round
        # alias. `kv_dtype="int8"` quantizes the KV POOL itself (int8
        # codes + per-block-row scales, dequant inside the kernels).
        # Both default OFF: the disabled path is the exact pre-round
        # bf16 program.
        if quantization not in (None, "w8a16"):
            raise ValueError(f"unknown quantization {quantization!r} "
                             "(supported: None, 'w8a16')")
        if weight_quant == "int8":
            quantization = "w8a16"
        elif weight_quant is not None:
            raise ValueError(f"unknown weight_quant {weight_quant!r} "
                             "(supported: 'int8')")
        if kv_dtype not in (None, "int8"):
            raise ValueError(f"unknown kv_dtype {kv_dtype!r} "
                             "(supported: None, 'int8')")
        self.quantization = quantization
        self.kv_dtype = kv_dtype
        params, _ = model.functional_state()
        if quantization == "w8a16":
            params = model.quantize_weights(params)
        self._params = params
        dt = params["ln_f.weight"].dtype
        self.enable_prefix_cache = bool(enable_prefix_cache)
        self._m_width = blocks_for(
            self.max_prompt_len + self.max_new + slack, self.block_size)
        if num_blocks is None:  # worst case: every slot at full horizon
            # (+1 CoW spare per slot when prefix caching is on, so the
            # default pool still fits max_slots worst-case requests)
            spare = 1 if self.enable_prefix_cache else 0
            num_blocks = self.max_slots * (self._m_width + spare) + 1
        if sharding is not None and sharding.dp > 1:
            # the pool's block axis shards over dp: round the array dim
            # up so the explicit placement divides evenly (the extra
            # blocks are just capacity)
            num_blocks = -(-int(num_blocks) // sharding.dp) * sharding.dp
        # host-RAM KV tier (long-context round): True -> default
        # HostKVTier, or an instance for explicit capacity/watermark.
        # Needs the prefix cache — tiering demotes/promotes INDEXED
        # retained content, which only exists when publishing is on.
        if kv_tier is not None and kv_tier is not False \
                and not self.enable_prefix_cache:
            raise ValueError(
                "kv_tier requires enable_prefix_cache=True (the tier "
                "holds demoted prefix-index content)")
        # tier prefetch-ahead (memory-flat long-context round): promote
        # a QUEUED request's cold tier blocks into the device pool
        # WHILE the current round computes, so admission's
        # attach_prefix finds the chain device-resident and pays no
        # promotion wall time. True -> lookahead 2 queued requests; an
        # int sets the lookahead depth. None/False = OFF (the exact
        # synchronous promote-on-attach path).
        if tier_prefetch is not None and tier_prefetch is not False:
            if kv_tier is None or kv_tier is False:
                raise ValueError(
                    "tier_prefetch requires kv_tier (prefetch-ahead "
                    "promotes host-tier content ahead of admission; "
                    "without a tier there is nothing to promote)")
            look = 2 if tier_prefetch is True else int(tier_prefetch)
            if look < 1:
                raise ValueError(
                    f"tier_prefetch={tier_prefetch!r} must be True or "
                    f"a positive lookahead depth (queued requests "
                    f"scanned per round)")
        else:
            look = 0
        self._prefetch_look = look
        self._prefetched: dict = {}    # rid -> set of prefetched hashes
        self._prefetch_done: set = set()  # rids whose walk went dry
        self._prefetch_issued = 0
        self._prefetch_hits = 0
        self._prefetch_wasted = 0
        self._prefetch_overlap_s = 0.0
        self._promote_ctx = None  # rid the in-progress attach serves
        self.cache = PagedKVCache(
            cfg.num_layers, cfg.num_heads,
            cfg.hidden_size // cfg.num_heads, block_size=self.block_size,
            num_blocks=int(num_blocks), dtype=dt, kv_dtype=kv_dtype,
            tier=kv_tier)
        self._blocks_for = blocks_for
        # sharded serving (serving_dist round): a ShardedEngineConfig
        # (or True for defaults) places the snapshotted/quantized
        # weights and the pool arrays on the mesh and hands the decoder
        # an explicit-shardings bundle. None = the exact pre-round
        # single-device path — serving_dist is never even imported.
        self.sharding = None
        self._mesh = None
        decode_shardings = None
        collective_quant = None
        if sharding is not None:
            from ..serving_dist import (apply_sharding,
                                        build_collective_quant)

            decode_shardings = apply_sharding(self, sharding)
            # quantized collectives (this round): int8/int4-group wire
            # for the mp-axis decode collectives — None (or tp=1, no
            # wire) keeps the exact r16 sharded programs
            collective_quant = build_collective_quant(sharding,
                                                      self._mesh)
        # the decoder's kv_dtype MUST match the cache's — PagedDecoder
        # re-checks the pairing eagerly on every dispatch
        self._decoder = PagedDecoder.for_config(
            cfg, self.block_size, kv_dtype=kv_dtype,
            shardings=decode_shardings,
            collective_quant=collective_quant,
            sp_attention=self._sp_attention)
        # analytic per-dispatch sp-attention byte accounting (host-side
        # arithmetic — the r20 dispatch_wire_bytes discipline): the
        # high-water mark feeds the serving_sp_attention_bytes_peak
        # gauge and, for ring/ulysses, every dispatch is asserted
        # under the chunk-length-independent flat bound
        self._sp_peak_bytes = 0
        self._sp_bytes_kw = dict(
            sp=self._sp_degree,
            tp=(sharding.tp if sharding is not None else 1),
            num_heads=cfg.num_heads,
            head_dim=cfg.hidden_size // cfg.num_heads,
            kv_quant=kv_dtype == "int8",
            itemsize=jnp.dtype(dt).itemsize)
        # per-slot sampling state (round 10): struct-of-arrays param
        # buffers + the [slots, V] penalty count buffer, scattered on
        # admit/refill. Constructor temperature is the DEFAULT for
        # requests submitted without SamplingParams (validated here).
        self._sp_store = SlotParamStore(self.max_slots, cfg.vocab_size)
        self._default_sampling = SamplingParams(
            temperature=self.temperature)
        self._detok = detokenize
        self.stop_tail_tokens = int(stop_tail_tokens)
        if self.stop_tail_tokens < 1:
            raise ValueError("stop_tail_tokens must be >= 1")
        self._seed0 = int(seed) & 0xFFFFFFFF
        self._auto_seeds = itertools.count()
        # slot state: None (idle) or dict(seq, req, toks, pos, budget)
        self._slots = [None] * self.max_slots
        self._worst: dict[int, int] = {}  # seq -> worst-case block count
        self._seq_counter = 0
        self._lock = threading.Condition()
        self._queue: list[_Req] = []
        self._stop = False
        self._thread = None
        # stats window
        self._lat = []
        self._ttft = []
        self._itl = []
        self._tokens_out = 0
        self._requests_done = 0
        self._steps = 0
        self._prefills = 0
        self._prefill_dispatches = 0
        self._active_integral = 0
        self._fill_integral = 0.0
        self._stop_reasons = dict.fromkeys(STOP_REASONS, 0)
        self._fastpath_dispatches = 0
        self._sampled_dispatches = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_rolled_back = 0
        self._spec_dispatches = 0
        self._spec_rounds_per_slot = 0
        # goodput accounting (ISSUE 10): generated-token positions
        # computed on device vs. the ones that reached a client —
        # decoded = goodput + spec-rolled-back + replayed, by
        # construction at every dispatch site
        self._decoded_tokens = 0
        self._replayed_tokens = 0
        # one-kernel round (r16): per-round dispatch accounting (both
        # engine paths — the split path reports its 1-3 dispatches per
        # round here too, so the fusion win is measurable), async
        # overlap, and the double-buffer state (the in-flight round +
        # the slot-indexed device carry; both live outside the stats
        # window and never reset)
        self._rounds = 0
        self._round_dispatch_count = 0
        self._mixed_rounds = 0
        self._overlap_s = 0.0
        self._pending = None
        self._carry = None
        self._zero_carry = None
        # steady-state device-argument reuse (async window rounds): the
        # whole plan argument set is round-invariant per (slots, seqs,
        # drafts) signature — caching the uploaded arrays is most of
        # "hide the host planner behind the device"
        self._args_cache = None
        self._tables_cache = None
        # front door (round 12): pluggable scheduler + preemption /
        # deadline window counters (zero + unused when no scheduler is
        # installed — the legacy submit/drain path is bit-identical)
        self._sched = None
        self._preemptions = 0
        self._resumes = 0
        self._preempt_cached_tokens = 0
        self._deadline_requests: dict[str, int] = {}
        self._deadline_misses: dict[str, int] = {}
        self._lane_ttft: dict[str, list] = {}
        self._lane_itl: dict[str, list] = {}
        self._t0 = None
        # ---- reliability (r17) ---------------------------------------
        # fault_plan: deterministic seam x occurrence injection (None +
        # unset PADDLE_TPU_FAULT_PLAN = no plan — every seam check is
        # one `is None` branch, the r15 recorder discipline).
        self._faults = resolve_fault_plan(fault_plan)
        # recovery: True (default) runs the recovery ladder — a
        # dispatch exception snapshots + requeues the implicated
        # requests instead of failing every in-flight future; False
        # restores the legacy fail-everything blast radius.
        if recovery is True:
            recovery = RecoveryPolicy()
        elif recovery is False or recovery is None:
            recovery = None
        elif not isinstance(recovery, RecoveryPolicy):
            raise TypeError(f"recovery must be a RecoveryPolicy or a "
                            f"bool, got {type(recovery).__name__}")
        self._recovery = recovery
        # journal: crash-consistent session journal (path or
        # SessionJournal); every accepted request and emitted token is
        # recorded, recover_from_journal() re-admits the interrupted.
        if isinstance(journal, (str, os.PathLike)):
            journal = SessionJournal(journal)
        elif journal is not None and not isinstance(journal,
                                                    SessionJournal):
            raise TypeError(f"journal must be a SessionJournal or a "
                            f"path, got {type(journal).__name__}")
        self._journal = journal
        # shed_queue_depth: admission shedding — a submit arriving
        # while >= this many requests are queued raises AdmissionShed
        # with a retry-after hint (None = never shed).
        if shed_queue_depth is not None and int(shed_queue_depth) < 1:
            raise ValueError(f"shed_queue_depth must be >= 1, "
                             f"got {shed_queue_depth}")
        self._shed_depth = (None if shed_queue_depth is None
                            else int(shed_queue_depth))
        self._fault_streak: dict[str, int] = {}  # rid -> consecutive
        self._consec_failures = 0                # failing dispatches
        self._any_timeouts = False  # set once a timed request is seen
        # SLO engine (ISSUE 14): declarative objectives over
        # TTFT/ITL/availability/goodput evaluated from sliding-window
        # reservoirs with multi-window burn rates; None (default) =
        # every feed site is one `is None` branch, the telemetry
        # discipline. True = observability.slo.default_slos().
        if slos is None or slos is False:
            self._slo = None
        elif isinstance(slos, SLOEngine):
            self._slo = slos
        elif slos is True:
            self._slo = SLOEngine(True)
        else:
            self._slo = SLOEngine(slos)
        # goodput-delta marks for the per-round SLO feed
        self._slo_good_mark = (0, 0)  # (tokens_out, decoded)
        # replica name a fleet wrapper sets (fleet.Replica) — stamps
        # trace events/spans so cross-replica assembly can tell the
        # in-process engines apart
        self.trace_name = None
        self._last_recovery = None  # {"ts","recovered_from","failures"}
        self._last_error_info = None  # structured degraded_reason
        # fleet round (r18): host ops the ENGINE THREAD executes at the
        # next round boundary (device state is only ever touched from
        # that thread — migration imports/exports queue here), and the
        # drain flag readiness() reports (live but not accepting new
        # placements).
        self._host_ops: list = []
        self._draining = False
        # elastic fleet (ISSUE 20): proof that warm_buckets() completed
        # before start() — the router's add_replica readiness gate
        # reads it, so a fresh replica never compiles inside a request
        # window
        self._warm_ran = False
        # window counters (reset_stats-coherent)
        self._faults_injected = 0
        self._dispatch_retries = 0
        self._recoveries = 0
        self._quarantined = 0
        self._timeouts = 0
        self._sheds = 0
        # ---- operations plane (ISSUE 10) -----------------------------
        # expose_port: None + PADDLE_TPU_METRICS_PORT unset = no ops
        # plane (the exact pre-round path: a disabled flight recorder
        # is one bool check per hook, no threads, no sockets).
        # expose_port=0 binds an ephemeral port (tests); the env var is
        # the production switch that needs no code change.
        if expose_port is None:
            env_port = os.environ.get(ENV_METRICS_PORT, "")
            expose_port = int(env_port) if env_port else None
        self._ops_progress = 0  # bumped on every dispatch/admission;
        self._last_error = None  # the stall watchdog samples it
        if isinstance(flight_recorder, _flight.FlightRecorder):
            self._recorder = flight_recorder
        else:
            self._recorder = _flight.FlightRecorder(
                enabled=bool(flight_recorder)
                or expose_port is not None)
        self.stall_timeout_s = float(stall_timeout_s)
        self._watchdog = None
        self.exporter = None
        # ---- attribution + capacity (ISSUE 17) -----------------------
        # attribution: None auto-enables with the ops plane or a live
        # metrics registry (the cost plane rides the telemetry
        # opt-in); True/False force. The ledger attaches to the cache
        # BEFORE any allocation, so block ownership is complete from
        # block one and the conservation invariants hold exactly.
        if attribution is None:
            attribution = expose_port is not None or _metrics.enabled()
        self._ledger = ResourceLedger() if attribution else None
        self.cache.ledger = self._ledger
        self._attr_parts = None  # parts of the dispatch in flight
        self._wire_mark = None   # decoder wire-byte level before it
        # deterministic pressure-signal bus: always constructed (one
        # sample is cheap and pull-only); auto-sampled at round
        # boundaries only when the telemetry plane is on, and always
        # sampled fresh by capacity_snapshot() / the /capacity
        # endpoint. Schema is the ROADMAP-3 Autoscaler contract.
        self._capacity = PressureSignals({
            "pool": self._cap_pool,
            "tier": self._cap_tier,
            "queues": self._cap_queues,
            "admission": self._cap_admission,
            "slo": self._cap_slo,
        })
        self._cap_auto = (self._recorder.enabled
                          or expose_port is not None)
        # tier telemetry: demote/promote land in the flight recorder
        # ring and the trace stream (kv_tier_demote / kv_tier_promote)
        if self.cache.tier is not None:
            self.cache.on_tier_event = self._on_tier_event
        # process-wide compile accounting: this engine answers "am I
        # serving live work" for the in-flight label, mirrors compile
        # events into its flight recorder, and windows the counter for
        # stats()["compiles"] (weakrefs — no unregister needed)
        _compile_tracker.register_in_flight_probe(self._ops_in_flight)
        _compile_tracker.add_listener(self._on_compile_event)
        self._compile_mark = _compile_tracker.mark()
        if expose_port is not None:
            # asking for a scrape endpoint IS opting into metrics — a
            # /metrics page of zeros because the registry gate stayed
            # closed would be the least debuggable outcome of all
            _metrics.REGISTRY.enable()
            self._watchdog = _flight.StallWatchdog(
                lambda: self._ops_progress, self._ops_in_flight,
                timeout=self.stall_timeout_s,
                on_stall=self._on_stall).start()
            from ..observability.exporter import OpsEndpoint

            self.exporter = OpsEndpoint(
                statusz_fn=self.statusz,
                healthz_fn=self.health,
                livez_fn=self.liveness,
                readyz_fn=self.readiness,
                slo_fn=(self.slo_report if self._slo is not None
                        else None),
                capacity_fn=self.capacity_snapshot).start(
                    port=expose_port)
            # pull-time health gauge; like the watchdog heartbeat
            # gauge, it follows the most recently built ops-plane
            # server when several are live
            _metrics.REGISTRY.gauge_fn(
                "serving_health_state",
                "engine health (0 ok, 1 degraded, 2 stalled) of the "
                "most recent ops-plane server",
                lambda: HEALTH_CODES[self.health()[0]])

    # ---- operations plane (ISSUE 10) -----------------------------------
    def _ops_in_flight(self):
        """True while the engine has live work: busy slots or queued
        requests. Read lock-free from watchdog/compile-tracker threads
        (GIL-atomic loads; staleness only delays detection one poll)."""
        if self._stop:
            # a stopped/killed engine can never dispatch again — a
            # kill() leaves its slots occupied by design (futures
            # unresolved for journal takeover), and reporting that as
            # "in flight" forever would poison the process-wide
            # compile tracker's in_flight label for every later server
            return False
        if any(s is not None for s in self._slots):
            return True
        if self._queue:
            return True
        if self._sched is not None:
            try:
                return self._sched.depth() > 0
            except Exception:  # noqa: BLE001 — a torn-down scheduler
                return False  # must not break health checks
        return False

    def _on_compile_event(self, ev):
        # a finished compile IS progress — without this, the dispatch
        # that just compiled reads as a stall to the watchdog (a
        # compile that itself exceeds the stall threshold still trips,
        # which is exactly the incident compile tracking exists for)
        self._ops_progress += 1
        self._recorder.record(
            "compile", program=ev["program"],
            dur_s=round(ev["dur_s"], 4), in_flight=ev["in_flight"],
            shard=ev["shard"])
        # attribution: an in-window compile is charged to the requests
        # the triggering dispatch computed for (compile wall time is
        # INSIDE the measured dispatch time — a parallel annotation,
        # like the trace assembler's compile_overlap_ms, not a
        # subtraction from it)
        if self._ledger is not None and self._attr_parts:
            self._ledger.charge_compile(int(ev["dur_s"] * 1e9),
                                        self._attr_parts)

    def _on_stall(self):
        self._recorder.record("stall", progress=self._ops_progress,
                              free_blocks=self.cache.
                              available_block_count)
        if self._recorder.enabled:
            self._recorder.dump(trigger="stall")

    def _on_tier_event(self, kind, **fields):
        """Cache tier callback -> flight recorder ring + trace event
        (literal names so the metric/span docs checker sees them)."""
        if kind == "demote":
            self._recorder.record("kv_tier_demote", **fields)
            _tracing.event("kv_tier_demote", **fields)
        elif kind == "tier_promote":
            # one aggregated promote BATCH (the whole tier-chain walk
            # of an attach or a prefetch tick): its wall time is split
            # OUT of the admission span into this dedicated event, so
            # the phase-tiling invariant holds — admission no longer
            # absorbs promotion time it didn't spend. Overlapped
            # batches (prefetch-ahead) also feed the overlap histogram:
            # copy time hidden behind device execution.
            if fields.get("overlapped"):
                dur = float(fields.get("dur_s", 0.0))
                _m_promote_overlap.observe(dur)
                with self._lock:
                    self._prefetch_overlap_s += dur
            if self._promote_ctx is not None:
                fields = dict(fields, request_id=self._promote_ctx)
            self._recorder.record("tier_promote", **fields)
            _tracing.event("tier_promote", **fields)
        else:
            self._recorder.record("kv_tier_promote", **fields)
            _tracing.event("kv_tier_promote", **fields)

    # ---- tier prefetch-ahead (memory-flat long-context round) -----------
    def _tier_prefetch_tick(self):
        """Promote the next queued requests' cold tier blocks into the
        device pool — called right after a round's dispatch is issued,
        so the host-side tier decodes overlap the device execution
        (pure host work: no device state is read or written). MOVE
        semantics are untouched — `prefetch_promote` runs the same
        promote walk an attach would, just earlier; a prefetched block
        that is reclaimed before admission simply re-promotes (or
        re-computes) on attach, token-identically. Budgeted by the
        FREE list only: prefetch fills idle capacity and never
        reclaims retained content from live traffic."""
        if not self._prefetch_look or self.cache.tier is None:
            return
        with self._lock:
            if self._sched is not None:
                # front-door lanes reorder admission: ask the
                # scheduler for its likely-next candidates
                # (LaneScheduler.peek — advisory order, no pops, no
                # rate charges). A scheduler without a peek hook
                # keeps the old skip behavior.
                peek = getattr(self._sched, "peek", None)
                if peek is None:
                    return
                heads = [r for r in peek(time.perf_counter(),
                                         self._prefetch_look)
                         if r.rid not in self._prefetch_done]
            else:
                heads = [r for r in self._queue[:self._prefetch_look]
                         if r.rid not in self._prefetch_done]
        budget = self.cache.free_block_count
        for r in heads:
            if budget <= 0:
                break
            prompt = (r.resume_ids if r.resume_ids is not None
                      else r.ids)
            hashes, _tokens, _nbytes = self.cache.prefetch_promote(
                prompt, limit_blocks=budget)
            if hashes:
                budget -= len(hashes)
                _m_prefetch_issued.inc(len(hashes))
                with self._lock:
                    self._prefetch_issued += len(hashes)
                    self._prefetched.setdefault(
                        r.rid, set()).update(hashes)
            else:
                # dry walk: nothing tiered (left) along this chain —
                # skip the rid until settlement, so an idle queue
                # doesn't re-hash long prompts every round
                with self._lock:
                    self._prefetch_done.add(r.rid)

    def _settle_prefetch_locked(self, rid):
        """Admission settlement: prefetched blocks still device-
        resident are HITS (their promotion wall time was hidden);
        blocks pool pressure reclaimed meanwhile are wasted. Caller
        holds the lock."""
        self._prefetch_done.discard(rid)
        pref = self._prefetched.pop(rid, None)
        if not pref:
            return
        hit = self.cache.device_resident_count(pref)
        wasted = len(pref) - hit
        self._prefetch_hits += hit
        self._prefetch_wasted += wasted
        if hit:
            _m_prefetch_hit.inc(hit)
        if wasted:
            _m_prefetch_wasted.inc(wasted)

    def _abandon_prefetch_locked(self, rid):
        """A queued request left without admission (timeout, stop) —
        everything prefetched for it is wasted. The blocks themselves
        stay parked in prefix-index retention and age out like any
        other published content. Caller holds the lock."""
        self._prefetch_done.discard(rid)
        pref = self._prefetched.pop(rid, None)
        if pref:
            self._prefetch_wasted += len(pref)
            _m_prefetch_wasted.inc(len(pref))

    # ---- capacity signals (ISSUE 17) ------------------------------------
    def _cap_pool(self):
        return self.cache.headroom()

    def _cap_tier(self):
        return self.cache._tier_stats()

    def _cap_queues(self):
        out = {"queue_depth": len(self._queue),
               "busy_slots": sum(1 for s in self._slots if s is not None),
               "max_slots": self.max_slots,
               "lanes": {}, "tenants": {}}
        sched = self._sched
        if sched is not None:
            try:
                out["queue_depth"] = sched.depth()
                out["lanes"] = sched.lane_depths()
                out["tenants"] = sched.tenant_depths()
            except Exception:  # noqa: BLE001 — a torn-down scheduler
                pass           # must not poison the snapshot
        return out

    def _cap_admission(self):
        info = self._last_error_info
        return {
            "sheds": self._sheds,
            "shed_queue_depth": self._shed_depth,
            "draining": self._draining,
            # structured BlockPoolExhausted pressure (r18): how short
            # the last failed allocation fell — zeroed when healthy
            "exhaustion_needed": (info or {}).get("needed", 0),
            "exhaustion_available": (info or {}).get("available", 0),
        }

    def _cap_slo(self):
        if self._slo is None:
            return {"enabled": False, "slos": []}
        rep = self._slo.report()
        return {"enabled": True, "worst": rep["worst"],
                "slos": [{"name": s["name"], "state": s["state"],
                          "burn_fast": s["burn_fast"],
                          "burn_slow": s["burn_slow"],
                          "budget_remaining": s["budget_remaining"]}
                         for s in rep["slos"]]}

    def capacity_snapshot(self):
        """One fresh `PressureSignals` snapshot — the `/capacity`
        endpoint payload and the fleet router's per-replica feed
        (schema_version 1; the ROADMAP-3 Autoscaler input)."""
        return self._capacity.sample()

    def _maybe_sample_capacity(self):
        """Round-boundary auto-sample (telemetry plane on only): a
        min-interval-gated snapshot recorded into the flight-recorder
        ring, so stall/exception dumps carry the pressure history."""
        if not self._cap_auto:
            return
        snap = self._capacity.maybe_sample()
        if snap is None:
            return
        pool = snap.get("pool", {})
        fc = snap.get("forecast", {})
        self._recorder.record(
            "capacity_sample",
            free_blocks=pool.get("free_blocks"),
            available_blocks=pool.get("available_blocks"),
            queue_depth=snap.get("queues", {}).get("queue_depth"),
            exhaustion_eta_s=fc.get("exhaustion_eta_s"))

    # ---- attribution (ISSUE 17) -----------------------------------------
    def _charge_dispatch(self, dur_s, parts):
        """Charge one dispatch's wall time to its resident requests
        and reconcile the collective-wire delta (sharded decode). The
        same `parts` drove any in-window compile charge — see
        `_on_compile_event`."""
        led = self._ledger
        if led is None or not parts:
            return
        led.charge_device(int(dur_s * 1e9), parts)
        if self._wire_mark is not None:
            total = self._decoder.wire_stats()["bytes_total"]
            delta = total - self._wire_mark
            self._wire_mark = total
            if delta > 0:
                led.charge_wire(delta, parts, kind="collective")

    def _attr_begin(self, parts):
        """Note the dispatch about to run (compile-charge target) and
        the decoder's wire-byte level before it."""
        if self._ledger is None:
            return
        self._attr_parts = parts
        if self._decoder.tp_degree > 1:
            self._wire_mark = self._decoder.wire_stats()["bytes_total"]

    @staticmethod
    def _cost_parts(pairs):
        """Apportionment rows [(tenant, rid, weight)] from (req,
        weight) pairs — weight is the request's share of the dispatch
        (tokens fed / tokens decoded / drafts verified)."""
        return [(r.meta.tenant if r.meta is not None else "default",
                 r.rid, int(w)) for r, w in pairs]

    # ---- causal tracing + SLOs (ISSUE 14) -------------------------------
    def _tr(self, req):
        """The trace-stamping attrs (trace_id / hop / cause / replica)
        one request's events, spans, and flight-recorder entries
        carry."""
        t = req.trace
        if t is None:
            return {}
        return t.attrs(replica=self.trace_name)

    def _rattr(self):
        """Replica attr for batch dispatch spans — lets the timeline
        exporter and cross-replica assembly tell in-process engines
        apart (empty off-fleet: no noise on a bare server)."""
        return ({"replica": self.trace_name}
                if self.trace_name is not None else {})

    def _slo_latency(self, kind, value_s, req, n=1):
        """Feed one ttft/itl observation (caller checked _slo)."""
        meta = req.meta
        self._slo.observe(kind, value_s=value_s, n=n,
                          lane=meta.lane if meta is not None else None,
                          tenant=(meta.tenant if meta is not None
                                  else None),
                          replica=self.trace_name)

    def _slo_avail(self, req, ok):
        """Feed one availability outcome (request finished vs failed
        terminally: quarantine / timeout / legacy dispatch failure)."""
        if self._slo is None:
            return
        meta = req.meta
        self._slo.observe("availability", good=ok,
                          lane=meta.lane if meta is not None else None,
                          tenant=(meta.tenant if meta is not None
                                  else None),
                          replica=self.trace_name)

    def _slo_goodput_round(self):
        """Per-round goodput feed: deltas of emitted vs decoded tokens
        since the last round (caller holds the lock and checked
        _slo)."""
        good0, dec0 = self._slo_good_mark
        good = max(0, self._tokens_out - good0)
        waste = max(0, (self._decoded_tokens - dec0)
                    - (self._tokens_out - good0))
        self._slo_good_mark = (self._tokens_out, self._decoded_tokens)
        if good or waste:
            self._slo.observe_counts("goodput", good, waste,
                                     replica=self.trace_name)

    def slo_report(self):
        """The /slo endpoint payload (`SLOEngine.report()` shape); the
        empty all-ok shape when the server runs without SLOs."""
        if self._slo is None:
            return {"slos": [], "worst": "ok", "paging": []}
        return self._slo.report()

    def export_timeline(self, path):
        """Write this engine's Chrome/Perfetto trace-event timeline
        (span sink + flight-recorder ring) to `path`; returns the
        event count. Fleet-wide timelines come from
        `FleetRouter.export_timeline`, which lays every replica out as
        its own process track."""
        from ..observability import timeline as _timeline

        name = self.trace_name or "engine"
        return _timeline.write_chrome_trace(
            path, recorders={name: self._recorder.events()},
            default_name=name)

    def health(self):
        """(status, detail) for /healthz: "stalled" while the watchdog
        sees pending work with no dispatch progress (503 — drain me),
        "degraded" after an engine dispatch exception — sticky only
        while UNRECOVERED: a clean recovery (first successful dispatch
        after the failure) or reset_stats() returns it to "ok", and
        the detail then carries the degradation reason it recovered
        from plus the recovery timestamp (r17)."""
        detail = {
            "engine_running": self._thread is not None,
            "progress": self._ops_progress,
            "stalls": self._watchdog.stalls if self._watchdog else 0,
        }
        if self._last_recovery is not None:
            detail["last_recovery"] = dict(self._last_recovery)
        if self._watchdog is not None and self._watchdog.stalled:
            detail["stall_timeout_s"] = self.stall_timeout_s
            return "stalled", detail
        if self._last_error is not None:
            detail["last_error"] = self._last_error
            detail["degraded_reason"] = self._last_error
            if self._last_error_info is not None:
                # machine-readable degradation (r18 satellite): the
                # seam, type, and — for pool exhaustion — the
                # structured needed/available shortfall
                detail["last_error_info"] = dict(self._last_error_info)
            return "degraded", detail
        return "ok", detail

    def liveness(self):
        """(live, detail) for /healthz/live — the ENGINE LOOP is alive
        (started, not stopped, thread running). Degraded or stalled is
        still live; dead is the fleet router's FAIL-OVER signal (its
        resident sessions re-admit elsewhere), where not-ready is
        merely its stop-routing signal. Split-health satellite, r18."""
        alive = (not self._stop and self._thread is not None
                 and self._thread.is_alive())
        return alive, {"engine_running": alive,
                       "stopped": self._stop,
                       "progress": self._ops_progress}

    def readiness(self):
        """(ready, detail) for /healthz/ready — alive AND accepting
        admissions: not draining (`set_draining`), not stalled. A
        router keeps sessions ON a not-ready replica (they finish or
        drain) but places no new ones — "drain, don't route" vs the
        liveness signal's "dead, fail over"."""
        alive, detail = self.liveness()
        stalled = self._watchdog is not None and self._watchdog.stalled
        ready = alive and not stalled and not self._draining
        detail = dict(detail, stalled=stalled, draining=self._draining,
                      warmed=self._warm_ran,
                      queue_depth=(self._sched.depth()
                                   if self._sched is not None
                                   else len(self._queue)))
        return ready, detail

    def set_draining(self, draining=True):
        """Mark the engine drain-only: /healthz/ready answers 503 (a
        router stops placing NEW sessions here) while residents keep
        decoding to completion. Liveness and the legacy /healthz are
        untouched. Returns self."""
        self._draining = bool(draining)
        self._recorder.record("draining", draining=self._draining)
        return self

    def statusz(self):
        """Live JSON engine state for /statusz: per-slot residency plus
        the full stats() blocks (pool, prefix cache, quantization,
        sharding, speculation, goodput, lanes/tenants when a front
        door is installed) and the flight-recorder/compile summaries."""
        with self._lock:
            slots = []
            for i, s in enumerate(self._slots):
                if s is None:
                    continue
                meta = s["req"].meta
                slots.append({
                    "slot": i, "request_id": s["req"].rid,
                    "seq": s["seq"], "prompt_len": int(s["prompt"].size),
                    "fed": int(s["fed"]), "tokens": len(s["toks"]),
                    "budget": s["budget"],
                    "phase": ("decode" if s["fed"] >= s["prompt"].size
                              else "prefill"),
                    "lane": meta.lane if meta else None,
                    "tenant": meta.tenant if meta else None,
                })
        status, detail = self.health()
        live, live_detail = self.liveness()
        ready, ready_detail = self.readiness()
        return {
            "server": "paged",
            "health": {"status": status, **detail},
            # split health semantics (r18): what /healthz/live and
            # /healthz/ready answer, inlined for one-stop debugging
            "liveness": {"live": live, **live_detail},
            "readiness": {"ready": ready, **ready_detail},
            "slots": slots,
            "max_slots": self.max_slots,
            "engine": self.stats(),
            "flight_recorder": self._recorder.stats(),
            "last_dump": self._recorder.last_dump,
        }

    def dump_flight_recorder(self):
        """Manual flight-recorder dump (also triggered automatically by
        a stall or an engine exception)."""
        return self._recorder.dump(trigger="manual")

    def _engine_exception(self, where, e, request_ids=()):
        """Shared dispatch-exception bookkeeping: health goes degraded
        (sticky until reset_stats), the exception counts per dispatch
        kind, and the flight recorder auto-dumps — the post-hoc record
        of the rounds that led here."""
        self._last_error = f"{where}: {type(e).__name__}: {e}"
        # structured twin of the string (r18 satellite): /statusz and
        # /healthz carry machine-readable fields — a router's passive
        # health signal parses these, not the message. Pool exhaustion
        # additionally carries its needed/available pressure fields.
        info = {"where": where, "error_type": type(e).__name__,
                "message": str(e)}
        if isinstance(e, BlockPoolExhausted):
            info["needed"] = e.needed
            info["available"] = e.available
        self._last_error_info = info
        _m_engine_exc.labels(where=where).inc()
        self._recorder.record("engine_exception", where=where,
                              error=self._last_error,
                              request_ids=list(request_ids))
        if self._recorder.enabled:
            self._recorder.dump(trigger="engine_exception")

    # ---- reliability (r17) ---------------------------------------------
    def _maybe_fault(self, seam):
        """Deterministic fault-injection point: one `is None` check
        when no plan is installed; otherwise poll the plan's seam x
        occurrence schedule and turn a scheduled fault into its effect
        (raise / simulated pool exhaustion / watchdog-visible sleep)."""
        plan = self._faults
        if plan is None:
            return
        f = plan.poll(seam)
        if f is None:
            return
        with self._lock:
            self._faults_injected += 1
        _m_fault_injected.labels(seam=seam).inc()
        self._recorder.record("fault_injected", seam=seam, kind=f.kind,
                              occurrence=f.index)
        _tracing.event("fault_injected", seam=seam, kind=f.kind,
                       occurrence=f.index)
        if f.kind == "slow":
            time.sleep(f.delay_s)
            return
        if f.kind == "exhausted":
            raise BlockPoolExhausted(
                f"injected fault at seam '{seam}' (occurrence "
                f"{f.index}): simulated pool exhaustion")
        raise plan.make_fault(f)

    def _recover_slot(self, i, where):
        """Snapshot one implicated slot for retry (the recovery
        ladder's requeue step): roll the sequence back to its DURABLE
        length (K/V provably written by completed dispatches — the
        failing dispatch may not have written what `ensure_many`
        already grew room for), publish the live prefix through the
        swap-out machinery when prefix caching is on, free the slot,
        and hand back the request with its resume state (generated
        tokens + resume prompt), exactly the preemption shape the r12
        parity suite proves token-identical. Returns None when the
        slot already emptied (the drain completed its request)."""
        s = self._slots[i]
        if s is None:
            return None
        seq, req = s["seq"], s["req"]
        toks = s["toks"]
        in_decode = s["fed"] >= s["prompt"].size
        durable = (s["pos"] + len(toks) - 1 if in_decode and toks
                   else int(s["fed"]))
        known = (np.concatenate([req.ids, np.asarray(toks, np.int32)])
                 if toks else req.ids)
        if self.cache.has_seq(seq):
            live = self.cache.seq_len(seq)
            durable = max(0, min(live, durable))
            if durable < live:
                self.cache.truncate_seq(seq, durable)
            if self.enable_prefix_cache and durable > 0:
                self.cache.swap_out_seq(seq, known[:durable])
            else:
                self.cache.free(seq)
        self._worst.pop(seq, None)
        self._slots[i] = None
        self._sp_store.clear_slot(i)
        req.gen0 = tuple(toks)
        req.resume_ids = known
        if req.trace is not None:
            # causal tracing: a fault-retry requeue starts a new hop —
            # the next residency's events carry hop+1 / cause "retry"
            req.trace = req.trace.child("retry")
        self._recorder.record(
            "recover_requeue", request_id=req.rid, slot=i, seq=seq,
            where=where, tokens_done=len(toks), durable=int(durable),
            **self._tr(req))
        _tracing.event("recover_requeue", request_id=req.rid, slot=i,
                       seq=seq, where=where, **self._tr(req))
        return req

    def _quarantine_slot(self, i, where, e, failures):
        """Give up on ONE request: fail its future with a diagnostic
        naming the fault seam, free its slot and blocks, and count it.
        Everything co-resident is untouched."""
        s = self._slots[i]
        seq, req = s["seq"], s["req"]
        if self.cache.has_seq(seq):
            self.cache.free(seq)
        self._worst.pop(seq, None)
        self._slots[i] = None
        self._sp_store.clear_slot(i)
        err = QuarantinedRequest(req.rid, where, failures, e)
        with self._lock:
            self._quarantined += 1
        _m_quarantined.inc()
        if self._journal is not None:
            self._journal.record_done(req.rid, "quarantined")
        self._recorder.record("quarantine", request_id=req.rid, slot=i,
                              seq=seq, seam=where, failures=failures,
                              error=f"{type(e).__name__}: {e}",
                              **self._tr(req))
        cost = (self._ledger.request_done(req.rid)
                if self._ledger is not None else None)
        _tracing.event("quarantined", request_id=req.rid, slot=i,
                       seam=where, failures=failures, cost=cost,
                       **self._tr(req))
        self._slo_avail(req, False)
        _logger.error("quarantined request %s after %d consecutive "
                      "failure(s) at seam %s: %s", req.rid, failures,
                      where, e)
        req.future.set_exception(err)

    def _dispatch_failure(self, where, e, slot_idx):
        """The engine's dispatch-exception path. With recovery OFF,
        the legacy blast radius: every request in the failing dispatch
        fails. With the recovery ladder ON (default): snapshot every
        implicated request through the swap-out machinery and requeue
        it at the FRONT of its queue, quarantine at most ONE request
        whose consecutive-failure streak crossed the policy threshold
        (highest streak, lowest slot on ties), rebuild the dispatch
        state (async chain, device-arg caches), and back off capped-
        exponentially before the loop retries."""
        rids = [self._slots[i]["req"].rid for i in slot_idx
                if self._slots[i] is not None]
        self._engine_exception(where, e, rids)
        if self._recovery is None:
            for i in slot_idx:
                s = self._slots[i]
                if s is None:
                    continue
                if self.cache.has_seq(s["seq"]):
                    self.cache.free(s["seq"])
                self._worst.pop(s["seq"], None)
                self._slo_avail(s["req"], False)
                if self._ledger is not None:
                    self._ledger.request_done(s["req"].rid)
                s["req"].future.set_exception(e)
                self._slots[i] = None
                self._sp_store.clear_slot(i)
            return
        pol = self._recovery
        # async: resolve the round already in flight FIRST, so the
        # resume snapshots include its tokens (it dispatched before
        # the failure and its outputs are real)
        self._drain_pending()
        with self._lock:
            self._dispatch_retries += 1
            self._consec_failures += 1
            consec = self._consec_failures
        _m_dispatch_retries.inc()
        live = [i for i in slot_idx if self._slots[i] is not None]
        for i in live:
            rid = self._slots[i]["req"].rid
            self._fault_streak[rid] = self._fault_streak.get(rid, 0) + 1
        suspects = [i for i in live
                    if self._fault_streak[self._slots[i]["req"].rid]
                    >= pol.quarantine_after]
        if suspects:
            victim = max(suspects, key=lambda i: (
                self._fault_streak[self._slots[i]["req"].rid], -i))
            streak = self._fault_streak.pop(
                self._slots[victim]["req"].rid)
            self._quarantine_slot(victim, where, e, streak)
            live.remove(victim)
        requeued = []
        for i in live:
            req = self._recover_slot(i, where)
            if req is not None:
                requeued.append(req)
        with self._lock:
            if self._sched is not None:
                now = time.perf_counter()
                # requeue() prepends: reversed keeps original order
                for req in reversed(requeued):
                    self._sched.requeue(req, now)
            else:
                for req in reversed(requeued):
                    self._queue.insert(0, req)
                _m_queue_depth.labels(server="paged").set(
                    len(self._queue))
            self._lock.notify()
        # rebuild dispatch state: the double-buffer chain and the
        # steady-state device-argument caches may name freed slots
        self._pending = None
        self._carry = None
        self._args_cache = None
        self._tables_cache = None
        delay = pol.backoff_s(consec)
        if delay > 0:
            with self._lock:
                if not self._stop:
                    self._lock.wait(timeout=delay)

    def _dispatch_ok(self, rids):
        """Success bookkeeping of the recovery ladder: reset the
        dispatched requests' failure streaks, and if this is the first
        success after >= 1 failure, record a CLEAN RECOVERY — health
        returns degraded -> ok, timestamped for /statusz."""
        if self._recovery is None or (self._consec_failures == 0
                                      and not self._fault_streak):
            return
        for rid in rids:
            self._fault_streak.pop(rid, None)
        if self._consec_failures:
            with self._lock:
                self._last_recovery = {
                    "ts": time.time(),
                    "recovered_from": self._last_error,
                    "failures": self._consec_failures,
                }
                self._consec_failures = 0
                self._recoveries += 1
                self._last_error = None  # degraded -> ok
                self._last_error_info = None
            _m_recoveries.inc()
            self._recorder.record(
                "recovered",
                failures=self._last_recovery["failures"],
                recovered_from=self._last_recovery["recovered_from"])
            _tracing.event("recovered",
                           failures=self._last_recovery["failures"])
            _logger.warning(
                "engine recovered after %d failed dispatch(es): %s",
                self._last_recovery["failures"],
                self._last_recovery["recovered_from"])

    def _fail_timeout_req(self, req, now):
        """Fail one expired request (already detached from any queue
        or slot). Caller holds the lock."""
        self._abandon_prefetch_locked(req.rid)
        self._timeouts += 1
        _m_timeouts.inc()
        if self._journal is not None:
            self._journal.record_done(req.rid, "timeout")
        self._recorder.record("request_timeout", request_id=req.rid,
                              waited_s=round(now - req.t_submit, 4),
                              timeout_s=req.timeout_s, **self._tr(req))
        cost = (self._ledger.request_done(req.rid)
                if self._ledger is not None else None)
        _tracing.event("request_timeout", request_id=req.rid,
                       waited_s=now - req.t_submit, cost=cost,
                       **self._tr(req))
        self._slo_avail(req, False)
        req.future.set_exception(RequestTimeout(
            req.rid, now - req.t_submit, req.timeout_s))

    def _expire_timeouts_locked(self, now):
        """Cancel every queued or resident request past its
        per-request timeout_s — SLOT-FREEING: a resident victim's
        blocks return to the pool immediately. Caller holds the
        lock."""
        def dead(r):
            return (r.timeout_s is not None
                    and now - r.t_submit > r.timeout_s)

        expired = [r for r in self._queue if dead(r)]
        if expired:
            for r in expired:
                self._queue.remove(r)
            _m_queue_depth.labels(server="paged").set(len(self._queue))
        if self._sched is not None:
            exp = getattr(self._sched, "expire", None)
            if exp is not None:
                expired.extend(exp(now, dead))
        for r in expired:
            self._fail_timeout_req(r, now)
        if any(s is not None and dead(s["req"]) for s in self._slots):
            self._drain_pending()  # async: host state goes authoritative
            for i, s in enumerate(self._slots):
                if s is None or not dead(s["req"]):
                    continue
                seq, req = s["seq"], s["req"]
                if self.cache.has_seq(seq):
                    self.cache.free(seq)
                self._worst.pop(seq, None)
                self._slots[i] = None
                self._sp_store.clear_slot(i)
                self._fail_timeout_req(req, now)

    def _retry_after_hint_locked(self, depth):
        """Estimated seconds until the queue drains one admission
        slot's worth of work — the AdmissionShed retry hint."""
        lat = sorted(self._lat)
        p50 = lat[len(lat) // 2] if lat else 0.25
        waves = -(-int(depth) // max(1, self.max_slots))
        return max(0.05, p50) * max(1, waves)

    def kill(self):
        """Hard-stop the engine WITHOUT resolving in-flight futures —
        the crash-simulation half of the journal recovery story: after
        kill(), a fresh server built over the same journal re-admits
        every accepted-but-unfinished request via
        `recover_from_journal`. (Graceful shutdown is `stop()`, which
        fails queued futures so no client hangs.)"""
        with self._lock:
            self._stop = True
            self._lock.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=120)
            self._thread = None
        if self._watchdog is not None:
            self._watchdog.stop()
        if self.exporter is not None:
            self.exporter.stop()
        if self._journal is not None:
            self._journal.flush()

    def recover_from_journal(self, journal=None):
        """Re-admit every accepted-but-unfinished request recorded in
        `journal` (default: the server's own). Each re-admission
        resumes from its recorded prompt + emitted tokens with its
        ORIGINAL seed, budget and sampling params, so — the decode
        stack being deterministic — the completed output is
        token-identical to a run that never crashed. Requests whose
        recorded state already satisfies a stop condition (budget
        reached, EOS/stop token emitted) resolve immediately.

        Returns {rid: Future}. Call before or after start()."""
        j = journal if journal is not None else self._journal
        if j is None:
            raise ValueError("no journal: pass one or build the "
                             "server with journal=")
        out = {}
        for ent in j.interrupted():
            if ent.get("trace"):
                # causal tracing: a crash-restart re-admission is a
                # new hop of the SAME trace (cause "retry")
                ent = dict(ent)
                ent["trace"] = TraceContext.from_dict(
                    ent["trace"]).child("retry").to_dict()
            out[ent["rid"]] = self.admit_journal_entry(ent)
        return out

    def admit_journal_entry(self, ent, on_token=None):
        """Re-admit ONE journal-shape session entry (the dict
        `SessionJournal.entry_for`/`interrupted()` produce: rid, ids,
        gen0, budget, seed, sampling, timeout_s, meta?) and return its
        Future — the replica-facing takeover hook (fleet round): a
        router re-places a dead or drained replica's session here with
        the ROUTER-journaled tokens folded into gen0, and the decode
        stack's determinism (counter-based PRNG resuming at step
        len(gen0), residency-invariant positions) makes the completed
        output token-identical to the run that was never interrupted.
        An entry whose recorded tokens already satisfy a stop
        condition resolves immediately. `on_token` streams the
        REMAINING tokens (the re-admission generates from len(gen0)
        on, so nothing already delivered is replayed to the client)."""
        req = self._build_resume_req(ent)
        req.on_token = on_token
        done = self._journal_terminal_reason(req)
        if done is not None:
            # the interruption lost only the terminal record: the
            # request is already complete — resolve without admitting
            if self._journal is not None:
                self._journal.record_done(req.rid, done)
            req.future.set_result(np.concatenate(
                [req.ids, np.asarray(req.gen0, np.int32)])
                if req.gen0 else req.ids.copy())
            return req.future
        with self._lock:
            if self._stop:
                raise RuntimeError("server stopped")
            if self._sched is not None:
                self._sched.on_submit(req, time.perf_counter())
            else:
                self._queue.append(req)
                _m_queue_depth.labels(server="paged").set(
                    len(self._queue))
            if self._journal is not None:
                # re-accept (under the lock, before the loop can
                # admit) with gen0 folded, so a second crash
                # resumes from here, not from the original prompt
                self._journal.record_accept(req)
            self._lock.notify()
        self._recorder.record("journal_readmit", request_id=req.rid,
                              tokens_done=len(req.gen0),
                              **self._tr(req))
        _tracing.event("journal_readmit", request_id=req.rid,
                       tokens_done=len(req.gen0), **self._tr(req))
        return req.future

    # ---- fleet host ops (r18) ------------------------------------------
    def _run_host_ops_locked(self):
        """Execute queued host ops on the engine thread (caller holds
        the lock, the in-flight round is drained): each op may touch
        the cache device arrays safely because nothing else ever does
        between round boundaries."""
        ops, self._host_ops = self._host_ops, []
        for fn, fut in ops:
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001 — the op's
                fut.set_exception(e)    # error belongs to its caller

    def _fail_host_ops_locked(self, exc):
        ops, self._host_ops = self._host_ops, []
        for _fn, fut in ops:
            if not fut.done():
                fut.set_exception(exc)

    def run_host_op(self, fn, timeout=None):
        """Run `fn()` on the ENGINE thread at the next round boundary
        (under the engine lock, with any async round drained) and
        return its result — the safe way for another thread to touch
        the paged cache's device arrays (migration import/export). On
        a not-yet-started server the op runs inline. Never call from
        an engine callback (on_token/scheduler) — that deadlocks."""
        with self._lock:
            if self._stop:
                raise RuntimeError("server stopped")
            if self._thread is None:
                return fn()
            f = Future()
            self._host_ops.append((fn, f))
            self._lock.notify()
        return f.result(timeout=timeout)

    def export_session(self, rid, include_kv=True):
        """Planned-migration SOURCE hook (fleet round): atomically
        detach one request — resident (preempt-style swap-out, its
        live K/V published through the prefix index when caching is
        on) or still queued — and return `(entry, kv_payload)`.
        `entry` is the journal-shape resume state
        `admit_journal_entry` re-admits on the target replica;
        `kv_payload` is `PagedKVCache.export_prefix` of the swapped-
        out chain (None when caching is off, the request never
        prefilled, or include_kv=False) — imported on the target, the
        session resumes with ZERO prefill recompute. The request's
        future on THIS server is abandoned (the router owns the
        client-facing future) and its journal entry closes with
        reason "migrated". Raises KeyError for an unknown or already-
        finished rid."""
        def op():
            for i, s in enumerate(self._slots):
                if s is not None and s["req"].rid == rid:
                    req = self._preempt_slot_locked(i, why="migration")
                    if req is None:
                        break  # the drain completed it: fall through
                    ent = SessionJournal.entry_for(req)
                    payload = None
                    if include_kv and self.enable_prefix_cache:
                        payload = self.cache.export_prefix(
                            req.resume_ids)
                    if payload is not None and self._ledger is not None:
                        # migration wire bytes, charged export-side to
                        # the departing session's tenant
                        nbytes = (_payload_nbytes(payload["k"])
                                  + _payload_nbytes(payload["v"]))
                        self._ledger.charge_wire(
                            nbytes, self._cost_parts([(req, 1)]),
                            kind="migration")
                    if self._journal is not None:
                        self._journal.record_done(rid, "migrated")
                    if self._ledger is not None:
                        # the session leaves this replica — close its
                        # per-request view (tenant window totals stay)
                        self._ledger.request_done(rid)
                    self._recorder.record(
                        "migrate_out", request_id=rid,
                        tokens_done=len(req.gen0),
                        kv_tokens=(len(payload["tokens"])
                                   if payload else 0), **self._tr(req))
                    _tracing.event("migrate_out", request_id=rid,
                                   tokens_done=len(req.gen0),
                                   **self._tr(req))
                    return ent, payload
            req = None
            if self._sched is not None:
                exp = getattr(self._sched, "expire", None)
                if exp is not None:
                    hits = exp(time.perf_counter(),
                               lambda r: r.rid == rid)
                    req = hits[0] if hits else None
            else:
                req = next((q for q in self._queue if q.rid == rid),
                           None)
                if req is not None:
                    self._queue.remove(req)
                    _m_queue_depth.labels(server="paged").set(
                        len(self._queue))
            if req is None:
                raise KeyError(
                    f"unknown or already-finished request {rid!r} in "
                    f"export_session()")
            ent = SessionJournal.entry_for(req)
            if self._journal is not None:
                self._journal.record_done(rid, "migrated")
            if self._ledger is not None:
                self._ledger.request_done(rid)
            self._recorder.record("migrate_out", request_id=rid,
                                  tokens_done=len(req.gen0),
                                  kv_tokens=0, **self._tr(req))
            return ent, None
        return self.run_host_op(op)

    def import_kv_payload(self, payload, owner=None):
        """Planned-migration TARGET hook: install an `export_prefix`
        payload into this server's pool (on the engine thread — see
        `run_host_op`) so the follow-up `admit_journal_entry` attaches
        it instead of re-prefilling. Returns tokens imported; raises
        BlockPoolExhausted when the pool cannot hold the chain (the
        router then falls back to plain journal replay). `owner` is
        the attribution (tenant, rid) the imported blocks' residency
        charges to on THIS replica."""
        return self.run_host_op(
            lambda: self.cache.import_prefix(payload, owner=owner))

    def _build_resume_req(self, ent):
        """One journal entry -> a resume-state `_Req` (bypasses
        submit(): the recorded seed must win over auto-derivation)."""
        sampling = (SamplingParams(**{k: tuple(v) if isinstance(v, list)
                                      else v
                                      for k, v in ent["sampling"].items()})
                    if ent.get("sampling") else self._default_sampling)
        if sampling.stop_strings and self._detok is None:
            raise ValueError(
                f"journal request {ent['rid']!r} uses stop_strings but "
                f"this server has no detokenizer (pass detokenize=)")
        meta = None
        if ent.get("meta"):
            m = ent["meta"]
            meta = RequestMeta(lane=m.get("lane", "interactive"),
                               tenant=m.get("tenant", "default"),
                               deadline_s=m.get("deadline_s"),
                               cost=int(m.get("cost", 0)))
        req = _Req(ids=np.asarray(ent["ids"], np.int32),
                   future=Future(), t_submit=time.perf_counter(),
                   rid=ent["rid"], sampling=sampling, meta=meta,
                   timeout_s=ent.get("timeout_s"))
        req.seed = int(ent["seed"])
        req.budget = int(ent["budget"])
        # causal tracing: a journal-shape entry carries the trace
        # context across restarts / replicas / migrations; without one
        # (pre-r19 journal) the resumed request starts a fresh trace
        req.trace = (TraceContext.from_dict(ent["trace"])
                     if ent.get("trace") else TraceContext.mint())
        gen0 = [int(t) for t in ent.get("gen0", [])]
        if gen0:
            req.gen0 = tuple(gen0)
            req.resume_ids = np.concatenate(
                [req.ids, np.asarray(gen0, np.int32)])
        if req.timeout_s is not None:
            self._any_timeouts = True
        return req

    def _journal_terminal_reason(self, req):
        """Whether a journal-recovered request's recorded tokens
        already satisfy a stop condition (the crash lost only the
        terminal record): returns the stop reason or None."""
        if not req.gen0:
            return None
        if len(req.gen0) >= req.budget:
            return "budget"
        last = int(req.gen0[-1])
        sp = req.sampling
        if self.eos >= 0 and last == self.eos:
            return "eos"
        if sp is not None and last in getattr(sp, "stop_token_ids", ()):
            return "stop_token"
        if sp is not None and sp.stop_strings and self._detok is not None:
            tail = self._detok(list(req.gen0)[-self.stop_tail_tokens:])
            if any(s in tail for s in sp.stop_strings):
                return "stop_string"
        return None

    def set_scheduler(self, sched):
        """Install a front-door scheduler (round 12) — an object owning
        the request queues and the admission/preemption policy. The
        engine consults it instead of its FIFO queue for: submission
        routing (`on_submit`, which may raise to REJECT), candidate
        selection (`next_request`/`pop`), victim selection for
        preemption (`victims`), requeue of preempted requests
        (`requeue`), packed-prefill ordering and per-slot chunk caps
        (`prefill_plan`), and queue-depth reporting (`lane_depths`/
        `tenant_depths`/`depth`). None uninstalls; with no scheduler
        the engine runs the exact legacy reservation-FIFO path.
        Install before start() — the loop reads it unlocked."""
        if self._thread is not None:
            raise RuntimeError("install the scheduler before start()")
        self._sched = sched
        return self

    def warm_buckets(self, modes=((False, False),)):
        """Pre-compile every reachable packed-prefill jit bucket
        (round 12) so live traffic never pays an XLA compile
        mid-request. The packed chunk path specializes per
        (packed length T, plan rows P, table width) triple — all
        power-of-two bucketed, so the space is small — but WHICH
        buckets a serving window hits depends on admission/preemption
        timing (share-capped chunks, one-token cache-hit resumes,
        churn-sized plans), so a warm-traffic drive cannot enumerate
        them deterministically. Production front ends compile their
        shape buckets before taking traffic; this is that switch.

        Each bucket is compiled by ONE synthetic dispatch whose
        positions are all packing pad (-1), so every write lands in
        the pool's reserved trash block and no sequence, sampling, or
        cache state changes. `modes`: the (any_sampled, any_penalties)
        static pairs to compile (default: the all-greedy fast path;
        pass `[(False, False), (True, False)]` etc. for sampled
        traffic). Call before `start()` — the loop owns the cache
        arrays once it runs. Returns the number of variants compiled."""
        if self._thread is not None:
            raise RuntimeError(
                "warm_buckets must run before start() (the engine loop "
                "owns the cache arrays once it is running)")
        if self._unified:
            # the unified loop never dispatches packed_prefill — its
            # bucket space is the combined-round (T, P) family
            n = self._warm_unified_buckets(modes)
            self._warm_ran = True
            return n
        jnp = self._jnp
        align = self._pack_align
        # sp-sharded prefill reaches sp x the replica budget per
        # dispatch (the _prefill_packed plan), so the reachable (T, P)
        # bucket family scales with it
        budget = self.prefill_chunk_tokens * self._sp_degree
        pairs = set()
        for rows in range(1, min(self.max_slots, budget) + 1):
            P = 1
            while P < rows:
                P *= 2
            # packed length range for a plan of `rows` chunks: each
            # region is align*ceil(n_i/align) with n_i >= 1 and
            # sum(n_i) <= budget, so off spans [rows*align, the
            # one-fat-chunk worst case]
            off_max = (rows - 1) * align + align * (
                -(-(budget - rows + 1) // align))
            T = align
            while T < rows * align:
                T *= 2
            while True:
                pairs.add((T, P))
                if T >= off_max:
                    break
                T *= 2
        widths = []
        w = 1
        while w < self._m_width:
            widths.append(w)
            w *= 2
        widths.append(self._m_width)  # the min(pow2, m_width) cap
        n = 0
        for mode in modes:
            for T, P in sorted(pairs):
                for mcap in widths:
                    # fresh args per dispatch: in penalty mode the
                    # count buffer is donated on accelerators, so a
                    # reused dict would hand back an invalidated array
                    sp = self._sp_store.warm_args(P, mode)
                    tok, stopped, kc, vc, counts = \
                        self._decoder.packed_prefill(
                            self._params, jnp.zeros((T,), jnp.int32),
                            jnp.zeros((T,), jnp.int32),
                            jnp.full((T,), -1, jnp.int32),
                            jnp.zeros((P, mcap), jnp.int32),
                            jnp.zeros((P,), jnp.int32),
                            self.cache.k_blocks, self.cache.v_blocks,
                            sp, mode)
                    # reinstall the round-tripped arrays (donated on
                    # accelerators); only trash-block rows were written
                    self._sp_store.swap_counts(counts)
                    self.cache.swap_arrays(kc, vc)
                    n += 1
        _logger.info("warm_buckets: compiled %d packed-prefill "
                     "variants (%d shape pairs x %d widths x %d modes)",
                     n, len(pairs), len(widths), len(modes))
        self._warm_ran = True
        return n

    def _warm_unified_buckets(self, modes):
        """Pre-compile the unified-round bucket space (r16): every
        reachable (packed length T, plan rows P) pair at the pinned
        table width, per sampling mode. The combined stream packs up
        to max_slots chunk/decode/verify regions, so T's worst case is
        the chunk half's worst packing plus max_slots pinned
        decode/verify regions; both axes bucket to powers of two, so
        the space stays small. Each bucket compiles via ONE synthetic
        all-pad dispatch (positions -1 route every write to the trash
        block; no sequence, sampling, carry or cache state changes)."""
        jnp = self._jnp
        align = self._pack_align
        dalign = self._verify_align
        K1 = self._uk1
        W = -(-K1 // dalign) * dalign
        budget = self.prefill_chunk_tokens
        chunk_hi = 0
        for rows in range(1, min(self.max_slots, budget) + 1):
            chunk_hi = max(chunk_hi, (rows - 1) * align + align * (
                -(-(budget - rows + 1) // align)))
        off_hi = chunk_hi + W * self.max_slots
        ts = []
        t = align
        while True:
            ts.append(t)
            if t >= off_hi:
                break
            t *= 2
        ps = []
        p = 1
        while True:
            ps.append(p)
            if p >= self.max_slots:
                break
            p *= 2
        zc = self._zero_carry_arrays()
        n = 0

        def one(T, P, mode, window):
            sp = self._sp_store.warm_unified_args(P, mode)
            (_vt, _ac, _st, kc, vc, counts, _ct, _cp,
             _cs) = self._decoder.unified_round(
                self._params, jnp.zeros((T,), jnp.int32),
                jnp.zeros((T,), jnp.int32),
                jnp.full((T,), -1, jnp.int32),
                jnp.zeros((P, self._m_width), jnp.int32),
                jnp.zeros((P, K1), jnp.int32),
                jnp.full((P,), -1, jnp.int32),
                jnp.full((P,), -1, jnp.int32),
                jnp.full((T,), -1, jnp.int32),
                jnp.full((T,), -1, jnp.int32),
                jnp.full((P,), -1, jnp.int32),
                *zc, self.cache.k_blocks, self.cache.v_blocks,
                sp, mode, window=window)
            self._sp_store.swap_counts(counts)
            self.cache.swap_arrays(kc, vc)

        for mode in modes:
            for P in ps:  # chunk-free WINDOW rounds: T pinned = P * W
                one(P * W, P, mode, True)
                n += 1
            for T in ts:  # mixed rounds: the packed (T, P) family
                for P in ps:
                    one(T, P, mode, False)
                    n += 1
        _logger.info("warm_buckets: compiled %d unified-round variants "
                     "(%d window + %d T x %d P packed, %d modes)",
                     n, len(ps), len(ts), len(ps), len(modes))
        return n

    # ---- client API ----------------------------------------------------
    def submit(self, ids, max_new_tokens=None, sampling=None, *,
               meta=None, on_token=None, timeout_s=None, rid=None,
               trace_ctx=None):
        """Enqueue one prompt (any length <= max_prompt_len; NO padding
        needed). Returns a Future resolving to the UNPADDED
        [len + generated] int32 sequence (generation stops at EOS, a
        stop condition, or the token budget).

        sampling: optional `SamplingParams` — per-request temperature /
        top-k / top-p / min-p, penalties, PRNG seed, stop token ids /
        stop strings, and token budget. Validation is EAGER (here), so
        a bad value fails the submit, not a later jitted dispatch.
        `max_new_tokens` (arg) overrides `sampling.max_new_tokens`
        overrides the server default. Stop strings require the server
        to be built with a `detokenize` callable; matching runs against
        the detokenized last `stop_tail_tokens` tokens.

        meta: optional `RequestMeta` (round 12) — lane / tenant /
        TTFT deadline / rate cost for the installed front-door
        scheduler. When a scheduler is installed the request routes
        into it (its `on_submit` may raise to reject — bounded
        queues); without one, `meta` rides along inert and the legacy
        FIFO path runs unchanged.
        on_token: optional callable `(token:int, reason:str|None)`
        invoked from the engine thread for every generated token
        (reason is None mid-stream, the stop reason on the final
        token). It must be fast and non-blocking; exceptions are
        logged and dropped, never propagated into the engine loop.
        timeout_s: per-request wall-clock deadline (r17) — a request
        still queued or resident past this many seconds after submit
        is CANCELLED: its slot and blocks are freed and its future
        fails with `RequestTimeout` (streams see reason="timeout").
        Enforced by the engine loop, so it needs a started server.
        rid: caller-pinned request id (fleet round) — a router names
        the session once and every replica-facing hook
        (`export_session`, journal records, quarantine diagnostics)
        speaks the same id. Default: auto-assigned "pN".
        trace_ctx: caller-minted `TraceContext` (ISSUE 14) — the fleet
        router/front door mints once at ITS submit so the request's
        whole fleet lifetime shares one trace_id; a bare engine mints
        its own hop-0 context here. Every event, span, flight-recorder
        entry and journal record the request touches is stamped with
        trace_id / hop / cause (+ the replica name on a fleet).

        When the server was built with `shed_queue_depth=`, a submit
        arriving at a queue already that deep raises `AdmissionShed`
        (nothing enqueued) carrying a `retry_after_s` hint."""
        if sampling is None:
            sampling = self._default_sampling
        elif not isinstance(sampling, SamplingParams):
            raise TypeError(f"sampling must be a SamplingParams, "
                            f"got {type(sampling).__name__}")
        if sampling.stop_strings and self._detok is None:
            raise ValueError(
                "stop_strings given but the server has no detokenizer "
                "(pass detokenize= to the PagedGenerationServer "
                "constructor)")
        ids = np.asarray(ids, np.int32).reshape(-1)
        if ids.size == 0 or ids.size > self.max_prompt_len:
            raise ValueError(f"prompt length {ids.size} not in "
                             f"[1, {self.max_prompt_len}]")
        budget = (max_new_tokens if max_new_tokens is not None
                  else sampling.max_new_tokens)
        budget = self.max_new if budget is None else int(budget)
        if not 1 <= budget <= self.max_new:
            raise ValueError(f"max_new_tokens {budget} not in "
                             f"[1, {self.max_new}]")
        if meta is not None and not isinstance(meta, RequestMeta):
            raise TypeError(f"meta must be a RequestMeta, "
                            f"got {type(meta).__name__}")
        if timeout_s is not None:
            timeout_s = float(timeout_s)
            if timeout_s <= 0:
                raise ValueError(f"timeout_s must be > 0, "
                                 f"got {timeout_s}")
            self._any_timeouts = True
        if trace_ctx is not None and not isinstance(trace_ctx,
                                                    TraceContext):
            raise TypeError(f"trace_ctx must be a TraceContext, "
                            f"got {type(trace_ctx).__name__}")
        req = _Req(ids=ids, future=Future(),
                   t_submit=time.perf_counter(),
                   rid=(str(rid) if rid is not None
                        else f"p{next(_req_ids)}"), sampling=sampling,
                   meta=meta, on_token=on_token, timeout_s=timeout_s,
                   trace=(trace_ctx if trace_ctx is not None
                          else TraceContext.mint()))
        # per-request PRNG stream seed: explicit seeds reproduce tokens
        # regardless of batch composition; auto seeds derive from the
        # server seed + a submission counter (distinct streams per
        # request, deterministic given arrival order)
        req.seed = (sampling.seed if sampling.seed is not None else
                    (self._seed0 + 0x9E3779B9 * (1 + next(
                        self._auto_seeds))) & 0xFFFFFFFF)
        req.budget = budget
        with self._lock:
            if self._stop:
                raise RuntimeError("server stopped")
            if self._shed_depth is not None:
                # admission shedding (r17): refuse — with a retry
                # hint — instead of queueing past the shed depth
                depth = (self._sched.depth() if self._sched is not None
                         else len(self._queue))
                if depth >= self._shed_depth:
                    self._sheds += 1
                    hint = self._retry_after_hint_locked(depth)
                    self._recorder.record(
                        "shed", request_id=req.rid, depth=depth,
                        retry_after_s=round(hint, 3))
                    raise AdmissionShed(depth, self._shed_depth, hint)
            if self._sched is not None:
                # scheduler-owned queues: on_submit may raise (bounded
                # queue rejection) — nothing is enqueued in that case
                try:
                    self._sched.on_submit(req, time.perf_counter())
                except Exception as e:
                    self._recorder.record(
                        "reject", request_id=req.rid,
                        error=f"{type(e).__name__}: {e}")
                    raise
            else:
                self._queue.append(req)
                _m_queue_depth.labels(server="paged").set(
                    len(self._queue))
            if self._journal is not None:
                # under the lock: the engine loop admits under this
                # lock too, so the accept record always precedes the
                # request's first token record
                self._journal.record_accept(req)
            self._lock.notify()
        if self._ledger is not None:
            # only ADMITTED requests enter the cost ledger (a shed or
            # bounded-queue reject raised above, nothing enqueued)
            self._ledger.request_begin(
                req.rid, meta.tenant if meta is not None else "default")
        self._recorder.record(
            "submit", request_id=req.rid, prompt_len=int(ids.size),
            budget=budget,
            lane=meta.lane if meta is not None else None,
            tenant=meta.tenant if meta is not None else None,
            **self._tr(req))
        _tracing.event("request_submitted", request_id=req.rid,
                       prompt_len=int(ids.size), budget=budget,
                       **self._tr(req))
        return req.future

    def start(self):
        if self._thread is not None:
            return self
        if self._stop:
            raise RuntimeError(
                "server was stopped; build a new PagedGenerationServer")
        self._t0 = time.perf_counter()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        with self._lock:
            self._stop = True
            self._lock.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=120)
            self._thread = None
        with self._lock:
            pending = list(self._queue)
            self._queue.clear()
            if self._sched is not None:
                pending.extend(self._sched.drain())
            for req in pending:
                self._abandon_prefetch_locked(req.rid)
                req.future.set_exception(RuntimeError("server stopped"))
        # ops plane teardown: release the port and the watchdog thread
        if self._watchdog is not None:
            self._watchdog.stop()
        if self.exporter is not None:
            self.exporter.stop()
        if self._journal is not None:
            # queued requests failed above stay journal-live on
            # purpose: a restarted server may still re-admit them
            self._journal.flush()

    def reset_stats(self):
        """Zero the measurement window — latency AND the TTFT samples
        the window's ttft percentiles derive from, so a post-reset
        stats() can never mix epochs."""
        with self._lock:
            self._lat.clear()
            self._ttft.clear()
            self._itl.clear()
            self._tokens_out = 0
            self._requests_done = 0
            self._steps = 0
            self._prefills = 0
            self._prefill_dispatches = 0
            self._active_integral = 0
            self._fill_integral = 0.0
            self._stop_reasons = dict.fromkeys(STOP_REASONS, 0)
            self._fastpath_dispatches = 0
            self._sampled_dispatches = 0
            self._spec_proposed = 0
            self._spec_accepted = 0
            self._spec_rolled_back = 0
            self._spec_dispatches = 0
            self._spec_rounds_per_slot = 0
            self._decoded_tokens = 0
            self._replayed_tokens = 0
            self._rounds = 0
            self._round_dispatch_count = 0
            self._mixed_rounds = 0
            self._overlap_s = 0.0
            self._compile_mark = _compile_tracker.mark()
            self._last_error = None  # a fresh window is healthy again
            self._last_error_info = None
            self._consec_failures = 0
            self._faults_injected = 0
            self._dispatch_retries = 0
            self._recoveries = 0
            self._quarantined = 0
            self._timeouts = 0
            self._sheds = 0
            self._preemptions = 0
            self._resumes = 0
            self._preempt_cached_tokens = 0
            self._prefetch_issued = 0
            self._prefetch_hits = 0
            self._prefetch_wasted = 0
            self._prefetch_overlap_s = 0.0
            self._sp_peak_bytes = 0
            self._deadline_requests = {}
            self._deadline_misses = {}
            self._lane_ttft = {}
            self._lane_itl = {}
            self._slo_good_mark = (0, 0)
            if self._sched is not None:
                self._sched.reset_window()
            self._decoder.reset_wire_stats()
            self._t0 = time.perf_counter()
        if self._ledger is not None:
            # window accounts zero; occupancy LEVELS carry forward so
            # both sides of each conservation equation restart at zero
            self._ledger.reset()
            self._wire_mark = (None if self._wire_mark is None else 0)

    def stats(self):
        """Window stats. ITL (inter-token latency) is per GENERATED
        token: each decode dispatch's host-visible gap since the slot's
        previous emission, amortized over the tokens it emitted (with
        multi-step scheduling, k tokens land per dispatch) — the metric
        the prefill_chunk_tokens knob trades against TTFT."""
        with self._lock:
            lat = sorted(self._lat)
            ttft = sorted(self._ttft)
            itl = sorted(self._itl)
            dt = (time.perf_counter() - self._t0) if self._t0 else 0.0
            n = len(lat)
            nt = len(ttft)
            ni = len(itl)
            pct = (lambda p: lat[min(n - 1, int(p * n))] if n else 0.0)
            tpct = (lambda p: ttft[min(nt - 1, int(p * nt))] if nt
                    else 0.0)
            ipct = (lambda p: itl[min(ni - 1, int(p * ni))] if ni
                    else 0.0)
            out = {
                "requests": n,
                "new_tokens": self._tokens_out,
                "tokens_per_sec": self._tokens_out / dt if dt else 0.0,
                "p50_ms": pct(0.50) * 1e3,
                "p90_ms": pct(0.90) * 1e3,
                "p99_ms": pct(0.99) * 1e3,
                "ttft_p50_ms": tpct(0.50) * 1e3,
                "ttft_p99_ms": tpct(0.99) * 1e3,
                "itl_p50_ms": ipct(0.50) * 1e3,
                "itl_p99_ms": ipct(0.99) * 1e3,
                "decode_steps": self._steps,
                "prefills": self._prefills,
                "prefill_dispatches": self._prefill_dispatches,
                # finished requests by why generation stopped, plus the
                # sampling pipeline's dispatch-mode split (fast path =
                # no resident sampled request: bare argmax)
                "stop_reasons": dict(self._stop_reasons),
                "sampling_fast_path_dispatches":
                    self._fastpath_dispatches,
                "sampling_sampled_dispatches": self._sampled_dispatches,
                # mean busy slots per decode step: the continuous-batching
                # analogue of the dense server's batch_fill
                "slot_fill": (self._active_integral
                              / ((self._steps or 1) * self.max_slots)),
                # mean internal fragmentation of ALLOCATED blocks while
                # decoding (sampled per dispatch; end-of-window cache
                # stats read 0 once everything is freed)
                "kv_block_fill": (self._fill_integral
                                  / (self._steps or 1)),
                # speculation accounting (round 11): zeros when the
                # server runs without a SpecConfig — schema-stable so
                # bench records and dashboards need no gating
                "speculation": {
                    "enabled": self.speculation is not None,
                    "proposed_tokens": self._spec_proposed,
                    "accepted_tokens": self._spec_accepted,
                    "rolled_back_tokens": self._spec_rolled_back,
                    "verify_dispatches": self._spec_dispatches,
                    "slot_rounds": self._spec_rounds_per_slot,
                    # fraction of proposed draft tokens accepted
                    "acceptance_rate": (self._spec_accepted
                                        / (self._spec_proposed or 1)),
                },
                # quantized serving (this round): config + byte
                # accounting, schema-stable (zeroed-but-present when
                # disabled — the speculation/frontdoor convention)
                "quantization": {
                    "enabled": (self.quantization is not None
                                or self.kv_dtype is not None),
                    "mode": self.quantization or "none",
                    "kv_dtype": self.cache.stats_kv_dtype(),
                    "kv_scale_bytes": self.cache.scale_bytes,
                    "kv_pool_bytes_total": self.cache.pool_bytes_total,
                },
                # sharded serving (serving_dist round): mesh config the
                # engine runs on — schema-stable (zeroed when disabled,
                # trivially reset-coherent: it is construction config,
                # not a window counter)
                "sharding": self._sharding_stats(),
                # tier prefetch-ahead (memory-flat long-context round):
                # blocks promoted ahead of admission and how they
                # settled — zeroed-when-disabled congruent schema,
                # reset-coherent window counters
                "tier_prefetch": {
                    "enabled": bool(self._prefetch_look),
                    "lookahead": self._prefetch_look,
                    "issued_blocks": self._prefetch_issued,
                    "hit_blocks": self._prefetch_hits,
                    "wasted_blocks": self._prefetch_wasted,
                    "hit_rate": (self._prefetch_hits
                                 / (self._prefetch_issued or 1)),
                    "overlap_promote_s": self._prefetch_overlap_s,
                },
                # quantized collectives (this round): analytic wire-byte
                # accounting of the sharded decode collectives this
                # window — bytes_total is the dispatched path,
                # bytes_baseline what bf16 would have shipped (equal
                # when quantization is off; all-zero schema for
                # unsharded / tp=1 servers), reset-coherent via
                # reset_stats -> decoder.reset_wire_stats
                "collectives": self._collectives_stats(),
                # goodput accounting (ISSUE 10): decoded device tokens
                # = emitted + speculation-rolled-back + replayed
                # (multi-step overrun discards, stop-truncated verify
                # positions, preempt-resume re-prefill of generated
                # tokens) — conservation holds per window by
                # construction at every dispatch site
                "goodput": {
                    "decoded_tokens": self._decoded_tokens,
                    "goodput_tokens": self._tokens_out,
                    "rolled_back_tokens": self._spec_rolled_back,
                    "replayed_tokens": self._replayed_tokens,
                    "goodput_ratio": (self._tokens_out
                                      / (self._decoded_tokens or 1)),
                },
                # one-kernel round (r16): dispatches-per-round on BOTH
                # engine paths (split: up to chunk-prefill + decode +
                # verify per round; unified: 1) plus the async loop's
                # hidden host-plan time — zeroed-when-disabled schema,
                # reset-coherent (mixed_rounds = rounds that contained
                # prefill AND decode/verify work, the rounds the fusion
                # actually collapses)
                "rounds": {
                    "unified": self._unified,
                    "async": self._async,
                    "rounds": self._rounds,
                    "attention_dispatches": self._round_dispatch_count,
                    "dispatches_per_round": (self._round_dispatch_count
                                             / (self._rounds or 1)),
                    "mixed_rounds": self._mixed_rounds,
                    "overlap_seconds": self._overlap_s,
                    "overlap_fraction": (self._overlap_s / dt
                                         if dt else 0.0),
                },
                # reliability (r17): fault injection + recovery ladder
                # + timeout/shed window counters — schema-stable
                # (zeros when nothing ever failed), reset-coherent
                "reliability": {
                    "recovery_enabled": self._recovery is not None,
                    "fault_plan": (self._faults.describe()
                                   if self._faults is not None
                                   else None),
                    "faults_injected": self._faults_injected,
                    "dispatch_retries": self._dispatch_retries,
                    "recoveries": self._recoveries,
                    "quarantined": self._quarantined,
                    "timeouts": self._timeouts,
                    "shed": self._sheds,
                    "consecutive_failures": self._consec_failures,
                    "last_recovery": (dict(self._last_recovery)
                                      if self._last_recovery else None),
                    "journal": (self._journal.stats()
                                if self._journal is not None else None),
                },
                # XLA compiles inside THIS stats window (the process-
                # wide compile tracker, windowed at reset_stats):
                # in_flight > 0 means a compile landed on live
                # requests — the bench's compile-clean assertion
                "compiles": {
                    "window_total": _compile_tracker.count_since(
                        self._compile_mark),
                    "window_in_flight": _compile_tracker.count_since(
                        self._compile_mark, in_flight=True),
                },
                # ops plane state (schema-stable when disabled)
                "ops": {
                    "exporter_port": (self.exporter.port
                                      if self.exporter else None),
                    "health": ("ok" if self._watchdog is None
                               and self._last_error is None
                               else self.health()[0]),
                    "stalls": (self._watchdog.stalls
                               if self._watchdog else 0),
                    "flight_recorder": self._recorder.stats(),
                },
                # admission headroom RIGHT NOW: free + LRU-reclaimable
                # blocks — the number the reservation check reasons
                # about (instantaneous, not a window counter)
                "available_blocks": self.cache.available_block_count,
                # queue depths (instantaneous): the FIFO queue without
                # a scheduler, the scheduler's lane/tenant queues with
                # one — schema-stable either way (empty dicts when no
                # front door is installed)
                "queue_depth": (len(self._queue) if self._sched is None
                                else self._sched.depth()),
                "lane_queue_depth": ({} if self._sched is None
                                     else self._sched.lane_depths()),
                "tenant_queue_depth": ({} if self._sched is None
                                       else self._sched.tenant_depths()),
                # front-door window counters (round 12): zeros when no
                # scheduler is installed — congruent schema so bench
                # records and dashboards need no gating (PR 5
                # convention), reset coherently by reset_stats()
                "frontdoor": self._frontdoor_stats_locked(),
                "wall_s": dt,
            }
            out["kv_cache"] = self.cache.stats()
        # per-tenant cost attribution (ISSUE 17): evaluated OUTSIDE
        # the engine lock (the ledger has its own) — zeroed congruent
        # schema when attribution is off, reset-coherent
        out["attribution"] = (self._ledger.stats()
                              if self._ledger is not None
                              else disabled_attribution_stats())
        # SLO burn-rate block (ISSUE 14): evaluated OUTSIDE the engine
        # lock (the SLO engine has its own) — schema-stable zeroed
        # shape when the server runs without SLOs
        out["slo"] = {
            "enabled": self._slo is not None,
            "slos": (self._slo.evaluate()
                     if self._slo is not None else []),
        }
        return out

    def cost_report(self):
        """Frozen per-tenant billing export for the current window
        (`CostReport`, ISSUE 17); None when attribution is off."""
        return self._ledger.report() if self._ledger is not None else None

    def _sharding_stats(self):
        """The stats()["sharding"] block: the ShardedEngineConfig's
        shape when sharding is on, the zeroed congruent schema when
        off (without importing serving_dist on the disabled path)."""
        if self.sharding is None:
            return {"enabled": False, "mesh_shape": {}, "tp_degree": 0,
                    "dp_degree": 0, "sp_degree": 0,
                    "collective_quant": "none",
                    "sp_attention": "none",
                    "sp_attention_bytes_peak": 0}
        out = self.sharding.stats_block()
        out["sp_attention_bytes_peak"] = self._sp_peak_bytes
        return out

    def _note_sp_peak(self, packed_tokens):
        """Analytic per-dispatch sp-attention byte accounting (memory-
        flat long-context round): compute the cross-shard fresh-K/V
        bytes THIS packed dispatch materializes per shard, keep the
        high-water mark (gauge + stats), and — for the memory-flat
        modes — assert the dispatch stays under the chunk-length-
        independent flat bound, every dispatch, on every backend (the
        invariant ring/ulysses exist to hold)."""
        from ..serving_dist.sp_attention import (sp_attention_flat_bound,
                                                 sp_attention_peak_bytes)

        mode = self._sp_attention
        peak = sp_attention_peak_bytes(mode, int(packed_tokens),
                                       **self._sp_bytes_kw)
        if mode != "allgather":
            kw = dict(self._sp_bytes_kw)
            kw.pop("sp")
            bound = sp_attention_flat_bound(mode, **kw)
            if peak > bound:
                raise AssertionError(
                    f"sp_attention={mode!r}: dispatch peak {peak} B "
                    f"exceeds the chunk-length-independent flat bound "
                    f"{bound} B — the O(block) memory invariant broke")
        if peak > self._sp_peak_bytes:
            self._sp_peak_bytes = peak
            _m_sp_peak_bytes.set(float(peak))

    def _collectives_stats(self):
        """The stats()["collectives"] block: the decoder's window wire
        bytes + the quantization config — zeroed congruent schema when
        sharding is off or tp=1 (no inter-chip wire)."""
        cq = getattr(self._decoder, "_cq", None)
        wire = self._decoder.wire_stats()
        return {
            "enabled": cq is not None,
            "mode": cq.mode if cq is not None else "none",
            "tp": self._decoder._tp,
            "bytes_total": wire["bytes_total"],
            "bytes_baseline": wire["bytes_baseline"],
            "by_collective": wire["by_collective"],
        }

    def _frontdoor_stats_locked(self):
        """The stats()["frontdoor"] block; caller holds the lock."""
        def pcts(samples):
            s = sorted(samples)
            n = len(s)
            return {
                "p50_ms": (s[min(n - 1, int(0.50 * n))] * 1e3
                           if n else 0.0),
                "p99_ms": (s[min(n - 1, int(0.99 * n))] * 1e3
                           if n else 0.0),
                "n": n,
            }

        lanes = {}
        for lane in sorted(set(self._lane_ttft) | set(self._lane_itl)):
            lanes[lane] = {
                "ttft": pcts(self._lane_ttft.get(lane, ())),
                "itl": pcts(self._lane_itl.get(lane, ())),
            }
        d_req = sum(self._deadline_requests.values())
        d_miss = sum(self._deadline_misses.values())
        out = {
            "enabled": self._sched is not None,
            "preemptions": self._preemptions,
            "resumes": self._resumes,
            "preempt_cached_tokens": self._preempt_cached_tokens,
            "deadline_requests": dict(self._deadline_requests),
            "deadline_misses": dict(self._deadline_misses),
            "deadline_miss_rate": d_miss / (d_req or 1),
            "lanes": lanes,
            "rejected": 0,
            "rate_throttled_skips": 0,
        }
        if self._sched is not None:
            out.update(self._sched.window_stats())
        return out

    # ---- engine --------------------------------------------------------
    def _outstanding_blocks(self):
        """Blocks the active slots may still demand in the worst case."""
        total = 0
        for slot in self._slots:
            if slot is not None:  # a just-picked slot holds 0 until its
                held = self.cache.blocks_held(slot["seq"])  # prefill runs
                total += max(0, self._worst[slot["seq"]] - held)
        return total

    def _worst_blocks(self, req):
        """Worst-case block reservation for `req`: the overrun slack
        covers a multi-step scan's up-to-k-1 discarded tokens and a
        verify dispatch's up-to-K speculative positions, plus one spare
        block for the (at most one) copy-on-write a prefix-cache
        attach ending mid-block can force. For a PREEMPTED request the
        resume prompt (ids + generated-so-far) replaces the prompt and
        the already-generated tokens come off the budget — the total is
        identical to the original reservation."""
        prompt = req.resume_ids if req.resume_ids is not None else req.ids
        remaining = req.budget - len(req.gen0)
        return self._blocks_for(
            prompt.size + remaining + self._overrun,
            self.block_size) + (1 if self.enable_prefix_cache else 0)

    def _install_slot_locked(self, i, req, worst):
        """Shared admission body: bind `req` to slot `i` (reservation
        already checked by the caller). A resumed request's slot is
        re-seeded with its pre-preemption tokens and its resume prompt,
        so every position/PRNG-step/budget formula downstream is
        residency-invariant."""
        seq = self._seq_counter
        self._seq_counter += 1
        self._worst[seq] = worst
        tenant = (req.meta.tenant if req.meta is not None
                  else "default")
        if self._ledger is not None:
            # tag the sequence BEFORE any block is taken: every
            # _take_blocks under this seq charges this (tenant, rid)
            self.cache.set_seq_owner(seq, tenant, req.rid)
        prompt = req.resume_ids if req.resume_ids is not None else req.ids
        # prefix caching: attach the longest cached block chain and
        # mark those tokens already-fed — the packed prefill below
        # starts at the first uncached token. A warm resume attaches
        # the blocks its own swap-out published (near-zero recompute).
        cached = 0
        if self.enable_prefix_cache:
            # prefetch settlement FIRST (hit = still device-resident at
            # this instant — the attach below would re-publish walked
            # hashes and make every block read as a hit), then stamp
            # the request id onto any tier_promote the attach fires
            self._settle_prefetch_locked(req.rid)
            self._promote_ctx = req.rid
            try:
                cached = self.cache.attach_prefix(seq, prompt)
            finally:
                self._promote_ctx = None
            if cached and self._ledger is not None:
                # attacher's saved recompute, credited at the measured
                # per-token prefill cost (publisher keeps paying the
                # blocks' residency — single-owner model)
                self._ledger.credit_prefix(tenant, req.rid, cached)
        # WARM RESUME fast path (round 12): when every context
        # position but the last attached from the cache and at least
        # one token was emitted before the preemption, the slot is
        # structurally a decode slot already — its last emitted token
        # is the decode input, position size-1 is the one position to
        # recompute, and the PRNG step counter is len(gen0). Marking
        # the prompt fully fed lets it rejoin the next DECODE dispatch
        # directly: a warm resume costs zero prefill dispatches.
        warm = (req.resume_ids is not None and bool(req.gen0)
                and cached >= prompt.size - 1)
        if warm:
            # the write block may still be shared with the prefix the
            # swap-out published — privatize it now (the same CoW
            # guard the chunked-prefill path runs per chunk)
            self.cache.prepare_write(seq, prompt.size - 1)
        # fed: prompt tokens already written to the paged cache —
        # a slot is in the PREFILL phase until fed == prompt length,
        # then decodes; t_pre0/t_last anchor the per-request prefill
        # trace span and the ITL clock
        self._slots[i] = {"seq": seq, "req": req,
                          "toks": list(req.gen0), "prompt": prompt,
                          "pos": req.ids.size, "budget": req.budget,
                          "fed": prompt.size if warm else cached,
                          "cached": cached,
                          "chunks": 0, "t_pre0": None,
                          "t_last": None}
        # scatter the request's sampling params into its slot row
        # (one device row-reset only when the request uses
        # penalties); the server-level EOS joins its stop-id set —
        # penalty counts seed from the RESUME prompt, which equals
        # prompt counts + generated counts, exactly the uninterrupted
        # run's buffer state
        self._sp_store.set_slot(i, req.sampling, req.seed,
                                eos=self.eos, prompt_ids=prompt)
        if req.resume_ids is not None:
            self._resumes += 1
            _m_resumes.inc()
            _tracing.event("resumed", request_id=req.rid, slot=i,
                           seq=seq, cached_tokens=cached,
                           tokens_done=len(req.gen0), warm=warm,
                           **self._tr(req))
        if warm and self._async:
            # the slot joins the next decode dispatch directly, so its
            # device-carry entry must hold its host-known state (no
            # unified round ever set it for this residency)
            self._seed_carry_slot(i)
        _m_slot_refills.inc()
        self._ops_progress += 1
        self._recorder.record(
            "admit", request_id=req.rid, slot=i, seq=seq,
            cached_tokens=cached, resume=req.resume_ids is not None,
            free_blocks=self.cache.available_block_count,
            **self._tr(req))
        _tracing.event("request_admitted", request_id=req.rid,
                       slot=i, seq=seq, cached_tokens=cached,
                       **self._tr(req))
        return seq

    def _preempt_slot_locked(self, i, why="pressure"):
        """Evict slot `i` mid-flight (round 12): publish its live K/V
        through the prefix-cache index (when caching is on — the
        swapped-out blocks park in LRU retention, so a prompt resume
        re-prefills ~one token unless pool pressure reclaimed them),
        release its blocks, and hand the request back for requeueing
        with its generated-so-far tokens saved as resume state. Called
        between dispatches only (no in-flight device work touches the
        victim) — in async mode the in-flight round is DRAINED first,
        so the victim's token list and published K/V are
        authoritative (the drain may complete the victim's request —
        then there is nothing to evict and this returns None)."""
        self._drain_pending()
        if self._slots[i] is None:
            return None
        s = self._slots[i]
        seq, req = s["seq"], s["req"]
        known = (np.concatenate([req.ids,
                                 np.asarray(s["toks"], np.int32)])
                 if s["toks"] else req.ids)
        cached = 0
        if self.cache.has_seq(seq):  # a never-prefilled slot owns none
            if self.enable_prefix_cache:
                cached = self.cache.swap_out_seq(seq, known)
            else:
                self.cache.free(seq)
        del self._worst[seq]
        self._slots[i] = None
        self._sp_store.clear_slot(i)
        req.gen0 = tuple(s["toks"])
        req.resume_ids = known
        req.preempts += 1
        self._preemptions += 1
        self._preempt_cached_tokens += cached
        _m_preemptions.labels(reason=why).inc()
        _m_preempt_cached.inc(cached)
        self._recorder.record(
            "preempt", request_id=req.rid, slot=i, seq=seq,
            tokens_done=len(s["toks"]), cached_tokens=cached,
            reason=why, **self._tr(req))
        _tracing.event("preempted", request_id=req.rid, slot=i, seq=seq,
                       tokens_done=len(s["toks"]), cached_tokens=cached,
                       reason=why, **self._tr(req))
        return req

    def _admit_locked(self):
        """Fill idle slots while the pool can cover each request's worst
        case; runs prefill OUTSIDE the lock? No — prefill here is called
        with the lock released by the loop; this method only picks
        (slot, req) pairs. Without a scheduler this is the legacy
        reservation-FIFO path, bit-identical to pre-round-12; with one,
        the scheduler orders candidates across lanes/tenants and may
        preempt victims to make room."""
        if self._sched is not None:
            return self._admit_sched_locked()
        picked = []
        for i, slot in enumerate(self._slots):
            if slot is not None or not self._queue:
                continue
            req = self._queue[0]
            worst = self._worst_blocks(req)
            # available counts LRU-retained prefix blocks: alloc paths
            # reclaim them before raising, so they back reservations
            if self.cache.available_block_count \
                    - self._outstanding_blocks() < worst:
                break  # head-of-line: keep arrival order under pressure
            self._queue.pop(0)
            seq = self._install_slot_locked(i, req, worst)
            picked.append((i, req, seq))
        if picked:
            _m_queue_depth.labels(server="paged").set(len(self._queue))
        return picked

    def _admit_sched_locked(self):
        """Scheduler-driven admission (round 12): ask the scheduler for
        candidates in policy order (lane weights, EDF, tenant fair
        share, rate limits); a candidate blocked on resources may name
        preemption victims — each victim is swapped out and requeued,
        then the reservation is rechecked. A lane whose candidate stays
        blocked is set aside for this pass (no cross-lane head-of-line
        blocking) and the other lanes keep admitting."""
        picked = []
        blocked: set = set()
        while True:
            now = time.perf_counter()
            req = self._sched.next_request(now, blocked)
            if req is None:
                break
            worst = self._worst_blocks(req)
            free_i = next((i for i, s in enumerate(self._slots)
                           if s is None), None)

            def short():
                return (self.cache.available_block_count
                        - self._outstanding_blocks()) < worst

            if free_i is None or short():
                # (slot, resident, remaining tokens): the remaining
                # budget feeds the policy's drain-wait hysteresis
                occupied = [(j, self._slots[j]["req"],
                             self._slots[j]["budget"]
                             - len(self._slots[j]["toks"]))
                            for j in range(self.max_slots)
                            if self._slots[j] is not None]
                for j in self._sched.victims(req, occupied, now):
                    victim = self._preempt_slot_locked(j)
                    if victim is not None:
                        self._sched.requeue(victim, now)
                    free_i = next((i for i, s in enumerate(self._slots)
                                   if s is None), None)
                    if free_i is not None and not short():
                        break
                if free_i is None or short():
                    blocked.add(getattr(req.meta, "lane", None))
                    continue
            self._sched.pop(req, now)
            seq = self._install_slot_locked(free_i, req, worst)
            picked.append((free_i, req, seq))
        return picked

    def _prefill_packed(self, pre_idx):
        """ONE packed ragged prefill dispatch: take up to
        prefill_chunk_tokens prompt tokens across the slots still
        feeding their prompts (head-of-line slot order), concatenate
        the chunks into a token-packed stream (each chunk's region
        aligned to _pack_align, the packed length bucketed to a power
        of two), bulk-grow the chunk's block tables, and run the
        packed_prefill program — K/V lands directly in each sequence's
        paged blocks. Slots whose FINAL chunk is in this dispatch
        sample their first token here (that is their TTFT)."""
        jnp = self._jnp
        align = self._pack_align
        # sp multiplies the per-dispatch chunk budget: the sp-sharded
        # packed program runs T/sp tokens per shard, so sp chunks'
        # worth of prompt tokens cost one replica-budget dispatch
        budget = self.prefill_chunk_tokens * self._sp_degree
        # chunk-budget sharing (round 12): the scheduler orders the
        # feeding slots (interactive/EDF first) and may cap each slot's
        # share of this chunk so one lane cannot monopolize the budget;
        # without a scheduler the order is slot order, uncapped
        if self._sched is not None:
            entries = self._sched.prefill_plan(
                [(i, self._slots[i]) for i in pre_idx], budget)
        else:
            entries = [(i, None) for i in pre_idx]
        plan = []  # (slot_idx, start, n, packed_offset)
        off = 0
        for i, cap in entries:
            if budget <= 0:
                break
            s = self._slots[i]
            n = min(s["prompt"].size - s["fed"], budget)
            if cap is not None:
                n = min(n, int(cap))
            if n <= 0:
                continue
            plan.append((i, s["fed"], n, off))
            off += -(-n // align) * align
            budget -= n
        if not plan:
            return
        T = align  # power-of-two bucket: compile count is logarithmic
        while T < off:  # in the packed budget, not per prompt length
            T *= 2
        # COMPACT segment rows: the dispatch carries tables only for the
        # plan's slots (row count bucketed to a power of two), so a
        # one-request churn round pays for one row's cache, not
        # max_slots of them
        P = 1
        while P < len(plan):
            P *= 2
        toks = np.zeros((T,), np.int32)
        seg = np.zeros((T,), np.int32)
        pos = np.full((T,), -1, np.int32)  # -1 marks packing pad
        sample_idx = np.zeros((P,), np.int32)
        done_rows = []  # (slot_idx, compact_row)
        for r, (i, start, n, o) in enumerate(plan):
            s = self._slots[i]
            toks[o:o + n] = s["prompt"][start:start + n]
            seg[o:o + n] = r
            pos[o:o + n] = np.arange(start, start + n, dtype=np.int32)
            if s["t_pre0"] is None:
                s["t_pre0"] = time.perf_counter()
            if start + n == s["prompt"].size:
                sample_idx[r] = o + n - 1
                done_rows.append((i, r))
        # decode-phase slots stall while this dispatch runs — the stall
        # the chunk budget exists to bound
        in_plan = {p[0] for p in plan}
        decoding = any(s is not None and j not in in_plan
                       and s["fed"] >= s["prompt"].size
                       for j, s in enumerate(self._slots))
        self._recorder.record(
            "prefill_chunk", packed=int(T), rows=len(plan),
            tokens=int(sum(p[2] for p in plan)),
            free_blocks=self.cache.available_block_count)
        if self._sp_degree > 1:
            self._note_sp_peak(T)
        parts = self._cost_parts(
            [(self._slots[i]["req"], n) for i, _start, n, _o in plan])
        self._attr_begin(parts)
        t0 = time.perf_counter()
        try:
            with _tracing.span(
                    "prefill_chunk", packed=T, segments=len(plan),
                    tokens=int(sum(p[2] for p in plan)),
                    request_ids=[self._slots[i]["req"].rid
                                 for i, *_ in plan], **self._rattr()):
                self._maybe_fault("slow_dispatch")
                self._maybe_fault("ensure_many")
                # bulk multi-sequence allocation: the whole chunk plan's
                # tables grow atomically (reservation-backed, so this
                # cannot exhaust the pool mid-plan)
                self.cache.ensure_many(
                    [(self._slots[i]["seq"], start + n)
                     for i, start, n, _ in plan])
                if self.enable_prefix_cache:
                    # copy-on-write guard: a chunk starting mid-block in
                    # an attached (shared or index-claimed) block gets a
                    # private copy before the dispatch writes into it
                    for i, start, _n, _o in plan:
                        self.cache.prepare_write(
                            self._slots[i]["seq"], start)
                # cap the table width at a power-of-two bucket of the
                # plan's deepest chunk end: early chunks of long
                # prompts attend (and the fallback gathers) only the
                # cache they can reach, and the jit re-specializes per
                # (T, width) pair — still logarithmically many
                mcap = 1
                need = max(self._blocks_for(start + n, self.block_size)
                           for _, start, n, _ in plan)
                while mcap < need:
                    mcap *= 2
                mcap = min(mcap, self._m_width)
                tables = jnp.asarray(self.cache.table_array(
                    [self._slots[plan[r][0]]["seq"]
                     if r < len(plan) else None for r in range(P)],
                    mcap))
                # per-slot sampling buffers gathered to compact plan
                # rows; token-0 sampling (PRNG step 0) runs the same
                # vectorized pipeline as decode
                done_set = {r for _, r in done_rows}
                # per-row PRNG base step: 0 for a fresh prompt; a
                # resumed request samples its next token at step
                # len(generated so far), the exact counter position an
                # uninterrupted decode would have used
                base_steps = np.array(
                    [len(self._slots[plan[r][0]]["toks"])
                     if r < len(plan) else 0 for r in range(P)],
                    np.int32)
                sp_args, sp_mode = self._sp_store.packed_args(
                    [plan[r][0] if r < len(plan) else None
                     for r in range(P)],
                    [r in done_set for r in range(P)], base_steps)
                self._maybe_fault("prefill")
                tok, stopped, kc, vc, counts = \
                    self._decoder.packed_prefill(
                        self._params, jnp.asarray(toks),
                        jnp.asarray(seg), jnp.asarray(pos), tables,
                        jnp.asarray(sample_idx), self.cache.k_blocks,
                        self.cache.v_blocks, sp_args, sp_mode)
                self._sp_store.swap_counts(counts)
                tok_h = np.asarray(tok)
                stopped_h = np.asarray(stopped)
        except Exception as e:  # noqa: BLE001 — the recovery ladder
            # (or, with recovery off, the legacy fail-the-chunk path)
            self._dispatch_failure("prefill", e,
                                   [i for i, *_ in plan])
            return
        self.cache.swap_arrays(kc, vc)
        self._dispatch_ok([self._slots[i]["req"].rid
                           for i, *_ in plan
                           if self._slots[i] is not None])
        t_now = time.perf_counter()
        self._charge_dispatch(t_now - t0, parts)
        if self._ledger is not None:
            # feed the measured prefill unit cost (EMA) — the rate the
            # prefix-cache savings credit is priced at
            self._ledger.note_prefill_cost(
                int((t_now - t0) * 1e9),
                int(sum(p[2] for p in plan)))
        self._ops_progress += 1
        if decoding:
            _m_decode_stall.observe(t_now - t0)
        _m_prefill_dispatches.inc()
        # goodput: a resumed request's chunk re-feeds already-generated
        # tokens (positions past its ORIGINAL prompt) — decoded work
        # that emits nothing, accounted as preempt replay
        replay = 0
        for i, start, n, _o in plan:
            req = self._slots[i]["req"]
            if req.resume_ids is not None:
                replay += max(0, start + n - max(start, req.ids.size))
        with self._lock:
            self._prefill_dispatches += 1
            if replay:
                self._decoded_tokens += replay
                self._replayed_tokens += replay
        if replay:
            _m_decoded.inc(replay)
            _m_replayed.inc(replay)
        for i, start, n, o in plan:
            s = self._slots[i]
            s["fed"] = start + n
            s["chunks"] += 1
        for i, r in done_rows:
            s = self._slots[i]
            req = s["req"]
            if req.ttft is None:
                # first token of the request's LIFETIME — a resumed
                # request keeps the TTFT of its first residency
                req.ttft = t_now - req.t_submit
                _m_ttft.observe(req.ttft)
                if self._slo is not None:
                    self._slo_latency("ttft", req.ttft, req)
                with self._lock:
                    self._ttft.append(req.ttft)
                    if req.meta is not None:
                        lane = req.meta.lane
                        self._lane_ttft.setdefault(lane, []).append(
                            req.ttft)
                        if req.meta.deadline_s is not None:
                            self._deadline_requests[lane] = \
                                self._deadline_requests.get(lane, 0) + 1
                            if req.ttft > req.meta.deadline_s:
                                self._deadline_misses[lane] = \
                                    self._deadline_misses.get(lane,
                                                              0) + 1
                                _m_deadline_miss.labels(lane=lane).inc()
                                _m_deadline_overage.observe(
                                    req.ttft - req.meta.deadline_s)
            if self.enable_prefix_cache:
                # every prompt K/V position is now written: index the
                # blocks so later requests can attach this prefix (a
                # resumed request publishes its resume prompt —
                # original prompt + generated-so-far)
                self.cache.publish_prefix(s["seq"], s["prompt"])
            # per-request prefill phase for the trace assembler: starts
            # at the request's FIRST chunk dispatch, ends now (its end
            # timestamp IS the request's first-token time)
            _tracing.event("prefill", request_id=req.rid,
                           ts=s["t_pre0"], dur=t_now - s["t_pre0"],
                           prompt_len=int(s["prompt"].size),
                           seq=s["seq"], chunks=s["chunks"],
                           cached_tokens=s["cached"], **self._tr(req))
            with self._lock:
                self._prefills += 1
                self._decoded_tokens += 1  # the token-0 sample
            _m_decoded.inc()
            s["t_last"] = t_now
            self._slot_token(i, int(tok_h[r]),
                             device_stopped=bool(stopped_h[r]))

    def _slot_token(self, i, tok, device_stopped=False):
        """Record one generated token for slot i; completes the request
        when generation stopped (slot freed for refill). Stop sources,
        in precedence order:
          * device_stopped — the dispatch's per-slot stop-token matrix
            matched (server EOS or a request stop_token_id);
          * stop strings — host-side: the request's stop strings
            searched in the detokenized last `stop_tail_tokens` tokens
            (the emitted tokens stay in the output);
          * budget — the request's token budget is exhausted."""
        slot = self._slots[i]
        slot["toks"].append(tok)
        if self._journal is not None:
            self._journal.record_token(slot["req"].rid, tok)
        sp = slot["req"].sampling
        reason = None
        if device_stopped:
            reason = ("eos" if self.eos >= 0 and tok == self.eos
                      else "stop_token")
        elif sp is not None and sp.stop_strings:
            # the token list spans preemption boundaries (a resumed
            # slot is re-seeded with its prior tokens), so a stop
            # string straddling a swap-out still matches
            try:
                if self._faults is not None:
                    self._maybe_fault("detokenize")
                tail = self._detok(slot["toks"][-self.stop_tail_tokens:])
            except Exception as e:  # noqa: BLE001 — a broken
                # detokenizer implicates exactly ONE request: fail it
                # with the seam named and keep every co-resident alive
                # (before r17 this killed the whole engine thread)
                self._quarantine_slot(i, "detokenize", e, 1)
                return
            if any(s in tail for s in sp.stop_strings):
                reason = "stop_string"
        if reason is None and len(slot["toks"]) >= slot["budget"]:
            reason = "budget"
        cb = slot["req"].on_token
        if cb is not None:
            # streaming (round 12): deliver from the engine thread —
            # the consumer side (frontend.stream) is bounded and
            # non-blocking; a broken callback must not kill the loop
            try:
                if self._faults is not None:
                    self._maybe_fault("stream_consumer")
                cb(tok, reason)
            except Exception:  # noqa: BLE001 — stream is best-effort
                _logger.exception(
                    "on_token callback failed for request %s "
                    "(stream dropped; generation continues)",
                    slot["req"].rid)
                slot["req"].on_token = None
        if reason is not None:
            seq, req = slot["seq"], slot["req"]
            self._ops_progress += 1
            self._fault_streak.pop(req.rid, None)
            if self._journal is not None:
                self._journal.record_done(req.rid, reason)
            self._recorder.record("request_done", request_id=req.rid,
                                  slot=i, new_tokens=len(slot["toks"]),
                                  reason=reason, **self._tr(req))
            cost = (self._ledger.request_done(req.rid,
                                              len(slot["toks"]))
                    if self._ledger is not None else None)
            _tracing.event("request_done", request_id=req.rid,
                           new_tokens=len(slot["toks"]),
                           ttft_s=req.ttft, reason=reason, cost=cost,
                           **self._tr(req))
            self._slo_avail(req, True)
            with _tracing.span("detokenize", request_id=req.rid,
                               **self._tr(req)):
                out = np.concatenate([req.ids,
                                      np.asarray(slot["toks"], np.int32)])
                self.cache.free(seq)
                del self._worst[seq]
                self._slots[i] = None
                self._sp_store.clear_slot(i)
                t_done = time.perf_counter()
                with self._lock:
                    self._lat.append(t_done - req.t_submit)
                    self._tokens_out += len(slot["toks"])
                    self._requests_done += 1
                    self._stop_reasons[reason] += 1
                _m_slot_releases.labels(reason=reason).inc()
                _m_stop_reason.labels(server="paged",
                                      reason=reason).inc()
                _m_requests_done.labels(server="paged").inc()
                _m_request_latency.labels(server="paged").observe(
                    t_done - req.t_submit)
                req.future.set_result(out)

    def _loop(self):
        try:
            self._loop_body()
        except Exception as e:  # noqa: BLE001 — an unhandled engine
            # bug (outside the per-dispatch except paths) must leave a
            # post-hoc record before the thread dies: health goes
            # degraded and the flight recorder dumps
            self._engine_exception("engine_loop", e)
            raise

    def _loop_body(self):
        while True:
            with self._lock:
                if self._stop:
                    # async: resolve the in-flight round so no future
                    # is stranded mid-stream
                    self._drain_pending()
                    self._fail_host_ops_locked(
                        RuntimeError("server stopped"))
                    return
                if self._host_ops:
                    # fleet host ops (r18): run queued migration
                    # exports/imports on THIS thread at the round
                    # boundary — the in-flight round is drained first
                    # so its write-back cannot overwrite an import
                    self._drain_pending()
                    self._run_host_ops_locked()
                if self._any_timeouts:
                    self._expire_timeouts_locked(time.perf_counter())
                self._admit_locked()
                if all(s is None for s in self._slots):
                    self._drain_pending()  # defensive: no-op when idle
                    self._lock.wait(timeout=0.1)
                    continue
            if self._unified:
                self._round_unified()
            else:
                self._round_split()

    def _note_round(self, n_dispatches, mixed):
        """Per-round dispatch accounting (r16), shared by both engine
        paths: `mixed` marks a round that carried prefill AND
        decode/verify work — the rounds the unified kernel collapses
        from up to 3 dispatches to 1."""
        with self._lock:
            self._rounds += 1
            self._round_dispatch_count += n_dispatches
            if mixed:
                self._mixed_rounds += 1
            if self._slo is not None:
                self._slo_goodput_round()
        _m_round_dispatches.observe(float(n_dispatches))
        # capacity auto-sampling (ISSUE 17): min-interval gated, so
        # this is a near-free no-op on almost every round
        self._maybe_sample_capacity()

    def _round_split(self):
        """One scheduler round of the SPLIT path (the pre-r16 loop
        body): at most one packed chunk-prefill dispatch, then one
        verify and/or one plain decode dispatch."""
        d0 = (self._prefill_dispatches + self._steps
              + self._spec_dispatches)
        # ---- packed/chunked prefill: at most ONE chunk dispatch
        # per round, interleaved with the decode dispatch below, so
        # in-flight decode never stalls longer than one chunk budget
        pre_idx = [i for i, s in enumerate(self._slots)
                   if s is not None
                   and s["fed"] < s["prompt"].size]
        if pre_idx:
            self._prefill_packed(pre_idx)
        _m_slots_busy.labels(server="paged").set(
            sum(s is not None for s in self._slots))
        # decode phase: prompt fully fed (first token sampled)
        active_idx = [i for i, s in enumerate(self._slots)
                      if s is not None
                      and s["fed"] >= s["prompt"].size]
        if active_idx:
            # speculative decoding (round 11): eligible slots propose
            # drafts and take ONE packed verification dispatch instead
            # of a decode step; the rest decode plainly below. With
            # speculation off this is a no-op and the round is the
            # exact pre-speculation path.
            spec_slots = ()
            if self._drafter is not None:
                spec_slots = self._speculate(active_idx)
            plain_idx = [i for i in active_idx
                         if i not in spec_slots
                         and self._slots[i] is not None]
            if plain_idx:
                self._decode_plain(plain_idx)
        # tier prefetch-ahead: promote the NEXT queued requests' cold
        # blocks now, before the coming round boundary's admission
        # pass runs attach_prefix (one `look` check when disabled)
        self._tier_prefetch_tick()
        d1 = (self._prefill_dispatches + self._steps
              + self._spec_dispatches)
        if d1 > d0:
            self._note_round(d1 - d0,
                             mixed=bool(pre_idx) and bool(active_idx))

    # ---- one-kernel round (r16) -----------------------------------------

    def _round_unified(self):
        """One scheduler round of the UNIFIED path: build the combined
        plan (chunk prefill rows + decode rows + verify regions), run
        it as ONE dispatch, and process the results.

        Synchronous mode processes the round immediately. ASYNC mode
        double-buffers: the round dispatched here runs on device while
        the NEXT loop iteration plans and dispatches its successor
        (inputs chained through the device carry), and only then syncs
        this round's outputs — so the host plan+dispatch work is
        hidden behind device execution, measured as overlap."""
        t0 = time.perf_counter()
        plan = self._plan_round()
        outs = self._dispatch_round(plan) if plan is not None else None
        t1 = time.perf_counter()
        # tier prefetch-ahead: the dispatch above is in flight on
        # device — promote the next queued requests' cold tier blocks
        # through this host-side window (the r16 async seam: the
        # overlapped work is pure host state, outside the overlap
        # measurement so the planner metric stays comparable)
        self._tier_prefetch_tick()
        if not self._async:
            if outs is not None:
                self._process_round(plan, outs)
            return
        pending, self._pending = self._pending, None
        if pending is not None:
            # everything since the previous iteration's sync point ran
            # while the pending round executed on device
            overlap = t1 - t0
            with self._lock:
                self._overlap_s += overlap
            _m_round_overlap.observe(overlap)
            self._process_round(*pending)
        if outs is not None:
            self._pending = (plan, outs)
        else:
            self._carry = None  # chain broken: reseed from host state

    def _drain_pending(self):
        """Async mode: resolve the in-flight round NOW so host state is
        authoritative (preemption swap-out, engine stop, idle). Breaks
        the device chain — the carry reseeds from host state at the
        next plan. No-op when nothing is in flight."""
        pending, self._pending = self._pending, None
        if pending is not None:
            self._carry = None
            self._process_round(*pending)

    def _seed_carry(self):
        """(Re)build the slot-indexed device carry from host state —
        the async chain's starting point after a start/drain. Only
        decode-phase slots have meaningful carry entries; everything
        else is written by its own round before being read."""
        jnp = self._jnp
        S = self.max_slots
        tok = np.zeros((S,), np.int32)
        posn = np.zeros((S,), np.int32)
        st = np.zeros((S,), np.int32)
        for i, s in enumerate(self._slots):
            if s is not None and s["toks"] \
                    and s["fed"] >= s["prompt"].size:
                tok[i] = s["toks"][-1]
                posn[i] = s["pos"] + len(s["toks"]) - 1
                st[i] = len(s["toks"])
        self._carry = (jnp.asarray(tok), jnp.asarray(posn),
                       jnp.asarray(st))

    def _seed_carry_slot(self, i):
        """Install one slot's host-known decode state into the live
        device carry — needed when a slot enters the decode phase
        without a unified dispatch having set its carry entry (the
        warm preempt-resume fast path joins the next decode dispatch
        directly)."""
        if self._carry is None:
            return
        s = self._slots[i]
        ct, cp, cs = self._carry
        self._carry = (ct.at[i].set(int(s["toks"][-1])),
                       cp.at[i].set(int(s["pos"] + len(s["toks"]) - 1)),
                       cs.at[i].set(len(s["toks"])))

    def _plan_round(self):
        """Build ONE combined round plan: prefill chunk rows (the
        exact `_prefill_packed` budget/ordering policy), plain decode
        rows, and speculative verify regions — each plan row is one
        ragged segment of a single packed stream, host-deterministic
        even in async mode (decode inputs are carry REFERENCES, not
        values). Returns None when no slot has work."""
        align = self._pack_align
        dalign = self._verify_align
        K1 = self._uk1
        # pinned decode/verify region width: one compiled T per round
        # composition, not per draft-count combination
        W = -(-K1 // dalign) * dalign
        rows = []
        # ---- chunk half (the _prefill_packed policy)
        pre_idx = [i for i, s in enumerate(self._slots)
                   if s is not None and s["fed"] < s["prompt"].size]
        budget = self.prefill_chunk_tokens
        if self._sched is not None and pre_idx:
            entries = self._sched.prefill_plan(
                [(i, self._slots[i]) for i in pre_idx], budget)
        else:
            entries = [(i, None) for i in pre_idx]
        for i, cap in entries:
            if budget <= 0:
                break
            s = self._slots[i]
            n = min(s["prompt"].size - s["fed"], budget)
            if cap is not None:
                n = min(n, int(cap))
            if n <= 0:
                continue
            rows.append({"kind": "chunk", "slot": i, "seq": s["seq"],
                         "start": s["fed"], "n": n,
                         "width": -(-n // align) * align,
                         "done": s["fed"] + n == s["prompt"].size})
            budget -= n
        # ---- decode / verify half: every decode-phase slot rides the
        # same dispatch (draft-free slots as dlen=0 rows)
        for i, s in enumerate(self._slots):
            if s is None or s["fed"] < s["prompt"].size:
                continue
            drafts = np.empty((0,), np.int32)
            if self._drafter is not None:
                # async note: the context is the host-KNOWN tokens —
                # up to one round stale. Stale drafts only lower the
                # acceptance rate; the verify math emits the target's
                # tokens regardless, so output is unchanged.
                remaining = s["budget"] - len(s["toks"])
                kcap = min(self._spec_k, remaining - 1)
                if kcap >= 1:
                    ctx = np.concatenate(
                        [s["req"].ids, np.asarray(s["toks"], np.int32)])
                    drafts = np.asarray(
                        self._drafter.propose(ctx, kcap),
                        np.int32).reshape(-1)[:kcap]
            rows.append({"kind": "step", "slot": i, "seq": s["seq"],
                         "drafts": drafts, "width": W,
                         "steps": len(s["toks"]),
                         "wpos": s["pos"] + len(s["toks"]) - 1})
        if not rows:
            return None
        if self._async and self._carry is None:
            self._seed_carry()
        P = 1
        while P < len(rows):
            P *= 2
        # chunk-free rounds (steady-state decode/verify — the common
        # case) take the WINDOW layout: T = P * W exactly, one pinned
        # region per row, so the dispatch runs the dense verify-window
        # trunk instead of paying the mixed-round packed geometry
        window = all(row["kind"] == "step" for row in rows)
        if window and self._async:
            # steady-state fast path: in async mode the whole device
            # argument set depends only on (slot, seq, drafts) — when
            # the signature matches the args cache, skip building the
            # plan arrays altogether (the host planner's inner loop
            # disappears from the round)
            akey = (P * W, P, tuple((row["slot"], row["seq"],
                                     row["drafts"].tobytes())
                                    for row in rows))
            if self._args_cache is not None \
                    and self._args_cache[0] == akey:
                return {"rows": rows, "T": P * W, "P": P,
                        "window": True, "akey": akey, "cached": True,
                        "n_chunk": 0, "n_step": len(rows),
                        "n_drafts": sum(int(r["drafts"].size)
                                        for r in rows)}
        if window:
            offsets = [r * W for r in range(len(rows))]
            T = P * W
        else:
            off = 0
            offsets = []
            for row in rows:
                offsets.append(off)
                off += row["width"]
            T = align  # power-of-two bucket, the chunk-path policy
            while T < off:
                T *= 2
        toks = np.zeros((T,), np.int32)
        seg = np.zeros((T,), np.int32)
        pos = np.full((T,), -1, np.int32)
        carry_map = np.full((T,), -1, np.int32)
        pos_map = np.full((T,), -1, np.int32)
        sample_idx = np.zeros((P, K1), np.int32)
        dlen = np.full((P,), -1, np.int32)
        row_slot = np.full((P,), -1, np.int32)
        steps_map = np.full((P,), -1, np.int32)
        steps = np.zeros((P,), np.int32)
        emit_rows = [False] * P
        n_chunk = n_step = n_drafts = 0
        for r, (row, o) in enumerate(zip(rows, offsets)):
            i = row["slot"]
            s = self._slots[i]
            if row["kind"] == "chunk":
                n_chunk += 1
                n = row["n"]
                start = row["start"]
                toks[o:o + n] = s["prompt"][start:start + n]
                seg[o:o + n] = r
                pos[o:o + n] = np.arange(start, start + n,
                                         dtype=np.int32)
                if s["t_pre0"] is None:
                    s["t_pre0"] = time.perf_counter()
                sample_idx[r] = o + n - 1  # every readout clamps there
                if row["done"]:
                    # token-0 samples HERE: a dlen=0 row at the
                    # resume-aware base step (0 for a fresh prompt)
                    dlen[r] = 0
                    row_slot[r] = i
                    steps[r] = len(s["toks"])
                    emit_rows[r] = True
            else:
                n_step += 1
                drafts = row["drafts"]
                k = int(drafts.size)
                n_drafts += k
                seg[o:o + 1 + k] = r
                toks[o + 1:o + 1 + k] = drafts
                if self._async:
                    # decode input token / positions / PRNG base step
                    # resolve from the device carry: round N's sample
                    # feeds round N+1 without a host sync
                    carry_map[o] = i
                    pos[o:o + 1 + k] = np.arange(0, 1 + k,
                                                 dtype=np.int32)
                    pos_map[o:o + 1 + k] = i
                    steps_map[r] = i
                else:
                    toks[o] = s["toks"][-1]
                    pos[o:o + 1 + k] = np.arange(
                        row["wpos"], row["wpos"] + 1 + k,
                        dtype=np.int32)
                    steps[r] = row["steps"]
                sample_idx[r] = o + np.minimum(np.arange(K1), k)
                dlen[r] = k
                row_slot[r] = i
                emit_rows[r] = True
        return {"rows": rows, "T": T, "P": P, "window": window,
                "toks": toks, "seg": seg,
                "pos": pos, "carry_map": carry_map, "pos_map": pos_map,
                "sample_idx": sample_idx, "dlen": dlen,
                "row_slot": row_slot, "steps_map": steps_map,
                "steps": steps, "emit_rows": emit_rows,
                "n_chunk": n_chunk, "n_step": n_step,
                "n_drafts": n_drafts}

    def _zero_carry_arrays(self):
        jnp = self._jnp
        if self._zero_carry is None:
            z = jnp.zeros((self.max_slots,), jnp.int32)
            self._zero_carry = (z, z, z)
        return self._zero_carry

    def _dispatch_round(self, plan):
        """Run one unified-round dispatch. Host-deterministic slot
        bookkeeping (fed positions, dispatch counters, proposal
        accounting) happens here; emissions wait for
        `_process_round`. Returns the device output triple (vtok,
        accepted, stopped) or None after a dispatch failure (the
        plan's slots are failed and freed)."""
        jnp = self._jnp
        rows = plan["rows"]
        # grow every row's table in one atomic call. Async step rows
        # grow to the host UPPER BOUND on the device write horizon
        # (the carry may be up to one emitted round ahead), capped by
        # the admission reservation.
        updates = []
        for row in rows:
            s = self._slots[row["slot"]]
            if row["kind"] == "chunk":
                updates.append((row["seq"], row["start"] + row["n"]))
            else:
                k = int(row["drafts"].size)
                # the last known token writes at wpos, drafts at
                # wpos+1..wpos+k (the split verify's horizon). Async:
                # the device write front may be one emitted round
                # ahead of wpos — grow by that bound too, capped at
                # the admission reservation.
                need = row["wpos"] + k + 1
                if self._async:
                    cap = s["pos"] + s["budget"] + self._overrun
                    need = min(need + 1 + self._spec_k, cap)
                updates.append((row["seq"], need))
        self._recorder.record(
            "round", packed=plan["T"], rows=len(rows),
            chunk_rows=plan["n_chunk"], step_rows=plan["n_step"],
            proposed=plan["n_drafts"],
            free_blocks=self.cache.available_block_count)
        # chunk rows weigh their fed tokens, step rows their verify
        # positions (drafts + the step token) — the same work split
        # the packed program computes
        parts = self._cost_parts(
            [(self._slots[row["slot"]]["req"],
              row["n"] if row["kind"] == "chunk"
              else row["drafts"].size + 1) for row in rows])
        plan["cost_parts"] = parts  # _process_round charges its sync
        self._attr_begin(parts)     # wait to the same rows
        t0 = time.perf_counter()
        try:
            with _tracing.span(
                    "round", packed=plan["T"], segments=len(rows),
                    chunk_rows=plan["n_chunk"],
                    step_rows=plan["n_step"],
                    request_ids=[self._slots[row["slot"]]["req"].rid
                                 for row in rows], **self._rattr()):
                self._maybe_fault("slow_dispatch")
                self._maybe_fault("ensure_many")
                self.cache.ensure_many(updates)
                if self.enable_prefix_cache and plan["n_chunk"]:
                    # CoW guard: a chunk starting mid-block in an
                    # attached (shared or index-claimed) block gets a
                    # private copy before the dispatch writes into it.
                    # A copy SWAPS a block id without changing the
                    # row's block count, so the table cache below
                    # cannot key on it — drop it for CoW-risk rounds.
                    for row in rows:
                        if row["kind"] == "chunk":
                            self.cache.prepare_write(row["seq"],
                                                     row["start"])
                    self._tables_cache = None
                P = plan["P"]
                seqs = tuple(rows[r]["seq"] if r < len(rows) else None
                             for r in range(P))
                # device-argument reuse: the table matrix changes only
                # when a row's block count grows, and in ASYNC window
                # rounds (steady-state decode — no chunk rows, inputs
                # ride the carry) the ENTIRE plan argument set is
                # invariant per (slot, seq, drafts) signature — most
                # rounds then re-dispatch already-uploaded arrays and
                # the host planner all but vanishes from the round
                tkey = (seqs, tuple(self.cache.blocks_held(s)
                                    if s is not None else 0
                                    for s in seqs))
                if self._tables_cache is not None \
                        and self._tables_cache[0] == tkey:
                    tables = self._tables_cache[1]
                else:
                    tables = jnp.asarray(self.cache.table_array(
                        list(seqs), self._m_width))
                    self._tables_cache = (tkey, tables)
                dev = akey = None
                if plan.get("cached"):
                    dev = self._args_cache[1]
                elif self._async and plan["window"]:
                    akey = (plan["T"], P, tuple(
                        (row["slot"], row["seq"],
                         row["drafts"].tobytes()) for row in rows))
                    if self._args_cache is not None \
                            and self._args_cache[0] == akey:
                        dev = self._args_cache[1]
                if dev is None:
                    slot_rows = [rows[r]["slot"] if r < len(rows)
                                 else None for r in range(P)]
                    sp_args, sp_mode = self._sp_store.unified_args(
                        slot_rows, plan["emit_rows"], plan["steps"])
                    dev = {
                        "toks": jnp.asarray(plan["toks"]),
                        "seg": jnp.asarray(plan["seg"]),
                        "pos": jnp.asarray(plan["pos"]),
                        "sample_idx": jnp.asarray(plan["sample_idx"]),
                        "dlen": jnp.asarray(plan["dlen"]),
                        "row_slot": jnp.asarray(plan["row_slot"]),
                        "carry_map": jnp.asarray(plan["carry_map"]),
                        "pos_map": jnp.asarray(plan["pos_map"]),
                        "steps_map": jnp.asarray(plan["steps_map"]),
                        "sp": sp_args, "mode": sp_mode,
                    }
                    if akey is not None:
                        self._args_cache = (akey, dev)
                sp_args, sp_mode = dev["sp"], dev["mode"]
                if sp_mode[1]:
                    # the penalty count buffer round-trips through the
                    # dispatch — refresh that one leaf per round
                    sp_args = dict(sp_args,
                                   counts=self._sp_store.counts)
                if self._async:
                    ct, cp, cs = self._carry
                else:
                    ct, cp, cs = self._zero_carry_arrays()
                self._maybe_fault("unified_round")
                (vtok, accepted, stopped, kc, vc, counts, nct, ncp,
                 ncs) = self._decoder.unified_round(
                    self._params, dev["toks"], dev["seg"], dev["pos"],
                    tables, dev["sample_idx"], dev["dlen"],
                    dev["row_slot"], dev["carry_map"], dev["pos_map"],
                    dev["steps_map"], ct, cp, cs,
                    self.cache.k_blocks, self.cache.v_blocks, sp_args,
                    sp_mode, window=plan["window"])
        except Exception as e:  # noqa: BLE001 — the recovery ladder
            # (or, with recovery off, the legacy fail-all path)
            self._carry = None
            self._dispatch_failure("unified_round", e,
                                   [row["slot"] for row in rows])
            return None
        self._sp_store.swap_counts(counts)
        self.cache.swap_arrays(kc, vc)
        self._dispatch_ok([self._slots[row["slot"]]["req"].rid
                           for row in rows
                           if self._slots[row["slot"]] is not None])
        if self._async:
            self._carry = (nct, ncp, ncs)
        self._charge_dispatch(time.perf_counter() - t0, parts)
        if self._ledger is not None and plan["n_chunk"]:
            chunk_toks = sum(row["n"] for row in rows
                             if row["kind"] == "chunk")
            self._ledger.note_prefill_cost(
                int((time.perf_counter() - t0) * 1e9), chunk_toks)
        self._ops_progress += 1
        # host-deterministic bookkeeping (valid before any sync): fed
        # positions advance, dispatch/mode counters, spec proposals
        replay = 0
        for row in rows:
            if row["kind"] != "chunk":
                continue
            s = self._slots[row["slot"]]
            s["fed"] = row["start"] + row["n"]
            s["chunks"] += 1
            req = s["req"]
            if req.resume_ids is not None:
                # a resumed request's chunk re-feeds already-generated
                # tokens — decoded work that emits nothing
                replay += max(0, row["start"] + row["n"]
                              - max(row["start"], req.ids.size))
        sampled = bool(sp_mode[0])
        with self._lock:
            if plan["n_chunk"]:
                self._prefill_dispatches += 1
            if plan["n_step"]:
                self._steps += 1
                self._active_integral += plan["n_step"]
                self._fill_integral += self.cache.block_fill()
            if sampled:
                self._sampled_dispatches += 1
            else:
                self._fastpath_dispatches += 1
            if plan["n_drafts"]:
                self._spec_dispatches += 1
                self._spec_proposed += plan["n_drafts"]
                self._spec_rounds_per_slot += sum(
                    1 for row in rows if row["kind"] == "step"
                    and row["drafts"].size)
            if replay:
                self._decoded_tokens += replay
                self._replayed_tokens += replay
        if plan["n_chunk"]:
            _m_prefill_dispatches.inc()
        if plan["n_drafts"]:
            _m_spec_verify.inc()
            _m_spec_proposed.inc(plan["n_drafts"])
        (_m_sampling_sampled if sampled else _m_sampling_fast).inc()
        if replay:
            _m_decoded.inc(replay)
            _m_replayed.inc(replay)
        _m_slots_busy.labels(server="paged").set(
            sum(s is not None for s in self._slots))
        self._note_round(1, mixed=bool(plan["n_chunk"]
                                       and plan["n_step"]))
        return (vtok, accepted, stopped)

    def _process_round(self, plan, outs):
        """Sync one unified round's outputs and emit its tokens — the
        ONLY host<->device sync point of the unified loop (async: runs
        one round late, while the successor executes). Rows whose slot
        was freed since planning (async overshoot past a stop the host
        had not yet seen) are discarded as replay, token-identically
        to the split path."""
        t_sync0 = time.perf_counter()
        vtok_h = np.asarray(outs[0])
        acc_h = np.asarray(outs[1])
        stop_h = np.asarray(outs[2])
        t_now = time.perf_counter()
        # async: the asarray above is where the host actually waits on
        # the device — busy time the dispatch-site charge missed
        self._charge_dispatch(t_now - t_sync0,
                              plan.get("cost_parts") or ())
        self._ops_progress += 1
        decoded = 0
        discarded = 0
        rolled = 0
        accepted_n = 0
        itl_updates = []
        for r, row in enumerate(plan["rows"]):
            i = row["slot"]
            s = self._slots[i]
            live = s is not None and s["seq"] == row["seq"]
            if row["kind"] == "chunk":
                if not row["done"]:
                    continue
                decoded += 1
                if not live:
                    discarded += 1
                    continue
                req = s["req"]
                if req.ttft is None:
                    # first token of the request's LIFETIME — a resumed
                    # request keeps the TTFT of its first residency
                    req.ttft = t_now - req.t_submit
                    _m_ttft.observe(req.ttft)
                    if self._slo is not None:
                        self._slo_latency("ttft", req.ttft, req)
                    with self._lock:
                        self._ttft.append(req.ttft)
                        if req.meta is not None:
                            lane = req.meta.lane
                            self._lane_ttft.setdefault(
                                lane, []).append(req.ttft)
                            if req.meta.deadline_s is not None:
                                self._deadline_requests[lane] = \
                                    self._deadline_requests.get(
                                        lane, 0) + 1
                                if req.ttft > req.meta.deadline_s:
                                    self._deadline_misses[lane] = \
                                        self._deadline_misses.get(
                                            lane, 0) + 1
                                    _m_deadline_miss.labels(
                                        lane=lane).inc()
                                    _m_deadline_overage.observe(
                                        req.ttft - req.meta.deadline_s)
                if self.enable_prefix_cache:
                    self.cache.publish_prefix(s["seq"], s["prompt"])
                _tracing.event("prefill", request_id=req.rid,
                               ts=s["t_pre0"],
                               dur=t_now - s["t_pre0"],
                               prompt_len=int(s["prompt"].size),
                               seq=s["seq"], chunks=s["chunks"],
                               cached_tokens=s["cached"])
                with self._lock:
                    self._prefills += 1
                s["t_last"] = t_now
                self._slot_token(i, int(vtok_h[r, 0]),
                                 device_stopped=bool(stop_h[r, 0]))
                continue
            # decode / verify row
            a = int(acc_h[r])
            k_r = int(row["drafts"].size)
            decoded += k_r + 1
            if not live:
                # async overshoot: the device ran one extra round for a
                # slot the host has since stopped — pure replay, plus
                # its drafts count as rolled back (conservation:
                # proposed == accepted + rolled_back)
                rolled += k_r
                discarded += 1
                continue
            if k_r and not self._async:
                # rollback FIRST (while the sequence still exists); the
                # async chain instead overwrites rejected positions at
                # the next rounds' write front (see docs/SERVING.md)
                self.cache.truncate_seq(s["seq"],
                                        row["wpos"] + a + 1)
            if k_r:
                rolled += k_r - a
                accepted_n += a
                _m_spec_accepted.inc(a)
                _m_spec_accept_rate.observe(a / k_r)
                _tracing.event("spec_round", request_id=s["req"].rid,
                               proposed=k_r, accepted=a,
                               rolled_back=k_r - a)
            t_prev = s["t_last"] if s["t_last"] is not None else t_now
            consumed = 0
            for jj in range(a + 1):
                consumed += 1
                self._slot_token(i, int(vtok_h[r, jj]),
                                 device_stopped=bool(stop_h[r, jj]))
                if self._slots[i] is None:  # stopped mid-prefix
                    break
            discarded += (a + 1) - consumed
            if self._slots[i] is not None:
                self._slots[i]["t_last"] = t_now
            per = max(t_now - t_prev, 0.0) / consumed
            lane = (s["req"].meta.lane if s["req"].meta is not None
                    else None)
            itl_updates.append((per, consumed, lane))
            if self._slo is not None:
                self._slo_latency("itl", per, s["req"], n=consumed)
            for _ in range(consumed):
                _m_itl.observe(per)
        with self._lock:
            for per, consumed, lane in itl_updates:
                self._itl.extend([per] * consumed)
                if lane is not None:
                    self._lane_itl.setdefault(lane, []).extend(
                        [per] * consumed)
            self._decoded_tokens += decoded
            self._spec_accepted += accepted_n
            self._spec_rolled_back += rolled
            if discarded:
                self._replayed_tokens += discarded
        _m_decoded.inc(decoded)
        if rolled:
            _m_spec_rolled_back.inc(rolled)
        if discarded:
            _m_replayed.inc(discarded)
        _m_goodput.set(self._tokens_out / (self._decoded_tokens or 1))

    def _decode_plain(self, active_idx):
        """One plain decode dispatch (k tokens per slot with multi-step
        scheduling) for the given decode-phase slots — the pre-round-11
        decode body, extracted so the scheduler can interleave it with
        the speculative verify dispatch."""
        jnp = self._jnp
        k = self.steps_per_dispatch
        tok = np.zeros((self.max_slots,), np.int32)
        pos = np.zeros((self.max_slots,), np.int32)
        act = np.zeros((self.max_slots,), bool)
        steps = np.zeros((self.max_slots,), np.int32)
        for i in active_idx:
            s = self._slots[i]
            tok[i] = s["toks"][-1]
            pos[i] = s["pos"] + len(s["toks"]) - 1
            act[i] = True
            steps[i] = len(s["toks"])  # PRNG step counter
        # per-slot sampling buffers + the static dispatch mode: ONE
        # jitted dispatch serves the whole mixed batch; all-greedy
        # residents take the argmax fast path
        sp_args, sp_mode = self._sp_store.step_args(steps)
        if sp_mode[0]:
            _m_sampling_sampled.inc()
        else:
            _m_sampling_fast.inc()
        with self._lock:
            if sp_mode[0]:
                self._sampled_dispatches += 1
            else:
                self._fastpath_dispatches += 1
        self._recorder.record(
            "decode_dispatch", slots=len(active_idx), k=k,
            sampled=bool(sp_mode[0]),
            free_blocks=self.cache.available_block_count)
        parts = self._cost_parts(
            [(self._slots[i]["req"], k) for i in active_idx])
        self._attr_begin(parts)
        t0 = time.perf_counter()
        try:
            with _tracing.span(
                    "decode_dispatch", k=k,
                    request_ids=[self._slots[i]["req"].rid
                                 for i in active_idx], **self._rattr()):
                self._maybe_fault("slow_dispatch")
                self._maybe_fault("ensure_many")
                # grow tables for the incoming token(s) BEFORE the
                # step writes them (k tokens starting at the feed
                # position) — inside the try so a pool error takes the
                # recovery path instead of killing the engine thread
                self.cache.ensure_many(
                    [(self._slots[i]["seq"], self._slots[i]["pos"]
                      + len(self._slots[i]["toks"]) - 1 + k)
                     for i in active_idx])
                tables = jnp.asarray(self.cache.table_array(
                    [s["seq"] if s is not None else None
                     for s in self._slots], self._m_width))
                self._maybe_fault("decode")
                if k == 1:
                    nxt, stopped, kc, vc, counts = \
                        self._decoder.step(
                            self._params, jnp.asarray(tok),
                            jnp.asarray(pos), jnp.asarray(act),
                            tables, self.cache.k_blocks,
                            self.cache.v_blocks, sp_args, sp_mode)
                    toks = np.asarray(nxt)[None]   # [1, S]
                    stops = np.asarray(stopped)[None]
                else:
                    toks, stopped, kc, vc, counts = \
                        self._decoder.multistep(k, sp_mode)(
                            self._params, jnp.asarray(tok),
                            jnp.asarray(pos), jnp.asarray(act),
                            tables, self.cache.k_blocks,
                            self.cache.v_blocks, sp_args)
                    toks = np.asarray(toks)        # [k, S]
                    stops = np.asarray(stopped)
        except Exception as e:  # noqa: BLE001 — the recovery ladder
            # (or, with recovery off, the legacy fail-all path)
            self._dispatch_failure("decode", e, list(active_idx))
            return
        self._sp_store.swap_counts(counts)
        self.cache.swap_arrays(kc, vc)
        self._dispatch_ok([self._slots[i]["req"].rid
                           for i in active_idx
                           if self._slots[i] is not None])
        t_now = time.perf_counter()
        self._charge_dispatch(t_now - t0, parts)
        self._ops_progress += 1
        decoded = toks.shape[0] * len(active_idx)
        discarded = 0
        with self._lock:
            self._steps += 1
            self._active_integral += len(active_idx)
            self._fill_integral += self.cache.block_fill()
            self._decoded_tokens += decoded
        _m_decoded.inc(decoded)
        for i in active_idx:
            s = self._slots[i]
            t_prev = s["t_last"] if s["t_last"] is not None else t_now
            consumed = 0
            for j in range(toks.shape[0]):
                consumed += 1
                self._slot_token(i, int(toks[j, i]),
                                 device_stopped=bool(stops[j, i]))
                if self._slots[i] is None:  # finished mid-scan: the
                    break  # remaining scan tokens are discarded
            discarded += toks.shape[0] - consumed  # multi-step overrun
            if self._slots[i] is not None:
                self._slots[i]["t_last"] = t_now
            # ITL: the dispatch's host-visible gap amortized over
            # the tokens it emitted for this slot
            per = max(t_now - t_prev, 0.0) / consumed
            with self._lock:
                self._itl.extend([per] * consumed)
                if s["req"].meta is not None:
                    self._lane_itl.setdefault(
                        s["req"].meta.lane, []).extend([per] * consumed)
            if self._slo is not None:
                self._slo_latency("itl", per, s["req"], n=consumed)
            for _ in range(consumed):
                _m_itl.observe(per)
        if discarded:
            with self._lock:
                self._replayed_tokens += discarded
            _m_replayed.inc(discarded)
        _m_goodput.set(self._tokens_out / (self._decoded_tokens or 1))

    def _speculate(self, active_idx):
        """Propose drafts for every eligible decode-phase slot; when
        any slot got a proposal, run ONE packed verification dispatch
        covering ALL decode-phase slots — draft-free slots ride along
        as k=0 rows whose single verify position IS their decode step,
        so a round never pays a verify AND a plain decode dispatch.
        Rounds where nobody proposes return () untouched and the loop
        takes the plain decode dispatch (the exact pre-speculation
        path, also what a disabled server always runs).

        Draft eligibility: the slot must be able to emit at least 2
        tokens (remaining budget >= 2 — with 1 left there is nothing a
        draft could add), and the drafter must propose at least one
        token for its context."""
        from ..spec_decode import build_verify_plan

        entries = []
        any_drafts = False
        empty = np.empty((0,), np.int32)
        for i in active_idx:
            s = self._slots[i]
            remaining = s["budget"] - len(s["toks"])
            kcap = min(self._spec_k, remaining - 1)
            drafts = empty
            if kcap >= 1:
                ctx = np.concatenate(
                    [s["req"].ids, np.asarray(s["toks"], np.int32)])
                drafts = np.asarray(self._drafter.propose(ctx, kcap),
                                    np.int32).reshape(-1)[:kcap]
            if drafts.size:
                any_drafts = True
            wpos = s["pos"] + len(s["toks"]) - 1
            entries.append((i, s["toks"][-1], wpos, len(s["toks"]),
                            drafts))
        if not any_drafts:
            return ()
        plan = build_verify_plan(entries, self._spec_k,
                                 self._verify_align,
                                 min_rows=self.max_slots)
        self._verify_packed(plan)
        return set(plan.slots)

    def _verify_packed(self, plan):
        """ONE packed verification dispatch for the plan's slots, then
        accept/rollback: each row's drafts were speculatively written at
        positions wpos+1..wpos+k; the dispatch returns the target's
        deterministic token per position, the accepted prefix length,
        and per-position stop flags. Accepted tokens (plus the bonus
        token) feed the normal `_slot_token` path; rejected tail
        positions roll the paged cache back via
        `PagedKVCache.truncate_seq`."""
        jnp = self._jnp
        proposed = int(sum(d.size for d in plan.drafts))
        with self._lock:
            self._spec_proposed += proposed
            self._spec_rounds_per_slot += sum(
                1 for d in plan.drafts if d.size)
        _m_spec_proposed.inc(proposed)
        self._recorder.record(
            "verify_dispatch", rows=plan.rows, proposed=proposed,
            free_blocks=self.cache.available_block_count)
        P = plan.dlen.shape[0]
        parts = self._cost_parts(
            [(self._slots[i]["req"], plan.drafts[r].size + 1)
             for r, i in enumerate(plan.slots)])
        self._attr_begin(parts)
        t0 = time.perf_counter()
        try:
            with _tracing.span(
                    "verify_dispatch", segments=plan.rows,
                    proposed=proposed,
                    request_ids=[self._slots[i]["req"].rid
                                 for i in plan.slots], **self._rattr()):
                self._maybe_fault("slow_dispatch")
                self._maybe_fault("ensure_many")
                # grow every row's table to its speculative write
                # horizon in one atomic call (reservation-backed: the
                # admission worst case includes the K-token overrun)
                self.cache.ensure_many(
                    plan.grow_updates([self._slots[i]["seq"]
                                       for i in plan.slots]))
                # FIXED table width (the decode-dispatch width, not the
                # prefill path's pow2 bucketing): verify runs every
                # round, so its jit shape must be pinned — one compiled
                # variant per sampling mode
                tables = jnp.asarray(self.cache.table_array(
                    [self._slots[plan.slots[r]]["seq"]
                     if r < plan.rows else None for r in range(P)],
                    self._m_width))
                sp_args, sp_mode = self._sp_store.verify_args(
                    [plan.slots[r] if r < plan.rows else None
                     for r in range(P)], plan.steps)
                self._maybe_fault("verify")
                vtok, accepted, stopped, kc, vc, counts = \
                    self._decoder.packed_verify(
                        self._params, jnp.asarray(plan.toks),
                        jnp.asarray(plan.seg), jnp.asarray(plan.pos),
                        tables, jnp.asarray(plan.sample_idx),
                        jnp.asarray(plan.dlen), self.cache.k_blocks,
                        self.cache.v_blocks, sp_args, sp_mode)
                vtok_h = np.asarray(vtok)
                acc_h = np.asarray(accepted)
                stop_h = np.asarray(stopped)
        except Exception as e:  # noqa: BLE001 — the recovery ladder
            # (or, with recovery off, the legacy fail-all path)
            self._dispatch_failure("verify", e, list(plan.slots))
            return
        self._sp_store.swap_counts(counts)
        self.cache.swap_arrays(kc, vc)
        self._dispatch_ok([self._slots[i]["req"].rid
                           for i in plan.slots
                           if self._slots[i] is not None])
        _m_spec_verify.inc()
        t_now = time.perf_counter()
        self._charge_dispatch(t_now - t0, parts)
        self._ops_progress += 1
        verify_discarded = 0
        with self._lock:
            self._spec_dispatches += 1
        for r, i in enumerate(plan.slots):
            s = self._slots[i]
            a = int(acc_h[r])
            k_r = int(plan.drafts[r].size)
            # rollback FIRST (while the sequence still exists): the
            # kept prefix is the last emitted token plus the accepted
            # drafts; rejected speculative positions leave the cache
            self.cache.truncate_seq(s["seq"], plan.write_pos[r] + a + 1)
            rolled = k_r - a
            if k_r:  # draft-free ride-along rows have nothing to score
                with self._lock:
                    self._spec_accepted += a
                    self._spec_rolled_back += rolled
                _m_spec_accepted.inc(a)
                _m_spec_rolled_back.inc(rolled)
                _m_spec_accept_rate.observe(a / k_r)
                _tracing.event("spec_round", request_id=s["req"].rid,
                               proposed=k_r, accepted=a,
                               rolled_back=rolled)
            t_prev = s["t_last"] if s["t_last"] is not None else t_now
            consumed = 0
            for j in range(a + 1):
                consumed += 1
                self._slot_token(i, int(vtok_h[r, j]),
                                 device_stopped=bool(stop_h[r, j]))
                if self._slots[i] is None:  # stopped mid-prefix: the
                    break  # remaining accepted tokens are discarded
            # goodput: the row computed k_r+1 verify positions — a+1
            # candidate emissions (stop-truncated remainder is replay)
            # plus k_r-a rejected drafts (rolled back above)
            with self._lock:
                self._decoded_tokens += k_r + 1
            _m_decoded.inc(k_r + 1)
            verify_discarded += (a + 1) - consumed
            if self._slots[i] is not None:
                self._slots[i]["t_last"] = t_now
            per = max(t_now - t_prev, 0.0) / consumed
            with self._lock:
                self._itl.extend([per] * consumed)
                if s["req"].meta is not None:
                    self._lane_itl.setdefault(
                        s["req"].meta.lane, []).extend([per] * consumed)
            if self._slo is not None:
                self._slo_latency("itl", per, s["req"], n=consumed)
            for _ in range(consumed):
                _m_itl.observe(per)
        if verify_discarded:
            with self._lock:
                self._replayed_tokens += verify_discarded
            _m_replayed.inc(verify_discarded)
        _m_goodput.set(self._tokens_out / (self._decoded_tokens or 1))


def measure_offered_load(server, prompts, offered_rps, duration_s):
    """Drive `server` at a target request rate for `duration_s`; returns
    the server stats plus achieved rate. `prompts`: pool of int lists,
    cycled."""
    futs = []
    t0 = time.perf_counter()
    i = 0
    while time.perf_counter() - t0 < duration_s:
        target = t0 + i / offered_rps
        now = time.perf_counter()
        if now < target:
            time.sleep(target - now)
        futs.append(server.submit(prompts[i % len(prompts)]))
        i += 1
    t_submit_end = time.perf_counter()  # the OFFER window ends here —
    # draining the queue below must not dilute the achieved rate
    for f in futs:
        f.result(timeout=600)
    out = server.stats()
    out["offered_rps"] = offered_rps
    out["achieved_rps"] = i / (t_submit_end - t0)
    return out


def measure_poisson_load(server, prompts, offered_rps, n_requests,
                         seed=0, timeout=600, max_new_tokens=None):
    """Open-loop arrival drive: submit `n_requests` prompts (cycled from
    the pool) at FIXED-SEED Poisson arrivals — exponential inter-arrival
    gaps with mean 1/offered_rps — then wait for all of them. Unlike the
    closed-loop all-upfront drain, this exercises steady-state admission
    CHURN: requests arrive while others are mid-decode, which is where
    prefill stalls live. Returns the server's stats() for the window
    plus offered/achieved rates. max_new_tokens caps each request's
    budget (the shared-prefix TTFT axis keeps decode short)."""
    rng = np.random.RandomState(seed)
    gaps = rng.exponential(1.0 / max(offered_rps, 1e-9),
                           size=int(n_requests))
    kw = {} if max_new_tokens is None \
        else {"max_new_tokens": int(max_new_tokens)}
    futs = []
    t0 = time.perf_counter()
    arrival = 0.0
    for i in range(int(n_requests)):
        arrival += gaps[i]
        now = time.perf_counter() - t0
        if now < arrival:
            time.sleep(arrival - now)
        futs.append(server.submit(prompts[i % len(prompts)], **kw))
    t_submit_end = time.perf_counter()  # offer window ends here
    for f in futs:
        f.result(timeout=timeout)
    out = server.stats()
    out["offered_rps"] = offered_rps
    out["achieved_rps"] = int(n_requests) / (t_submit_end - t0)
    return out
