"""Inference predictor (ref: paddle/fluid/inference/ + paddle.inference API).

TPU-first: a predictor is a compiled forward with donated input buffers and a
persistent params pytree on device.
"""
from __future__ import annotations

import jax
import numpy as np

from ..core.tensor import Tensor


class Config:
    def __init__(self, model_path=None, params_path=None):
        self.model_path = model_path
        self.params_path = params_path
        self._use_tpu = True

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_tpu = True

    def disable_gpu(self):
        self._use_tpu = False

    def switch_ir_optim(self, flag=True):
        pass

    def enable_memory_optim(self):
        pass


class Predictor:
    """Wraps a Layer (or pure fn) into a compiled inference callable."""

    def __init__(self, model, example_inputs=None):
        from ..nn.layer.layers import Layer
        self._layer = model if isinstance(model, Layer) else None
        self._fn = None
        if self._layer is not None:
            self._layer.eval()
            params, bufs = self._layer.functional_state()
            self._params, self._bufs = params, bufs
            layer = self._layer

            def fwd(params, bufs, *xs):
                saved = layer.functional_state()
                layer.load_functional_state(params, bufs)
                try:
                    out = layer(*[Tensor(x) for x in xs])
                finally:
                    layer.load_functional_state(*saved)
                return jax.tree_util.tree_map(
                    lambda t: t._value if isinstance(t, Tensor) else t, out,
                    is_leaf=lambda t: isinstance(t, Tensor))
            self._fn = jax.jit(fwd)
        else:
            self._fn = jax.jit(model)
            self._params, self._bufs = {}, {}

    def run(self, inputs):
        xs = [i._value if isinstance(i, Tensor) else np.asarray(i)
              for i in (inputs if isinstance(inputs, (list, tuple)) else [inputs])]
        if self._layer is not None:
            out = self._fn(self._params, self._bufs, *xs)
        else:
            out = self._fn(*xs)
        return jax.tree_util.tree_map(Tensor, out)

    __call__ = run


def create_predictor(config_or_model, example_inputs=None):
    if isinstance(config_or_model, Config):
        from ..jit import load as jit_load
        payload = jit_load(config_or_model.model_path)
        raise NotImplementedError(
            "file-based predictor requires jit.save'd layer; "
            "pass the Layer directly")
    return Predictor(config_or_model, example_inputs)
