"""Inference predictor (ref: paddle/fluid/inference/api/analysis_predictor.cc
+ paddle.inference python API).

TPU-first: the deployable artifact is a serialized StableHLO module
(jit.save's .pdmodel) + an npz params archive (.pdiparams). A Predictor
deserializes the module in a fresh process — no Python model class, no
paddle_tpu.models import — and runs it as one AOT XLA computation with the
params resident on device. The reference's Config knobs that steer CUDA/
MKLDNN engines map to device placement here; IR optimization is XLA's job.
"""
from __future__ import annotations

import jax
import numpy as np

from ..core.tensor import Tensor


class Config:
    """paddle.inference.Config (ref: analysis_config.cc). Accepts either
    Config(prog_file, params_file) or Config(model_dir) with the default
    `inference.pdmodel` names, like the reference."""

    def __init__(self, model_path=None, params_path=None):
        self.model_path = model_path
        self.params_path = params_path
        self._device = "tpu"
        self._memory_optim = True

    # --- file locations ------------------------------------------------
    def set_prog_file(self, path):
        self.model_path = path

    def set_params_file(self, path):
        self.params_path = path

    def prog_file(self):
        return self.model_path

    def params_file(self):
        return self.params_path

    def _prefix(self):
        """Common path prefix of the .pdmodel/.pdiparams pair."""
        import os
        p = self.model_path
        if p is None:
            raise ValueError("Config has no model path")
        if os.path.isdir(p):
            p = os.path.join(p, "inference")
        if p.endswith(".pdmodel"):
            p = p[: -len(".pdmodel")]
        return p

    # --- device / engine knobs ----------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = "tpu"  # accelerator path == the TPU backend

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device == "tpu"

    def switch_ir_optim(self, flag=True):
        pass  # XLA always optimizes; no separate IR pass pipeline

    def enable_memory_optim(self):
        self._memory_optim = True

    def switch_use_feed_fetch_ops(self, flag):
        pass

    def disable_glog_info(self):
        pass


class _IOHandle:
    """Input/output tensor handle (ref: ZeroCopyTensor): copy_from_cpu /
    copy_to_cpu move host arrays in and out of the predictor slot."""

    def __init__(self, name):
        self.name = name
        self._array = None

    def copy_from_cpu(self, arr):
        self._array = np.ascontiguousarray(arr)

    def reshape(self, shape):
        if self._array is not None:
            self._array = self._array.reshape(shape)

    def copy_to_cpu(self):
        return np.asarray(self._array)

    def shape(self):
        return list(self._array.shape) if self._array is not None else None


class Predictor:
    """Compiled inference callable. Two construction paths:
    - from a live Layer / pure fn (dev convenience), or
    - from Config via `create_predictor` (deployment: deserialized
      StableHLO + params, no model class)."""

    def __init__(self, model, example_inputs=None):
        from ..nn.layer.layers import Layer
        self._translated = None
        self._layer = model if isinstance(model, Layer) else None
        self._in_handles = {}
        self._out_arrays = []
        if self._layer is not None:
            from ..jit import TranslatedLayer
            if isinstance(model, TranslatedLayer):
                self._translated = model
                self._fn = None
            else:
                self._layer.eval()
                params, bufs = self._layer.functional_state()
                self._params, self._bufs = params, bufs
                layer = self._layer

                def fwd(params, bufs, *xs):
                    saved = layer.functional_state()
                    layer.load_functional_state(params, bufs)
                    try:
                        out = layer(*[Tensor(x) for x in xs])
                    finally:
                        layer.load_functional_state(*saved)
                    return jax.tree_util.tree_map(
                        lambda t: t._value if isinstance(t, Tensor) else t,
                        out, is_leaf=lambda t: isinstance(t, Tensor))
                self._fn = jax.jit(fwd)
        else:
            self._fn = jax.jit(model)
            self._params, self._bufs = {}, {}

    # --- direct call API ----------------------------------------------
    @staticmethod
    def _handle_order(name):
        # input_10 must come after input_2: sort by numeric suffix
        stem, _, idx = name.rpartition("_")
        return (stem, int(idx)) if idx.isdigit() else (name, -1)

    def run(self, inputs=None):
        if inputs is None:  # handle-based flow (reference predictor.run())
            xs = [self._in_handles[n]._array
                  for n in sorted(self._in_handles, key=self._handle_order)]
            out = self._run_raw(xs)
            flat = jax.tree_util.tree_leaves(out)
            self._out_arrays = [np.asarray(
                x._value if isinstance(x, Tensor) else x) for x in flat]
            return True
        xs = [i._value if isinstance(i, Tensor) else np.asarray(i)
              for i in (inputs if isinstance(inputs, (list, tuple))
                        else [inputs])]
        out = self._run_raw(xs)
        return jax.tree_util.tree_map(
            lambda x: x if isinstance(x, Tensor) else Tensor(x), out,
            is_leaf=lambda x: not isinstance(x, (list, tuple, dict)))

    def _run_raw(self, xs):
        if self._translated is not None:
            return self._translated(*xs)
        if self._layer is not None:
            return self._fn(self._params, self._bufs, *xs)
        return self._fn(*xs)

    __call__ = run

    # --- handle API (ref: paddle.inference zero-copy flow) -------------
    def get_input_names(self):
        if self._translated is not None:
            n = len(self._translated._meta.get("in_specs", []))
            return [f"input_{i}" for i in range(n)]
        return sorted(self._in_handles) or ["input_0"]

    def get_input_handle(self, name):
        return self._in_handles.setdefault(name, _IOHandle(name))

    def get_output_names(self):
        return [f"output_{i}" for i in range(max(1, len(self._out_arrays)))]

    def get_output_handle(self, name):
        idx = int(name.rsplit("_", 1)[-1]) if "_" in name else 0
        h = _IOHandle(name)
        if idx < len(self._out_arrays):
            h._array = self._out_arrays[idx]
        return h


def create_predictor(config_or_model, example_inputs=None):
    """paddle.inference.create_predictor — from a Config, rebuild the
    predictor out of the serialized artifacts alone (ref:
    analysis_predictor.cc CreatePaddlePredictor)."""
    if isinstance(config_or_model, Config):
        from ..jit import load as jit_load
        translated = jit_load(config_or_model._prefix())
        return Predictor(translated)
    return Predictor(config_or_model, example_inputs)


class DataType:
    """Tensor element types of the predictor IO surface (ref:
    fluid/inference DataType from paddle_infer_declare)."""
    FLOAT32 = "float32"
    FLOAT16 = "float16"
    INT64 = "int64"
    INT32 = "int32"
    UINT8 = "uint8"
    INT8 = "int8"


class PlaceType:
    """Where predictor tensors live. kTPU covers the accelerator; the
    CUDA names are accepted for source compat and map to it."""
    kHOST = kCPU = "cpu"
    kGPU = kTPU = kXPU = "tpu"


class PrecisionType:
    """Serving precision request (ref: AnalysisConfig::Precision).
    Float32 runs as-is; Half maps to bfloat16 (the TPU half type);
    Int8 expects a slim-converted model (see paddle.slim
    save_quantized_model)."""
    Float32 = 0
    Half = 1
    Int8 = 2


def get_num_bytes_of_data_type(dtype):
    import jax.numpy as jnp
    return np.dtype(jnp.dtype(str(dtype))).itemsize


def get_version():
    from ..version import full_version
    return f"paddle_tpu {full_version} (StableHLO artifact serving)"


class PredictorPool:
    """N predictors over ONE artifact (ref: fluid/inference
    PredictorPool): the artifact is deserialized and its StableHLO
    translated once, shared by every slot (XLA computations are
    stateless); only the per-slot IO handles are private, so each pool
    slot can serve a different thread without re-compiling or holding N
    weight copies."""

    def __init__(self, config, size=1):
        if size < 1:
            raise ValueError("pool size must be >= 1")
        if isinstance(config, Config):
            from ..jit import load as jit_load
            shared = jit_load(config._prefix())  # one load+translate
            self._preds = [Predictor(shared) for _ in range(size)]
        else:
            self._preds = [create_predictor(config) for _ in range(size)]

    def retrive(self, idx):  # reference spelling
        return self._preds[idx]

    retrieve = retrive

    def __len__(self):
        return len(self._preds)


from .kv_cache import BlockPoolExhausted, PagedKVCache  # noqa: E402
from .kv_quant import QuantizedKV  # noqa: E402
from .serving import (GenerationServer, PagedGenerationServer,  # noqa: E402
                      measure_offered_load, measure_poisson_load)
