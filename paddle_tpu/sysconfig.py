"""paddle.sysconfig (ref: python/paddle/sysconfig.py) — locations of the
native pieces a C++ extension would compile against. Here that is the
csrc/ directory (headers == sources for the ctypes-bound runtime) and the
directory holding the built .so."""
from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]

_CSRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "csrc")


def get_include():
    return _CSRC


def get_lib():
    return _CSRC
