"""paddle.autograd namespace (ref: python/paddle/autograd/ — PyLayer,
backward, no_grad)."""
from __future__ import annotations

from .core.autograd import Node, backward, grad, no_grad  # noqa: F401
from .core.tensor import Tensor


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.attrs = {}

    def save_for_backward(self, *tensors):
        self._saved = tensors

    def saved_tensor(self):
        return self._saved

    saved_tensors = property(lambda self: self._saved)


class PyLayerMeta(type):
    def __call__(cls, *args, **kwargs):
        raise RuntimeError("call PyLayer subclasses via .apply()")


class PyLayer(metaclass=PyLayerMeta):
    """Custom op with user-defined backward (ref: paddle.autograd.PyLayer).

    class Exp(PyLayer):
        @staticmethod
        def forward(ctx, x):
            y = paddle.exp(x)
            ctx.save_for_backward(y)
            return y
        @staticmethod
        def backward(ctx, dy):
            (y,) = ctx.saved_tensor()
            return dy * y
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grad_outputs):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from .core.autograd import grad_enabled, no_grad
        ctx = PyLayerContext()
        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(outs, (tuple, list))
        out_list = list(outs) if multi else [outs]
        out_list = [o if isinstance(o, Tensor) else Tensor(o) for o in out_list]

        diff_inputs = [a for a in args
                       if isinstance(a, Tensor) and not a.stop_gradient]
        diff_ids = {id(a) for a in diff_inputs}
        if grad_enabled() and diff_inputs:
            tensor_args = [a for a in args if isinstance(a, Tensor)]

            def vjp_fn(cts):
                cts_t = cts if isinstance(cts, tuple) else (cts,)
                with no_grad():
                    gin = cls.backward(ctx, *[Tensor(c) for c in cts_t])
                gin = gin if isinstance(gin, (tuple, list)) else (gin,)
                raw = [g._value if isinstance(g, Tensor) else g for g in gin]
                # backward returns one grad per tensor input, in order
                raw = list(raw) + [None] * (len(tensor_args) - len(raw))
                return [g for a, g in zip(tensor_args, raw)
                        if id(a) in diff_ids]

            node = Node(vjp_fn, diff_inputs, out_list, cls.__name__, multi)
            for o in out_list:
                o._node = node
                o.stop_gradient = False
        return tuple(out_list) if multi else out_list[0]


# paddle 2.x location alias
class LegacyPyLayer(PyLayer, metaclass=PyLayerMeta):
    pass
