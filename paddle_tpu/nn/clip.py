"""Gradient clipping (ref: python/paddle/fluid/clip.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        """Operate on list of (param, grad Tensor) pairs (static-graph style)."""
        params = [p for p, _ in params_grads]
        grads = [g._value if isinstance(g, Tensor) else g for _, g in params_grads]
        clipped = self._clip_raw(params, grads)
        return [(p, Tensor(g)) for p, g in zip(params, clipped)]

    def _clip_raw(self, params, grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):  # noqa: A002
        self.max = max
        self.min = -max if min is None else min

    def _clip_raw(self, params, grads):
        return [jnp.clip(g, self.min, self.max) if _clippable(p) else g
                for p, g in zip(params, grads)]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def _clip_raw(self, params, grads):
        out = []
        for p, g in zip(params, grads):
            if not _clippable(p):
                out.append(g)
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g)))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append(g * scale)
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = clip_norm

    def _clip_raw(self, params, grads):
        sq = [jnp.sum(jnp.square(g)) for p, g in zip(params, grads)
              if _clippable(p)]
        if not sq:
            return grads
        global_norm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        return [g * scale if _clippable(p) else g
                for p, g in zip(params, grads)]

    def clip_tree(self, grads_tree):
        """Pure pytree version for jitted steps."""
        import jax
        leaves = jax.tree_util.tree_leaves(grads_tree)
        global_norm = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        return jax.tree_util.tree_map(lambda g: g * scale, grads_tree)


def _clippable(p):
    return getattr(p, "need_clip", True)


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    """paddle.nn.utils.clip_grad_norm_ equivalent (eager, in-place on .grad)."""
    params = [p for p in parameters if p.grad is not None]
    if not params:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(p.grad._value)) for p in params]))
    else:
        total = jnp.power(sum(jnp.sum(jnp.power(jnp.abs(p.grad._value), norm_type))
                              for p in params), 1.0 / norm_type)
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in params:
        p.grad = Tensor(p.grad._value * scale)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    for p in parameters:
        if p.grad is not None:
            p.grad = Tensor(jnp.clip(p.grad._value, -clip_value, clip_value))


# fluid-era aliases
GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm
