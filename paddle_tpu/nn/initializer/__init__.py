"""Weight initializers.

Reference: python/paddle/fluid/initializer.py + python/paddle/nn/initializer/.
Each initializer is a callable `(shape, dtype) -> jax.Array` drawing from the
global generator; in static mode the same callable is recorded into the
startup program and run by the Executor.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core import dtype as dtype_mod
from ...core import rng


class Initializer:
    def __call__(self, shape, dtype=None):
        raise NotImplementedError

    def _dt(self, dtype):
        return dtype_mod.convert_dtype(dtype)


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=None):
        return jnp.full(tuple(shape), self.value, self._dt(dtype))


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=None):
        return jax.random.uniform(rng.next_key(), tuple(shape), self._dt(dtype),
                                  self.low, self.high)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None):
        return self.mean + self.std * jax.random.normal(
            rng.next_key(), tuple(shape), self._dt(dtype))


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None):
        out = jax.random.truncated_normal(rng.next_key(), -2.0, 2.0,
                                          tuple(shape), self._dt(dtype))
        return self.mean + self.std * out


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels [out, in, *k] (paddle conv weight layout)
    rf = int(np.prod(shape[2:]))
    return shape[1] * rf, shape[0] * rf


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(rng.next_key(), tuple(shape), self._dt(dtype),
                                  -limit, limit)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(rng.next_key(), tuple(shape), self._dt(dtype))


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype=None):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(rng.next_key(), tuple(shape), self._dt(dtype),
                                  -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype=None):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        std = gain / math.sqrt(fi)
        return std * jax.random.normal(rng.next_key(), tuple(shape), self._dt(dtype))


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=None):
        from ...core.tensor import Tensor
        v = self.value
        if isinstance(v, Tensor):
            v = v._value
        arr = jnp.asarray(np.asarray(v), dtype=self._dt(dtype))
        return jnp.reshape(arr, tuple(shape)) if tuple(arr.shape) != tuple(shape) else arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=None):
        shape = tuple(shape)
        rows = shape[0]
        cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        flat = jax.random.normal(rng.next_key(), (max(rows, cols), min(rows, cols)),
                                 self._dt(dtype))
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        q = q.T if rows < cols else q
        return self.gain * jnp.reshape(q[:rows, :cols], shape)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype=None):
        out = np.zeros(tuple(shape), dtype=np.dtype(jnp.dtype(self._dt(dtype))))
        oc, ic = shape[0], shape[1]
        mid = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(min(oc // self.groups, ic)):
                out[(g * (oc // self.groups) + i, i) + tuple(mid)] = 1.0
        return jnp.asarray(out)


# fluid-era aliases
ConstantInitializer = Constant
UniformInitializer = Uniform
NormalInitializer = Normal
TruncatedNormalInitializer = TruncatedNormal
XavierInitializer = XavierUniform
MSRAInitializer = KaimingNormal
NumpyArrayInitializer = Assign


def calculate_gain(nonlinearity, param=None):
    gains = {"sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
             "conv3d": 1.0, "tanh": 5.0 / 3, "relu": math.sqrt(2.0),
             "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
             "selu": 3.0 / 4}
    return gains[nonlinearity]


class Bilinear(Initializer):
    """Bilinear upsampling kernel init for conv_transpose (ref:
    python/paddle/fluid/initializer.py BilinearInitializer)."""

    def __call__(self, shape, dtype="float32"):
        if len(shape) != 4:
            raise ValueError("Bilinear initializer expects a 4-D weight")
        weight = np.zeros(shape, dtype="float32")
        c_out, c_in, h, w = shape
        f = np.ceil(w / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape[2:]))):
            x = i % w
            y = (i // w) % h
            val = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
            weight[:, :, y, x] = val
        return jnp.asarray(weight, self._dt(dtype))


_global_initializer = {"weight": None, "bias": None}


def set_global_initializer(weight_init, bias_init=None):
    """Default initializer for subsequently-created params (ref:
    fluid/initializer.py set_global_initializer)."""
    _global_initializer["weight"] = weight_init
    _global_initializer["bias"] = bias_init


import sys as _sys  # noqa: E402

_self = _sys.modules[__name__]
assign = _self
constant = _self
kaiming = _self
normal = _self
uniform = _self
xavier = _self
