"""paddle.nn.functional.transformer module path (ref:
nn/functional/transformer.py)."""
from ...ops import scaled_dot_product_attention  # noqa: F401
from ...ops.attention import fused_feedforward, fused_multi_head_attention  # noqa: F401,E501

__all__ = ["fused_multi_head_attention", "fused_feedforward",
           "scaled_dot_product_attention"]
